"""SQL abstract syntax tree.

A deliberately small, typed AST — the stand-in for PostgreSQL's parse
tree that the reference receives from the postgres parser.  Desugaring
(BETWEEN, IN, NOT LIKE, avg->sum/count) happens in later phases, not here.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional

# ---------------------------------------------------------------- exprs


class Expr:
    pass


@dataclass(frozen=True)
class ColumnRef(Expr):
    name: str
    table: Optional[str] = None  # qualified a.b

    def __str__(self):
        return f"{self.table}.{self.name}" if self.table else self.name


@dataclass(frozen=True)
class Literal(Expr):
    value: Any          # python int/float/Decimal/str/bool/None
    type_name: str = "" # inferred literal category: int/decimal/float/string/bool/null

    def __str__(self):
        if self.type_name == "string":
            return "'" + str(self.value).replace("'", "''") + "'"
        return str(self.value)


@dataclass(frozen=True)
class Star(Expr):
    def __str__(self):
        return "*"


@dataclass(frozen=True)
class BinOp(Expr):
    op: str  # + - * / % = <> < <= > >= and or
    left: Expr
    right: Expr

    def __str__(self):
        return f"({self.left} {self.op} {self.right})"


@dataclass(frozen=True)
class UnOp(Expr):
    op: str  # not, -
    operand: Expr

    def __str__(self):
        return f"({self.op} {self.operand})"


@dataclass(frozen=True)
class FuncCall(Expr):
    name: str
    args: tuple[Expr, ...] = ()
    distinct: bool = False
    # ordered-set / ordered aggregate: string_agg(x, ',' ORDER BY y DESC)
    agg_order: tuple = ()  # tuple[(Expr, asc: bool)]
    # agg(...) FILTER (WHERE cond) — desugared at bind time by wrapping
    # the value argument in CASE WHEN cond THEN arg END
    filter: Optional[Expr] = None

    def __str__(self):
        inner = ", ".join(str(a) for a in self.args)
        if self.distinct:
            inner = "distinct " + inner
        return f"{self.name}({inner})"


@dataclass(frozen=True)
class IntervalLiteral(Expr):
    """INTERVAL 'n' unit / INTERVAL '1 year 2 days' — PostgreSQL's
    months/days/microseconds decomposition."""
    months: int = 0
    days: int = 0
    micros: int = 0


@dataclass(frozen=True)
class Between(Expr):
    expr: Expr
    lo: Expr
    hi: Expr
    negated: bool = False


@dataclass(frozen=True)
class InList(Expr):
    expr: Expr
    items: tuple[Expr, ...]
    negated: bool = False


@dataclass(frozen=True)
class IsNull(Expr):
    expr: Expr
    negated: bool = False


@dataclass(frozen=True)
class Cast(Expr):
    expr: Expr
    type_name: str
    type_args: tuple[int, ...] = ()


@dataclass(frozen=True)
class CaseExpr(Expr):
    whens: tuple[tuple[Expr, Expr], ...]
    else_: Optional[Expr] = None


@dataclass(frozen=True)
class WindowCall(Expr):
    """fn(...) OVER (PARTITION BY ... ORDER BY ... [frame]) or
    fn(...) OVER name / OVER (name ...) referencing a WINDOW clause."""
    func: "FuncCall" = None
    partition_by: tuple = ()
    order_by: tuple = ()  # tuple[OrderItem-like (expr, asc)]
    # frame: (mode, (dir, n|None), (dir, n|None)) with mode rows|range,
    # dir in preceding|current|following, None = unbounded;
    # frame None = default (RANGE UNBOUNDED PRECEDING .. CURRENT ROW)
    frame: Optional[tuple] = None
    # named-window reference: OVER w (verbatim=True, uses w including
    # its frame) or OVER (w ...) (copy rules: partition from w, own
    # order only if w has none, own frame)
    ref_name: Optional[str] = None
    ref_verbatim: bool = False

    def __hash__(self):
        return id(self)

    def __eq__(self, other):
        return self is other


@dataclass(frozen=True)
class Param(Expr):
    """$N placeholder bound at execute time (prepared-statement analog)."""
    index: int  # 1-based


@dataclass(frozen=True)
class Exists(Expr):
    """EXISTS (SELECT ...) — executed ahead of the outer query via
    recursive planning (reference: recursive_planning.c handles EXISTS
    sublinks as subplans); LIMIT 1 semantics."""
    select: object  # A.Select | A.SetOp
    negated: bool = False

    def __hash__(self):
        return id(self.select)

    def __eq__(self, other):
        return self is other


@dataclass(frozen=True)
class Subquery(Expr):
    """Scalar subquery or IN-subquery source; executed ahead of the outer
    query as an intermediate result (reference: recursive planning,
    planner/recursive_planning.c + read_intermediate_result)."""
    select: object  # A.Select (unhashable field kept opaque)

    def __hash__(self):
        return id(self.select)

    def __eq__(self, other):
        return self is other


# ------------------------------------------------------------ statements


class Statement:
    pass


@dataclass
class ColumnDef:
    name: str
    type_name: str
    type_args: list[int] = field(default_factory=list)
    not_null: bool = False
    primary_key: bool = False   # implies not_null + unique index
    unique: bool = False        # column-level UNIQUE constraint
    default_sql: str = ""       # DEFAULT expression (SQL text)


@dataclass
class CreateSchema(Statement):
    name: str
    if_not_exists: bool = False


@dataclass
class DropSchema(Statement):
    name: str
    cascade: bool = False


@dataclass
class CreateTable(Statement):
    name: str
    columns: list[ColumnDef]
    if_not_exists: bool = False
    options: dict = field(default_factory=dict)  # USING/WITH columnar opts
    # foreign keys (column-level REFERENCES + table-level FOREIGN KEY):
    # [{"columns", "ref_table", "ref_columns", "on_delete"}]
    foreign_keys: list = field(default_factory=list)
    # PARTITION BY RANGE (col) -> the partition column name
    partition_by: "str | None" = None
    # CREATE TABLE x PARTITION OF parent FOR VALUES FROM (a) TO (b):
    # {"parent", "lo", "hi"} with raw literal values (None = MINVALUE/
    # MAXVALUE); physical conversion happens at DDL execution
    partition_of: "dict | None" = None
    # CHECK constraints (column- or table-level), SQL text each
    checks: list = field(default_factory=list)


@dataclass
class DropTable(Statement):
    name: str
    if_exists: bool = False


@dataclass
class CreateTableAs(Statement):
    """CREATE TABLE name AS SELECT ... — schema inferred from the
    result (planner types where known, value inference otherwise)."""
    name: str
    select: object = None   # Select | SetOp | WithSelect
    if_not_exists: bool = False


@dataclass
class CreateExtension(Statement):
    """Reference: commands/extension.c propagation."""
    name: str
    if_not_exists: bool = False
    version: "str | None" = None


@dataclass
class DropExtension(Statement):
    name: str
    if_exists: bool = False


@dataclass
class CreateDomain(Statement):
    """CREATE DOMAIN name AS type [NOT NULL] [CHECK (expr)].
    Reference: commands/domain.c propagation; VALUE refers to the
    checked value inside the CHECK expression."""
    name: str
    base: str
    type_args: list = field(default_factory=list)
    not_null: bool = False
    check_sql: "str | None" = None


@dataclass
class DropDomain(Statement):
    name: str
    if_exists: bool = False


@dataclass
class CreateCollation(Statement):
    """Reference: commands/collation.c propagation (metadata object)."""
    name: str
    options: dict = field(default_factory=dict)


@dataclass
class DropCollation(Statement):
    name: str
    if_exists: bool = False


@dataclass
class CreatePublication(Statement):
    """CREATE PUBLICATION name FOR TABLE t1, t2 | FOR ALL TABLES.
    Reference: commands/publication.c; gates the CDC stream."""
    name: str
    tables: "list | str" = "all"   # list of names, or "all"


@dataclass
class DropPublication(Statement):
    name: str
    if_exists: bool = False


@dataclass
class CreateStatistics(Statement):
    """CREATE STATISTICS name ON c1, c2 FROM t.
    Reference: commands/statistics.c propagation."""
    name: str
    columns: list = field(default_factory=list)
    table: str = ""


@dataclass
class DropStatistics(Statement):
    name: str
    if_exists: bool = False


@dataclass
class Prepare(Statement):
    """PREPARE name [(types)] AS statement — the stored unit is the
    statement's SQL text, so EXECUTE rides the text-keyed generic-plan
    cache (reference: prepared statements + Job->deferredPruning)."""
    name: str
    sql: str = ""


@dataclass
class ExecutePrepared(Statement):
    name: str
    args: list = field(default_factory=list)   # literal Exprs


@dataclass
class Deallocate(Statement):
    name: "str | None" = None   # None = ALL


@dataclass
class SetConfig(Statement):
    """SET [citus.]name = value | TO value — runtime settings (the GUC
    surface; reference: ~139 citus.* GUCs, shared_library_init.c)."""
    name: str
    value: object = None


@dataclass
class ShowConfig(Statement):
    """SHOW [citus.]name | SHOW ALL."""
    name: str = "all"


@dataclass
class Analyze(Statement):
    """ANALYZE [table]: refresh derived statistics (extended-statistics
    ndistinct; column bounds are always skip-list-live here).
    Reference: commands/vacuum.c ANALYZE propagation."""
    table: "str | None" = None


@dataclass
class VacuumAnalyze(Statement):
    table: str = ""
    full: bool = False


@dataclass
class Reindex(Statement):
    """REINDEX INDEX name | REINDEX TABLE name: rebuild segment files
    (reference: reindex propagated through commands/index.c)."""
    kind: str = "index"   # index | table
    name: str = ""


@dataclass
class CreateIndex(Statement):
    """CREATE [UNIQUE] INDEX name ON table (column).
    Reference: commands/index.c (DDL propagation) +
    columnar_tableam.c:1444 (index build over columnar)."""
    name: str
    table: str
    column: str
    unique: bool = False
    if_not_exists: bool = False


@dataclass
class DropIndex(Statement):
    name: str
    if_exists: bool = False


@dataclass(frozen=True)
class OnConflict:
    """INSERT ... ON CONFLICT (cols) DO NOTHING | DO UPDATE SET ...
    [WHERE ...].  Assignments/where may reference ``excluded.col``."""
    targets: tuple = ()        # conflict target column names
    action: str = "nothing"    # nothing | update
    assignments: tuple = ()    # tuple[(col, Expr)]
    where: Optional[Expr] = None


@dataclass
class Insert(Statement):
    table: str
    columns: Optional[list[str]]
    rows: list[list[Expr]]
    select: Optional["Select"] = None  # INSERT ... SELECT
    returning: Optional[list] = None   # [SelectItem] | None
    on_conflict: Optional[OnConflict] = None


@dataclass
class OrderItem:
    expr: Expr
    ascending: bool = True
    nulls_first: Optional[bool] = None


@dataclass
class TableRef:
    name: str
    alias: Optional[str] = None


@dataclass
class SubqueryRef:
    """Derived table: FROM (SELECT ...) alias — materialized as an
    intermediate result before the outer query runs (reference:
    recursive planning of subqueries in FROM,
    recursive_planning.c RecursivelyPlanSubqueryWalker)."""
    select: object  # Select | SetOp
    alias: str


@dataclass
class FunctionRef:
    """Set-returning function in FROM: generate_series(a, b [, step]).
    Materialized like a derived table (reference: SRFs run through the
    standard executor; here the recursive-planning temp-table seam)."""
    name: str
    args: tuple = ()
    alias: Optional[str] = None


@dataclass
class Join:
    left: "FromItem"
    right: "FromItem"
    kind: str            # inner, left, right, full, cross
    condition: Optional[Expr] = None


FromItem = "TableRef | Join"


@dataclass(frozen=True)
class GroupingSetsSpec(Expr):
    """GROUP BY ROLLUP(...)/CUBE(...)/GROUPING SETS(...) — expands to a
    union of per-set grouped executions with NULL padding (reference:
    PostgreSQL executes these natively; recursive composition here)."""
    sets: tuple = ()  # tuple[tuple[Expr, ...]]


@dataclass
class SelectItem:
    expr: Expr
    alias: Optional[str] = None


@dataclass
class WithSelect(Statement):
    """WITH [RECURSIVE] name AS (SELECT ...) [, ...] SELECT ... — each
    CTE materializes as an intermediate result (reference: cte_inline.c
    / recursive_planning.c materialization path; recursive CTEs iterate
    coordinator-side like recursive_planning.c:1175's supported case)."""
    ctes: list = field(default_factory=list)  # [(name, Select)]
    body: "Select" = None
    recursive: bool = False
    # name -> explicit column alias list (WITH r(n) AS ...)
    cte_cols: dict = field(default_factory=dict)


@dataclass
class Select(Statement):
    items: list[SelectItem]
    from_: Optional[object] = None   # TableRef | Join | None
    where: Optional[Expr] = None
    group_by: list[Expr] = field(default_factory=list)
    having: Optional[Expr] = None
    order_by: list[OrderItem] = field(default_factory=list)
    limit: Optional[int] = None
    offset: Optional[int] = None
    distinct: bool = False
    # WINDOW name AS (spec) declarations: tuple[(name, WindowCall-spec)]
    # (the spec is a WindowCall with func=None)
    windows: tuple = ()
    # SELECT DISTINCT ON (expr, ...): keep the first row per key in
    # ORDER BY order (PostgreSQL extension)
    distinct_on: tuple = ()


@dataclass
class CreateView(Statement):
    """CREATE VIEW name AS SELECT ... — stored as SQL text in the
    catalog (reference: views propagate as distributed objects,
    commands/view.c); references expand like derived tables."""
    name: str = ""
    select: object = None       # parsed body (validation only)
    sql: str = ""               # body text, reparsed at each use
    or_replace: bool = False


@dataclass
class DropView(Statement):
    name: str = ""
    if_exists: bool = False


@dataclass
class CreateSequence(Statement):
    """Reference: commands/sequence.c — distributed sequences hand out
    disjoint ranges; here a catalog-backed counter with block caching."""
    name: str = ""
    start: int = 1
    increment: int = 1
    if_not_exists: bool = False


@dataclass
class DropSequence(Statement):
    name: str = ""
    if_exists: bool = False


@dataclass
class CreateFunction(Statement):
    """CREATE FUNCTION name(a type, ...) RETURNS type AS 'expr'
    LANGUAGE SQL — an expression macro inlined at planning time, the
    analog of distributed functions executing next to the data
    (commands/function.c + function_call_delegation.c)."""
    name: str = ""
    arg_names: list = field(default_factory=list)
    arg_types: list = field(default_factory=list)   # sql type names
    returns: str = ""
    body: str = ""                                  # expression SQL text
    or_replace: bool = False


@dataclass
class DropFunction(Statement):
    name: str = ""
    if_exists: bool = False


@dataclass
class CreatePolicy(Statement):
    """CREATE POLICY name ON table [FOR cmd] [TO roles] [USING (expr)]
    [WITH CHECK (expr)] — row-level security (reference:
    commands/policy.c propagation; enforcement here is engine-native)."""
    name: str = ""
    table: str = ""
    cmd: str = "all"            # all | select | insert | update | delete
    roles: tuple = ("public",)
    using_sql: Optional[str] = None
    check_sql: Optional[str] = None


@dataclass
class DropPolicy(Statement):
    name: str = ""
    table: str = ""
    if_exists: bool = False


@dataclass
class AlterTableRls(Statement):
    """ALTER TABLE t ENABLE|DISABLE ROW LEVEL SECURITY."""
    table: str = ""
    enable: bool = True


@dataclass
class CreateTrigger(Statement):
    """CREATE TRIGGER name AFTER event ON table [FOR EACH STATEMENT]
    EXECUTE FUNCTION f() — statement-level AFTER triggers running a
    stored SQL-statement function (reference: commands/trigger.c
    propagates triggers; row-level procedural bodies are PL/pgSQL and
    out of scope)."""
    name: str = ""
    event: str = "insert"       # insert | update | delete
    table: str = ""
    function: str = ""


@dataclass
class DropTrigger(Statement):
    name: str = ""
    table: str = ""
    if_exists: bool = False


@dataclass
class CreateTsConfig(Statement):
    """CREATE TEXT SEARCH CONFIGURATION name (PARSER = p | COPY = c) —
    propagated catalog objects (reference: commands/text_search.c; FTS
    execution itself is the host database's concern in the reference,
    so these are metadata-only here too)."""
    name: str = ""
    options: dict = field(default_factory=dict)


@dataclass
class DropTsConfig(Statement):
    name: str = ""
    if_exists: bool = False


@dataclass
class CreateType(Statement):
    """CREATE TYPE name AS ENUM (...) — enum columns store the label's
    declaration index; labels validate at ingest (reference: types
    propagate as distributed objects, commands/type.c)."""
    name: str = ""
    labels: list = field(default_factory=list)


@dataclass
class DropType(Statement):
    name: str = ""
    if_exists: bool = False


@dataclass
class CreateRole(Statement):
    """Reference: roles propagate as distributed objects
    (commands/role.c); here a catalog-registered principal."""
    name: str = ""
    if_not_exists: bool = False


@dataclass
class DropRole(Statement):
    name: str = ""
    if_exists: bool = False


@dataclass
class Grant(Statement):
    """GRANT/REVOKE privileges ON table TO/FROM role (commands/grant.c)."""
    privileges: list = field(default_factory=list)  # select/insert/update/delete or ["all"]
    table: str = ""
    role: str = ""
    revoke: bool = False


@dataclass
class SetOp(Statement):
    """UNION / INTERSECT / EXCEPT [ALL] over two selects (or nested set
    operations).  Trailing ORDER BY / LIMIT / OFFSET bind to the whole
    operation, as in PostgreSQL.  Reference: set operations route through
    recursive planning when they cannot be pushed down
    (recursive_planning.c:223)."""
    op: str = "union"          # union | intersect | except
    all: bool = False
    left: object = None        # Select | SetOp
    right: object = None       # Select | SetOp
    order_by: list = field(default_factory=list)   # [OrderItem]
    limit: Optional[int] = None
    offset: Optional[int] = None


@dataclass
class AlterTable(Statement):
    table: str
    action: str   # add_column | drop_column | rename_column | rename_table
                  # | add_check
    column: Optional[ColumnDef] = None
    old_name: Optional[str] = None
    new_name: Optional[str] = None
    check_sql: Optional[str] = None  # ADD [CONSTRAINT n] CHECK (expr)


@dataclass
class CopyFrom(Statement):
    table: str
    path: str
    options: dict = field(default_factory=dict)


@dataclass
class CopyTo(Statement):
    table: str
    path: str
    options: dict = field(default_factory=dict)


@dataclass
class CopyQueryTo(Statement):
    """COPY (SELECT ...) TO 'path' — query-result export."""
    select: object = None
    path: str = ""
    options: dict = field(default_factory=dict)


@dataclass
class Delete(Statement):
    table: str
    where: Optional[Expr] = None
    returning: Optional[list] = None   # [SelectItem] | None


@dataclass
class Update(Statement):
    table: str
    assignments: list[tuple[str, Expr]] = field(default_factory=list)
    where: Optional[Expr] = None
    returning: Optional[list] = None   # [SelectItem] | None


@dataclass
class Truncate(Statement):
    table: str
    # TRUNCATE a, b, c — additional tables beyond the first
    more: tuple = ()


@dataclass
class TransactionStmt(Statement):
    """BEGIN / COMMIT / ROLLBACK / SAVEPOINT family (reference:
    transaction/transaction_management.c wraps exactly these)."""

    kind: str  # begin | commit | rollback | savepoint | rollback_to | release
    name: Optional[str] = None  # savepoint name


@dataclass
class Vacuum(Statement):
    table: str
    full: bool = False


@dataclass
class MergeWhen:
    matched: bool
    action: str                       # update | delete | insert | nothing
    condition: Optional[Expr] = None  # AND <cond> on the WHEN clause
    assignments: list = field(default_factory=list)   # update
    insert_columns: Optional[list] = None             # insert
    insert_values: list = field(default_factory=list) # insert


@dataclass
class Merge(Statement):
    target: "TableRef" = None
    source: "TableRef" = None
    on: Expr = None
    whens: list = field(default_factory=list)


@dataclass
class UtilityCall(Statement):
    """SELECT create_distributed_table('t', 'col') style UDF utilities —
    the reference exposes its control plane as SQL-callable UDFs
    (src/backend/distributed/sql/udfs/)."""

    name: str
    args: list[Any]


@dataclass
class Explain(Statement):
    statement: Statement
    analyze: bool = False
