"""SQL tokenizer + recursive-descent parser.

Covers the analytical subset the engine executes: CREATE/DROP TABLE,
INSERT ... VALUES / INSERT ... SELECT, SELECT with joins, WHERE, GROUP BY,
HAVING, ORDER BY, LIMIT/OFFSET, EXPLAIN [ANALYZE], and the UDF-style
utility calls the reference exposes (create_distributed_table, ...).
The reference delegates parsing to PostgreSQL; we own it, so the grammar
is intentionally a strict, unambiguous subset.
"""

from __future__ import annotations

import dataclasses
import decimal
import re
from dataclasses import dataclass
from typing import Optional

from citus_tpu.errors import SqlSyntaxError, UnsupportedFeatureError
from citus_tpu.planner import ast_nodes as A

_TOKEN_RE = re.compile(
    r"""
    (?P<ws>\s+|--[^\n]*)
  | (?P<num>\d+\.\d*(?:[eE][+-]?\d+)?|\.\d+(?:[eE][+-]?\d+)?|\d+[eE][+-]?\d+|\d+)
  | (?P<str>'(?:[^']|'')*')
  | (?P<ident>[A-Za-z_][A-Za-z_0-9$]*)
  | (?P<param>\$\d+)
  | (?P<op><=|>=|<>|!=|::|=|<|>|\+|-|\*|/|%|\(|\)|\[|\]|,|;|\.)
    """,
    re.VERBOSE,
)

KEYWORDS = {
    "select", "from", "where", "group", "by", "having", "order", "limit",
    "offset", "as", "and", "or", "not", "in", "between", "is", "null",
    "true", "false", "create", "drop", "table", "if", "exists", "insert",
    "into", "values", "distinct", "asc", "desc", "nulls", "first", "last",
    "join", "inner", "left", "right", "full", "outer", "cross", "on",
    "case", "when", "then", "else", "end", "cast", "explain", "analyze",
    "using", "with", "like", "ilike", "delete", "update", "set", "truncate",
    "vacuum", "copy", "alter", "add", "column", "rename", "to",
    "schema", "cascade", "merge", "matched", "nothing", "do", "over",
    "partition", "union", "intersect", "except", "all", "within",
    "rows", "range", "unbounded", "preceding", "following", "current", "row",
    "grant", "revoke", "returning", "window",
}


@dataclass
class Token:
    kind: str  # num | str | ident | kw | op | eof
    value: str
    pos: int


def tokenize(text: str) -> list[Token]:
    out, pos = [], 0
    while pos < len(text):
        m = _TOKEN_RE.match(text, pos)
        if not m:
            raise SqlSyntaxError(f"unexpected character {text[pos]!r}", pos, text)
        pos = m.end()
        if m.lastgroup == "ws":
            continue
        kind, value = m.lastgroup, m.group()
        if kind == "ident":
            low = value.lower()
            if low in KEYWORDS:
                out.append(Token("kw", low, m.start()))
            else:
                out.append(Token("ident", low, m.start()))
        else:
            out.append(Token(kind, value, m.start()))
    out.append(Token("eof", "", len(text)))
    return out


class Parser:
    def __init__(self, text: str):
        self.text = text
        self.toks = tokenize(text)
        self.i = 0

    # ---- token helpers -------------------------------------------------
    def peek(self, offset: int = 0) -> Token:
        return self.toks[min(self.i + offset, len(self.toks) - 1)]

    def next(self) -> Token:
        t = self.toks[self.i]
        if t.kind != "eof":
            self.i += 1
        return t

    def at_kw(self, *kws: str) -> bool:
        t = self.peek()
        return t.kind == "kw" and t.value in kws

    def at_op(self, *ops: str) -> bool:
        t = self.peek()
        return t.kind == "op" and t.value in ops

    def accept_kw(self, *kws: str) -> Optional[Token]:
        if self.at_kw(*kws):
            return self.next()
        return None

    def accept_op(self, *ops: str) -> Optional[Token]:
        if self.at_op(*ops):
            return self.next()
        return None

    def expect_kw(self, kw: str) -> Token:
        if not self.at_kw(kw):
            self.error(f"expected {kw.upper()}")
        return self.next()

    def expect_op(self, op: str) -> Token:
        if not self.at_op(op):
            self.error(f"expected {op!r}")
        return self.next()

    def expect_ident(self) -> str:
        t = self.peek()
        if t.kind != "ident":
            self.error("expected identifier")
        self.next()
        return t.value

    def error(self, msg: str):
        t = self.peek()
        got = t.value or "end of input"
        raise SqlSyntaxError(f"{msg}, got {got!r}", t.pos, self.text)

    # ---- statements ----------------------------------------------------
    def _try_parse_transaction_stmt(self) -> "Optional[A.Statement]":
        """BEGIN/START TRANSACTION, COMMIT/END, ROLLBACK/ABORT,
        SAVEPOINT, ROLLBACK TO [SAVEPOINT], RELEASE [SAVEPOINT]
        (PostgreSQL spellings; reference wraps these in
        transaction_management.c:319)."""
        t = self.peek()
        word = t.value.lower() if t.kind in ("ident", "kw") else None

        def _eat_work_transaction():
            n = self.peek()
            if n.kind == "ident" and n.value.lower() in ("work", "transaction"):
                self.next()

        if word in ("begin", "start"):
            self.next()
            if word == "start":
                n = self.peek()
                if not (n.kind == "ident"
                        and n.value.lower() == "transaction"):
                    self.error("expected TRANSACTION after START")
                self.next()
            else:
                _eat_work_transaction()
            return A.TransactionStmt("begin")
        if word in ("commit", "end"):
            self.next()
            _eat_work_transaction()
            return A.TransactionStmt("commit")
        if word in ("rollback", "abort"):
            self.next()
            if word == "rollback" and self.accept_kw("to"):
                n = self.peek()
                if n.kind == "ident" and n.value.lower() == "savepoint":
                    self.next()
                return A.TransactionStmt("rollback_to", self.expect_ident())
            _eat_work_transaction()
            return A.TransactionStmt("rollback")
        if word == "savepoint":
            self.next()
            return A.TransactionStmt("savepoint", self.expect_ident())
        if word == "release":
            self.next()
            n = self.peek()
            if n.kind == "ident" and n.value.lower() == "savepoint":
                self.next()
            return A.TransactionStmt("release", self.expect_ident())
        return None

    def parse_statements(self) -> list[A.Statement]:
        stmts = []
        while self.peek().kind != "eof":
            stmts.append(self.parse_statement())
            while self.accept_op(";"):
                pass
        return stmts

    def parse_statement(self) -> A.Statement:
        ts = self._try_parse_transaction_stmt()
        if ts is not None:
            return ts
        if self.at_kw("explain"):
            return self.parse_explain()
        if self.at_kw("with"):
            return self.parse_with_select()
        if self.at_kw("select"):
            return self.parse_select_or_utility()
        if self.at_kw("create"):
            return self.parse_create_table()
        if self.at_kw("drop"):
            return self.parse_drop_table()
        if self.at_kw("insert"):
            return self.parse_insert()
        if self.at_kw("delete"):
            self.next()
            self.expect_kw("from")
            name = self.parse_table_name()
            where = self.parse_expr() if self.accept_kw("where") else None
            return A.Delete(name, where, self._parse_returning())
        if self.at_kw("update"):
            self.next()
            name = self.parse_table_name()
            self.expect_kw("set")
            assignments = []
            while True:
                col = self.expect_ident()
                self.expect_op("=")
                assignments.append((col, self.parse_expr()))
                if not self.accept_op(","):
                    break
            where = self.parse_expr() if self.accept_kw("where") else None
            return A.Update(name, assignments, where,
                            self._parse_returning())
        if self.at_kw("truncate"):
            self.next()
            self.accept_kw("table")
            names = [self.parse_table_name()]
            while self.accept_op(","):
                names.append(self.parse_table_name())
            return A.Truncate(names[0], tuple(names[1:]))
        if self.at_kw("alter"):
            return self.parse_alter_table()
        if self.at_kw("merge"):
            return self.parse_merge()
        if self.at_kw("copy"):
            self.next()
            if self.at_op("("):
                # COPY (query) TO 'path' — export a query result
                self.next()
                sub: A.Statement = self.parse_with_select() \
                    if self.at_kw("with") else self.parse_select()
                self.expect_op(")")
                self.expect_kw("to")
                path, options = self._parse_copy_path_and_options()
                return A.CopyQueryTo(sub, path, options)
            name = self.parse_table_name()
            to = False
            if self.accept_kw("to"):
                to = True
            else:
                self.expect_kw("from")
            path, options = self._parse_copy_path_and_options()
            return (A.CopyTo if to else A.CopyFrom)(name, path, options)
        if self.peek().kind == "ident" and self.peek().value == "prepare":
            self.next()
            name = self.expect_ident()
            if self.accept_op("("):  # optional parameter-type list
                while True:
                    self.parse_type_name()
                    if not self.accept_op(","):
                        break
                self.expect_op(")")
            self.expect_kw("as")
            start = self.peek().pos
            body = self.parse_statement()  # validate + consume
            if isinstance(body, (A.Prepare, A.ExecutePrepared,
                                 A.Deallocate, A.TransactionStmt)):
                self.error("PREPARE body must be a plannable statement")
            sql = self.text[start:self.peek().pos].strip().rstrip(";")
            return A.Prepare(name, sql)
        if self.peek().kind == "ident" and self.peek().value == "execute" \
                and self.peek(1).kind == "ident":
            self.next()
            name = self.expect_ident()
            args = []
            if self.accept_op("("):
                while True:
                    args.append(self.parse_expr())
                    if not self.accept_op(","):
                        break
                self.expect_op(")")
            return A.ExecutePrepared(name, args)
        if self.peek().kind == "ident" and self.peek().value == "deallocate":
            self.next()
            if self.peek().kind == "ident" and self.peek().value == "prepare":
                self.next()
            if self.at_kw("all"):
                self.next()
                return A.Deallocate(None)
            return A.Deallocate(self.expect_ident())
        if self.peek().value == "set" and self.peek().kind in ("kw", "ident"):
            self.next()
            name = self.expect_ident()
            if self.accept_op("."):
                name = f"{name}.{self.expect_ident()}"
            if not (self.accept_op("=") or self.accept_kw("to")):
                self.error("expected = or TO after SET name")
            t = self.next()
            # SET citus.log_min_duration_ms = -1 (negative sentinel)
            if t.kind == "op" and t.value == "-" and self.peek().kind == "num":
                t = self.next()
                n = float(t.value) \
                    if ("." in t.value or "e" in t.value.lower()) \
                    else int(t.value)
                return A.SetConfig(name, -n)
            if t.kind == "str":
                value: object = t.value[1:-1].replace("''", "'")
            elif t.kind == "num":
                value = float(t.value) \
                    if ("." in t.value or "e" in t.value.lower()) \
                    else int(t.value)
            elif t.value in ("true", "false", "on", "off"):
                value = t.value in ("true", "on")
            else:
                value = t.value
            return A.SetConfig(name, value)
        if self.peek().kind == "ident" and self.peek().value == "show":
            self.next()
            if self.at_kw("all"):
                self.next()
                return A.ShowConfig("all")
            name = self.expect_ident()
            if self.accept_op("."):
                name = f"{name}.{self.expect_ident()}"
            return A.ShowConfig(name)
        if self.at_kw("vacuum"):
            self.next()
            # "full" lexes as a keyword (FULL OUTER JOIN)
            full = bool(self.peek().value == "full" and self.next())
            if self.accept_kw("analyze"):
                name = self.parse_table_name()
                return A.VacuumAnalyze(name, full)
            return A.Vacuum(self.parse_table_name(), full)
        if self.at_kw("analyze"):
            self.next()
            name = self.parse_table_name() if self.peek().kind in (
                "ident",) else None
            return A.Analyze(name)
        if self.peek().kind == "ident" and self.peek().value == "reindex":
            self.next()
            t = self.peek()
            if t.kind in ("ident", "kw") and t.value in ("index", "table"):
                self.next()
                kind = t.value
            else:
                self.error("expected INDEX or TABLE after REINDEX")
            return A.Reindex(kind, self.parse_table_name())
        if self.at_kw("grant", "revoke"):
            revoke = self.next().value == "revoke"
            privs = []
            if self.at_kw("all"):
                self.next()
                if self.peek().kind == "ident" and self.peek().value == "privileges":
                    self.next()
                privs = ["all"]
            else:
                while True:
                    t = self.next()
                    name = t.value
                    if name not in ("select", "insert", "update", "delete",
                                    "truncate"):
                        self.error("expected a privilege name")
                    privs.append(name)
                    if not self.accept_op(","):
                        break
            self.expect_kw("on")
            self.accept_kw("table")
            table = self.parse_table_name()
            if revoke:
                self.expect_kw("from")
            else:
                self.expect_kw("to")
            role = self.expect_ident()
            return A.Grant(privs, table, role, revoke)
        self.error("expected a statement")

    def parse_with_select(self) -> A.WithSelect:
        self.expect_kw("with")
        recursive = False
        if self.peek().kind == "ident" and self.peek().value == "recursive":
            self.next()
            recursive = True
        ctes = []
        cte_cols: dict = {}
        while True:
            name = self.expect_ident()
            if self.at_op("("):
                self.next()
                cols = [self.expect_ident()]
                while self.accept_op(","):
                    cols.append(self.expect_ident())
                self.expect_op(")")
                cte_cols[name] = cols
            self.expect_kw("as")
            self.expect_op("(")
            ctes.append((name, self.parse_select()))
            self.expect_op(")")
            if not self.accept_op(","):
                break
        body = self.parse_select()
        return A.WithSelect(ctes, body, recursive, cte_cols)

    def parse_merge(self) -> A.Merge:
        self.expect_kw("merge")
        self.expect_kw("into")
        target = self.parse_table_ref()
        self.expect_kw("using")
        source = self.parse_table_ref()
        self.expect_kw("on")
        on = self.parse_expr()
        whens = []
        while self.at_kw("when"):
            self.next()
            matched = True
            if self.accept_kw("not"):
                self.expect_kw("matched")
                matched = False
            else:
                self.expect_kw("matched")
            cond = None
            if self.accept_kw("and"):
                cond = self.parse_expr()
            self.expect_kw("then")
            if self.accept_kw("update"):
                self.expect_kw("set")
                assignments = []
                while True:
                    col = self.expect_ident()
                    self.expect_op("=")
                    assignments.append((col, self.parse_expr()))
                    if not self.accept_op(","):
                        break
                whens.append(A.MergeWhen(matched, "update", cond, assignments))
            elif self.accept_kw("delete"):
                whens.append(A.MergeWhen(matched, "delete", cond))
            elif self.accept_kw("insert"):
                cols = None
                if self.at_op("("):
                    self.next()
                    cols = []
                    while True:
                        cols.append(self.expect_ident())
                        if not self.accept_op(","):
                            break
                    self.expect_op(")")
                self.expect_kw("values")
                self.expect_op("(")
                vals = []
                while True:
                    vals.append(self.parse_expr())
                    if not self.accept_op(","):
                        break
                self.expect_op(")")
                whens.append(A.MergeWhen(matched, "insert", cond,
                                         insert_columns=cols, insert_values=vals))
            elif self.accept_kw("do"):
                self.expect_kw("nothing")
                whens.append(A.MergeWhen(matched, "nothing", cond))
            else:
                self.error("expected UPDATE, DELETE, INSERT, or DO NOTHING")
        if not whens:
            self.error("MERGE requires at least one WHEN clause")
        return A.Merge(target, source, on, whens)

    def parse_alter_table(self) -> A.AlterTable:
        self.expect_kw("alter")
        self.expect_kw("table")
        name = self.parse_table_name()
        if self.accept_kw("add"):
            # ADD [CONSTRAINT name] CHECK (expr)
            if self.peek().kind == "ident" \
                    and self.peek().value in ("constraint", "check"):
                ck_name = None
                if self.peek().value == "constraint":
                    self.next()
                    ck_name = self.expect_ident()
                if not (self.peek().kind == "ident"
                        and self.peek().value == "check"):
                    self.error("expected CHECK")
                self.next()
                return A.AlterTable(name, "add_check",
                                    check_sql=self._parse_paren_expr_text(),
                                    new_name=ck_name)
            self.accept_kw("column")
            cname = self.expect_ident()
            tname, targs = self.parse_type_name()
            not_null = False
            if self.accept_kw("not"):
                self.expect_kw("null")
                not_null = True
            return A.AlterTable(name, "add_column",
                                column=A.ColumnDef(cname, tname, targs, not_null))
        if self.accept_kw("drop"):
            if self.peek().kind == "ident" \
                    and self.peek().value == "constraint":
                self.next()
                return A.AlterTable(name, "drop_constraint",
                                    old_name=self.expect_ident())
            self.accept_kw("column")
            return A.AlterTable(name, "drop_column", old_name=self.expect_ident())
        if self.accept_kw("rename"):
            if self.accept_kw("column"):
                old = self.expect_ident()
                self.expect_kw("to")
                return A.AlterTable(name, "rename_column", old_name=old,
                                    new_name=self.expect_ident())
            if self.accept_kw("to"):
                return A.AlterTable(name, "rename_table",
                                    new_name=self.expect_ident())
            old = self.expect_ident()
            self.expect_kw("to")
            return A.AlterTable(name, "rename_column", old_name=old,
                                new_name=self.expect_ident())
        if self.accept_kw("alter"):
            # ALTER COLUMN c SET DEFAULT expr / DROP DEFAULT
            self.accept_kw("column")
            cname = self.expect_ident()
            if self.accept_kw("set"):
                if not (self.peek().kind == "ident"
                        and self.peek().value == "default"):
                    self.error("expected DEFAULT")
                self.next()
                start = self.peek().pos
                self.parse_additive()
                end = self.peek().pos if self.peek().kind != "eof" \
                    else len(self.text)
                return A.AlterTable(name, "set_default", old_name=cname,
                                    check_sql=self.text[start:end].strip())
            if self.accept_kw("drop"):
                if not (self.peek().kind == "ident"
                        and self.peek().value == "default"):
                    self.error("expected DEFAULT")
                self.next()
                return A.AlterTable(name, "set_default", old_name=cname,
                                    check_sql=None)
            self.error("expected SET DEFAULT or DROP DEFAULT")
        if self.peek().kind == "ident" \
                and self.peek().value in ("enable", "disable"):
            enable = self.next().value == "enable"
            for word, kinds in (("row", ("kw", "ident")),
                                ("level", ("ident",)),
                                ("security", ("ident",))):
                t = self.peek()
                if not (t.kind in kinds and t.value == word):
                    self.error("expected ROW LEVEL SECURITY")
                self.next()
            return A.AlterTableRls(name, enable)
        self.error("expected ADD, DROP, RENAME, or ENABLE/DISABLE ROW "
                   "LEVEL SECURITY")

    def parse_explain(self) -> A.Explain:
        self.expect_kw("explain")
        analyze = bool(self.accept_kw("analyze"))
        return A.Explain(self.parse_statement(), analyze=analyze)

    def _maybe_grouping_sets(self):
        """ROLLUP(e...) | CUBE(e...) | GROUPING SETS((..), (..), e) as the
        whole GROUP BY clause -> GroupingSetsSpec, else None."""
        t = self.peek()
        if t.kind != "ident" or t.value not in ("rollup", "cube", "grouping"):
            return None
        kind = self.next().value
        if kind == "grouping":
            if not (self.peek().kind == "ident" and self.peek().value == "sets"):
                self.error("expected SETS after GROUPING")
            self.next()
            self.expect_op("(")
            sets = []
            while True:
                if self.accept_op("("):
                    exprs = []
                    if not self.at_op(")"):
                        while True:
                            exprs.append(self.parse_expr())
                            if not self.accept_op(","):
                                break
                    self.expect_op(")")
                    sets.append(tuple(exprs))
                else:
                    sets.append((self.parse_expr(),))
                if not self.accept_op(","):
                    break
            self.expect_op(")")
            return A.GroupingSetsSpec(tuple(sets))
        self.expect_op("(")
        exprs = []
        while True:
            exprs.append(self.parse_expr())
            if not self.accept_op(","):
                break
        self.expect_op(")")
        if kind == "rollup":
            sets = [tuple(exprs[:i]) for i in range(len(exprs), -1, -1)]
        else:  # cube
            if len(exprs) > 5:
                self.error("CUBE supports at most 5 expressions")
            from itertools import combinations
            sets = []
            for r in range(len(exprs), -1, -1):
                for combo in combinations(range(len(exprs)), r):
                    sets.append(tuple(exprs[i] for i in combo))
        return A.GroupingSetsSpec(tuple(sets))

    def _parse_window_spec(self):
        """'(' [base_window_name] [PARTITION BY ...] [ORDER BY ...]
        [ROWS|RANGE frame] ')' -> (partition tuple, order tuple,
        frame|None, base_name|None)."""
        self.expect_op("(")
        base = None
        if self.peek().kind == "ident" and not self.at_op(")"):
            base = self.expect_ident()
        part, order = [], []
        if self.accept_kw("partition"):
            self.expect_kw("by")
            while True:
                part.append(self.parse_expr())
                if not self.accept_op(","):
                    break
        if self.accept_kw("order"):
            self.expect_kw("by")
            while True:
                e_ = self.parse_expr()
                asc = True
                if self.accept_kw("asc"):
                    pass
                elif self.accept_kw("desc"):
                    asc = False
                order.append((e_, asc))
                if not self.accept_op(","):
                    break
        frame = None
        if self.at_kw("rows", "range"):
            mode = self.next().value
            if self.accept_kw("between"):
                start = self._parse_frame_bound()
                self.expect_kw("and")
                end = self._parse_frame_bound()
            else:
                # shorthand: frame start only, end = CURRENT ROW
                start = self._parse_frame_bound()
                end = ("current", 0)
            frame = (mode, start, end)
        self.expect_op(")")
        return tuple(part), tuple(order), frame, base

    def _parse_frame_bound(self):
        """UNBOUNDED PRECEDING|FOLLOWING | CURRENT ROW | N PRECEDING|
        FOLLOWING -> ('preceding'|'following', n|None) with None =
        unbounded, or ('current', 0)."""
        if self.accept_kw("unbounded"):
            d = self.next().value
            if d not in ("preceding", "following"):
                self.error("expected PRECEDING or FOLLOWING")
            return (d, None)
        if self.accept_kw("current"):
            self.expect_kw("row")
            return ("current", 0)
        t = self.next()
        if t.kind != "num":
            self.error("expected a frame bound")
        d = self.next().value
        if d not in ("preceding", "following"):
            self.error("expected PRECEDING or FOLLOWING")
        return (d, int(t.value))

    def _parse_paren_expr_text(self) -> str:
        """'(' expr ')' -> the expression's source text (validated by
        parsing, persisted as SQL so it survives the catalog)."""
        self.expect_op("(")
        start = self.peek().pos
        self.parse_expr()
        end = self.peek().pos   # position of the closing ')'
        self.expect_op(")")
        return self.text[start:end].strip()

    def parse_table_name(self) -> str:
        name = self.expect_ident()
        if self.accept_op("."):
            return f"{name}.{self.expect_ident()}"
        return name

    # -- CREATE TABLE t (col type [not null], ...) [using columnar] [with (...)]
    def parse_create_table(self):
        self.expect_kw("create")
        if self.accept_kw("schema"):
            if_not_exists = False
            if self.accept_kw("if"):
                self.expect_kw("not")
                self.expect_kw("exists")
                if_not_exists = True
            return A.CreateSchema(self.expect_ident(), if_not_exists)
        if self.peek().kind == "ident" and self.peek().value in ("role", "user"):
            self.next()
            if_not_exists = False
            if self.accept_kw("if"):
                self.expect_kw("not")
                self.expect_kw("exists")
                if_not_exists = True
            return A.CreateRole(self.expect_ident(), if_not_exists)
        if self.peek().kind == "ident" and self.peek().value == "extension":
            self.next()
            ine = self._accept_if_not_exists()
            name = self.expect_ident()
            version = None
            if self.peek().kind == "ident" and self.peek().value == "version":
                self.next()
                vt = self.next()
                version = vt.value.strip("'")
            return A.CreateExtension(name, ine, version)
        if self.peek().kind == "ident" and self.peek().value == "domain":
            self.next()
            name = self.expect_ident()
            self.expect_kw("as")
            base, targs = self.parse_type_name()
            not_null = False
            check_sql = None
            while True:
                if self.accept_kw("not"):
                    self.expect_kw("null")
                    not_null = True
                    continue
                if self.peek().kind == "ident" and self.peek().value == "check":
                    self.next()
                    check_sql = self._parse_paren_expr_text()
                    continue
                break
            return A.CreateDomain(name, base, targs, not_null, check_sql)
        if self.peek().kind == "ident" and self.peek().value == "collation":
            self.next()
            name = self.expect_ident()
            options: dict = {}
            if self.accept_op("("):
                while True:
                    key = self.next().value
                    self.expect_op("=")
                    options[key] = self.next().value.strip("'")
                    if not self.accept_op(","):
                        break
                self.expect_op(")")
            return A.CreateCollation(name, options)
        if self.peek().kind == "ident" and self.peek().value == "publication":
            self.next()
            name = self.expect_ident()
            # no FOR clause = EMPTY publication (PostgreSQL semantics),
            # not FOR ALL TABLES
            tables: "list | str" = []
            if self.peek().value == "for":
                self.next()
                if self.peek().value == "all":
                    self.next()
                    if self.peek().value != "tables":
                        self.error("expected TABLES")
                    self.next()
                    tables = "all"
                else:
                    self.expect_kw("table")
                    tables = [self.parse_table_name()]
                    while self.accept_op(","):
                        tables.append(self.parse_table_name())
            return A.CreatePublication(name, tables)
        if self.peek().kind == "ident" and self.peek().value == "statistics":
            self.next()
            name = self.expect_ident()
            self.expect_kw("on")
            cols = [self.expect_ident()]
            while self.accept_op(","):
                cols.append(self.expect_ident())
            self.expect_kw("from")
            return A.CreateStatistics(name, cols, self.parse_table_name())
        if self.peek().kind == "ident" and self.peek().value in ("unique",
                                                                 "index"):
            unique = self.next().value == "unique"
            if unique:
                if not (self.peek().kind == "ident"
                        and self.peek().value == "index"):
                    self.error("expected INDEX after UNIQUE")
                self.next()
            if_not_exists = False
            if self.accept_kw("if"):
                self.expect_kw("not")
                self.expect_kw("exists")
                if_not_exists = True
            name = self.expect_ident()
            self.expect_kw("on")
            table = self.parse_table_name()
            self.expect_op("(")
            column = self.expect_ident()
            if self.accept_op(","):
                self.error("multi-column indexes are not supported")
            self.expect_op(")")
            return A.CreateIndex(name, table, column, unique, if_not_exists)
        or_replace = False
        if self.peek().kind == "kw" and self.peek().value == "or":
            # CREATE OR REPLACE FUNCTION
            self.next()
            if not (self.peek().kind == "ident" and self.peek().value == "replace"):
                self.error("expected REPLACE")
            self.next()
            or_replace = True
        if self.peek().kind == "ident" and self.peek().value == "function":
            self.next()
            name = self.expect_ident()
            self.expect_op("(")
            arg_names, arg_types = [], []
            if not self.at_op(")"):
                while True:
                    arg_names.append(self.expect_ident())
                    tn, targs = self.parse_type_name()
                    arg_types.append(tn)
                    if not self.accept_op(","):
                        break
            self.expect_op(")")
            if not (self.peek().kind == "ident" and self.peek().value == "returns"):
                self.error("expected RETURNS")
            self.next()
            ret, _ = self.parse_type_name()
            self.expect_kw("as")
            bt = self.next()
            if bt.kind != "str":
                self.error("expected a quoted function body")
            body = bt.value[1:-1].replace("''", "'")
            if self.peek().kind == "ident" and self.peek().value == "language":
                self.next()
                self.next()  # sql
            return A.CreateFunction(name, arg_names, arg_types, ret, body,
                                    or_replace)
        if or_replace and not (self.peek().kind == "ident"
                               and self.peek().value == "view"):
            self.error("expected FUNCTION or VIEW after OR REPLACE")
        if self.peek().kind == "ident" and self.peek().value == "type":
            self.next()
            name = self.expect_ident()
            self.expect_kw("as")
            if not (self.peek().kind == "ident" and self.peek().value == "enum"):
                self.error("only CREATE TYPE ... AS ENUM is supported")
            self.next()
            self.expect_op("(")
            labels = []
            while True:
                lt = self.next()
                if lt.kind != "str":
                    self.error("expected a quoted enum label")
                labels.append(lt.value[1:-1].replace("''", "'"))
                if not self.accept_op(","):
                    break
            self.expect_op(")")
            return A.CreateType(name, labels)
        if self.peek().kind == "ident" and self.peek().value == "policy":
            self.next()
            name = self.expect_ident()
            self.expect_kw("on")
            table = self.parse_table_name()
            cmd = "all"
            if self.peek().kind == "ident" and self.peek().value == "for":
                self.next()
                t = self.next()
                if t.value not in ("all", "select", "insert", "update",
                                   "delete"):
                    self.error("expected ALL/SELECT/INSERT/UPDATE/DELETE")
                cmd = t.value
            roles: tuple = ("public",)
            if self.accept_kw("to"):
                rs = [self.expect_ident()]
                while self.accept_op(","):
                    rs.append(self.expect_ident())
                roles = tuple(rs)
            using_sql = check_sql = None
            if self.accept_kw("using"):
                using_sql = self._parse_paren_expr_text()
            if self.accept_kw("with"):
                if not (self.peek().kind == "ident"
                        and self.peek().value == "check"):
                    self.error("expected CHECK")
                self.next()
                check_sql = self._parse_paren_expr_text()
            return A.CreatePolicy(name, table, cmd, roles, using_sql,
                                  check_sql)
        if self.peek().kind == "ident" and self.peek().value == "trigger":
            self.next()
            name = self.expect_ident()
            if not (self.peek().kind == "ident"
                    and self.peek().value == "after"):
                self.error("only AFTER triggers are supported")
            self.next()
            evt = self.next()
            if evt.value not in ("insert", "update", "delete"):
                self.error("expected INSERT, UPDATE, or DELETE")
            self.expect_kw("on")
            table = self.parse_table_name()
            if self.peek().kind == "ident" and self.peek().value == "for":
                self.next()
                if self.peek().kind == "ident" and self.peek().value == "each":
                    self.next()
                t = self.next()
                if t.value != "statement":
                    self.error("only FOR EACH STATEMENT triggers are "
                               "supported")
            if not (self.peek().kind == "ident"
                    and self.peek().value == "execute"):
                self.error("expected EXECUTE FUNCTION")
            self.next()
            if self.peek().kind == "ident" \
                    and self.peek().value in ("function", "procedure"):
                self.next()
            fname = self.expect_ident()
            self.expect_op("(")
            self.expect_op(")")
            return A.CreateTrigger(name, evt.value, table, fname)
        if self.peek().kind == "ident" and self.peek().value == "text":
            self.next()
            for word in ("search", "configuration"):
                if not (self.peek().kind == "ident"
                        and self.peek().value == word):
                    self.error(f"expected {word.upper()}")
                self.next()
            name = self.expect_ident()
            options: dict = {}
            if self.accept_op("("):
                while True:
                    key = self.next().value
                    self.expect_op("=")
                    options[key] = self.next().value.strip("'")
                    if not self.accept_op(","):
                        break
                self.expect_op(")")
            return A.CreateTsConfig(name, options)
        if self.peek().kind == "ident" and self.peek().value == "view":
            self.next()
            name = self.parse_table_name()
            self.expect_kw("as")
            body_start = self.peek().pos
            sel = self.parse_with_select() if self.at_kw("with") \
                else self.parse_select()
            return A.CreateView(name, sel,
                                self.text[body_start:self.peek().pos].strip(),
                                or_replace)
        if self.peek().kind == "ident" and self.peek().value == "sequence":
            self.next()
            if_not_exists = False
            if self.accept_kw("if"):
                self.expect_kw("not")
                self.expect_kw("exists")
                if_not_exists = True
            name = self.parse_table_name()
            start, increment = 1, 1
            while self.peek().kind == "ident" and self.peek().value in ("start", "increment"):
                kw = self.next().value
                self.accept_kw("with") or (self.peek().kind == "ident"
                                           and self.peek().value == "by" and self.next())
                neg = bool(self.accept_op("-"))
                t = self.next()
                if t.kind != "num":
                    self.error("expected a number")
                v = -int(t.value) if neg else int(t.value)
                if kw == "start":
                    start = v
                else:
                    increment = v
            return A.CreateSequence(name, start, increment, if_not_exists)
        self.expect_kw("table")
        if_not_exists = False
        if self.accept_kw("if"):
            self.expect_kw("not") if self.at_kw("not") else self.error("expected NOT")
            self.expect_kw("exists")
            if_not_exists = True
        name = self.parse_table_name()
        if self.accept_kw("partition"):
            # CREATE TABLE x PARTITION OF parent FOR VALUES FROM (a) TO (b)
            if not (self.peek().kind == "ident" and self.peek().value == "of"):
                self.error("expected OF")
            self.next()
            parent = self.parse_table_name()
            lo = hi = None
            if self.peek().value == "for":
                self.next()
                if self.peek().value != "values":
                    self.error("expected VALUES")
                self.next()
                self.expect_kw("from")
                self.expect_op("(")
                lo = self._parse_partition_bound()
                self.expect_op(")")
                self.expect_kw("to")
                self.expect_op("(")
                hi = self._parse_partition_bound()
                self.expect_op(")")
            else:
                self.error("expected FOR VALUES FROM (..) TO (..)")
            return A.CreateTable(name, [], if_not_exists,
                                 partition_of={"parent": parent,
                                               "lo": lo, "hi": hi})
        if self.at_kw("as"):
            # CREATE TABLE x AS SELECT ... (CTAS)
            self.next()
            if self.at_kw("with"):
                sel: A.Statement = self.parse_with_select()
            else:
                sel = self.parse_select()
            return A.CreateTableAs(name, sel, if_not_exists)
        self.expect_op("(")
        cols = []
        fkeys = []
        table_pk: list = []
        table_unique: list = []
        table_checks: list = []
        while True:
            if self.peek().kind == "ident" and self.peek().value == "foreign":
                # table constraint: FOREIGN KEY (cols) REFERENCES t (cols)
                self.next()
                if not (self.peek().kind == "ident"
                        and self.peek().value == "key"):
                    self.error("expected KEY")
                self.next()
                self.expect_op("(")
                fcols = [self.expect_ident()]
                while self.accept_op(","):
                    fcols.append(self.expect_ident())
                self.expect_op(")")
                fkeys.append(self._parse_references(fcols))
                if not self.accept_op(","):
                    break
                continue
            if self.peek().kind == "ident" \
                    and self.peek().value == "check" \
                    and self.peek(1).kind == "op" \
                    and self.peek(1).value == "(":
                # table constraint: CHECK (expr)
                self.next()
                table_checks.append(self._parse_paren_expr_text())
                if not self.accept_op(","):
                    break
                continue
            if self.peek().kind == "ident" \
                    and self.peek().value in ("primary", "unique") \
                    and self.peek(1).kind in ("ident", "op") \
                    and (self.peek(1).value == "key"
                         or self.peek(1).value == "("):
                # table constraint: PRIMARY KEY (cols) / UNIQUE (cols)
                is_pk = self.next().value == "primary"
                if is_pk:
                    if not (self.peek().kind == "ident"
                            and self.peek().value == "key"):
                        self.error("expected KEY after PRIMARY")
                    self.next()
                self.expect_op("(")
                kcols = [self.expect_ident()]
                while self.accept_op(","):
                    kcols.append(self.expect_ident())
                self.expect_op(")")
                if len(kcols) > 1:
                    self.error("multi-column PRIMARY KEY/UNIQUE "
                               "constraints are not supported")
                (table_pk if is_pk else table_unique).append(kcols[0])
                if not self.accept_op(","):
                    break
                continue
            cname = self.expect_ident()
            tname, targs = self.parse_type_name()
            not_null = False
            primary_key = False
            unique = False
            default_sql = ""
            while True:
                if self.peek().kind == "ident" \
                        and self.peek().value == "check" \
                        and self.peek(1).kind == "op" \
                        and self.peek(1).value == "(":
                    self.next()
                    table_checks.append(self._parse_paren_expr_text())
                    continue
                if self.peek().kind == "ident" \
                        and self.peek().value == "default":
                    self.next()
                    start = self.peek().pos
                    self.parse_additive()  # validate the expression
                    end = self.peek().pos if self.peek().kind != "eof" \
                        else len(self.text)
                    default_sql = self.text[start:end].strip()
                    continue
                if self.accept_kw("not"):
                    self.expect_kw("null")
                    not_null = True
                    continue
                if self.peek().kind == "ident" \
                        and self.peek().value == "primary":
                    self.next()
                    if not (self.peek().kind == "ident"
                            and self.peek().value == "key"):
                        self.error("expected KEY after PRIMARY")
                    self.next()
                    primary_key = True
                    not_null = True
                    continue
                if self.peek().kind == "ident" \
                        and self.peek().value == "unique":
                    self.next()
                    unique = True
                    continue
                if self.peek().kind == "ident" \
                        and self.peek().value == "references":
                    fkeys.append(self._parse_references([cname]))
                    continue
                break
            cols.append(A.ColumnDef(cname, tname, targs, not_null,
                                    primary_key, unique, default_sql))
            if not self.accept_op(","):
                break
        self.expect_op(")")
        if table_pk or table_unique:
            # table-level single-column constraints fold onto the column
            import dataclasses as _dc
            by_name = {c.name: i for i, c in enumerate(cols)}
            for cn in table_pk:
                i = by_name.get(cn)
                if i is None:
                    self.error(f"PRIMARY KEY column {cn!r} not defined")
                cols[i] = _dc.replace(cols[i], primary_key=True,
                                      not_null=True)
            for cn in table_unique:
                i = by_name.get(cn)
                if i is None:
                    self.error(f"UNIQUE column {cn!r} not defined")
                cols[i] = _dc.replace(cols[i], unique=True)
        options: dict = {}
        partition_by = None
        if self.accept_kw("partition"):
            self.expect_kw("by")
            # "range" lexes as a keyword (window frames use it)
            if self.peek().value != "range":
                self.error("only PARTITION BY RANGE is supported")
            self.next()
            self.expect_op("(")
            partition_by = self.expect_ident()
            self.expect_op(")")
        if self.accept_kw("using"):
            options["access_method"] = self.expect_ident()
        if self.accept_kw("with"):
            self.expect_op("(")
            while True:
                key = self.expect_ident()
                self.expect_op("=")
                t = self.next()
                options[key] = t.value.strip("'")
                if not self.accept_op(","):
                    break
            self.expect_op(")")
        return A.CreateTable(name, cols, if_not_exists, options, fkeys,
                             partition_by=partition_by,
                             checks=table_checks)

    def _parse_copy_path_and_options(self):
        """'path' [WITH (opt [value], ...)] — shared by every COPY form."""
        t = self.next()
        if t.kind != "str":
            self.error("expected a quoted file path after COPY")
        path = t.value[1:-1].replace("''", "'")
        options: dict = {}
        if self.accept_kw("with"):
            self.expect_op("(")
            while True:
                key = self.expect_ident() \
                    if self.peek().kind == "ident" else self.next().value
                if self.at_op(")") or self.at_op(","):
                    options[key] = True
                else:
                    v = self.next()
                    options[key] = v.value.strip("'")
                if not self.accept_op(","):
                    break
            self.expect_op(")")
        return path, options

    def _accept_if_not_exists(self) -> bool:
        if self.accept_kw("if"):
            self.expect_kw("not")
            self.expect_kw("exists")
            return True
        return False

    def _parse_partition_bound(self):
        """One FOR VALUES bound: literal, MINVALUE, or MAXVALUE (both
        map to None = unbounded)."""
        t = self.peek()
        if t.kind == "ident" and t.value in ("minvalue", "maxvalue"):
            self.next()
            return None
        neg = bool(self.accept_op("-"))
        t = self.next()
        if t.kind == "num":
            v = float(t.value) if "." in t.value else int(t.value)
            return -v if neg else v
        if t.kind == "str":
            return t.value[1:-1].replace("''", "'")
        self.error("expected a partition bound literal")

    def _parse_references(self, fcols: list[str]) -> dict:
        """REFERENCES tbl [(cols)] [ON DELETE CASCADE|RESTRICT|SET NULL|
        NO ACTION] — the referenced columns default to the referenced
        table's distribution column (resolved at DDL time)."""
        if not (self.peek().kind == "ident"
                and self.peek().value == "references"):
            self.error("expected REFERENCES")
        self.next()
        ref_table = self.parse_table_name()
        ref_cols: list[str] = []
        if self.accept_op("("):
            ref_cols.append(self.expect_ident())
            while self.accept_op(","):
                ref_cols.append(self.expect_ident())
            self.expect_op(")")
        on_delete = "restrict"
        if self.accept_kw("on"):
            self.expect_kw("delete")
            if self.accept_kw("cascade"):
                on_delete = "cascade"
            elif self.accept_kw("set"):
                self.expect_kw("null")
                on_delete = "set null"
            elif self.peek().kind == "ident" \
                    and self.peek().value in ("restrict", "no"):
                if self.next().value == "no":
                    if not (self.peek().kind == "ident"
                            and self.peek().value == "action"):
                        self.error("expected ACTION")
                    self.next()
            else:
                self.error("expected CASCADE, RESTRICT, SET NULL or "
                           "NO ACTION")
        return {"columns": list(fcols), "ref_table": ref_table,
                "ref_columns": ref_cols, "on_delete": on_delete}

    def parse_type_name(self) -> tuple[str, list[int]]:
        t = self.peek()
        if t.kind not in ("ident", "kw"):
            self.error("expected type name")
        self.next()
        name = t.value
        # two-word types: double precision, character varying,
        # timestamp with[out] time zone
        if name == "double" and self.peek().kind == "ident" and self.peek().value == "precision":
            self.next()
        elif name == "character":
            if self.peek().kind == "ident" and self.peek().value == "varying":
                self.next()
            name = "varchar"
        elif name == "timestamp" and self.peek().kind in ("ident", "kw") \
                and self.peek().value in ("with", "without"):
            with_tz = self.next().value == "with"
            if not (self.peek().value == "time"
                    and self.peek(1).value == "zone"):
                self.error("expected TIME ZONE after WITH/WITHOUT")
            self.next()
            self.next()
            if with_tz:
                name = "timestamptz"
        args: list[int] = []
        if self.at_op("("):
            self.next()
            while True:
                nt = self.next()
                if nt.kind != "num":
                    self.error("expected number in type args")
                args.append(int(nt.value))
                if not self.accept_op(","):
                    break
            self.expect_op(")")
        if self.at_op("[") :
            # 1-D array type: elem[]
            self.next()
            self.expect_op("]")
            name = name + "[]"
        return name, args

    def parse_drop_table(self):
        self.expect_kw("drop")
        if self.accept_kw("schema"):
            name = self.expect_ident()
            cascade = bool(self.accept_kw("cascade"))
            return A.DropSchema(name, cascade)
        if self.peek().kind == "ident" and self.peek().value in ("role", "user"):
            self.next()
            if_exists = False
            if self.accept_kw("if"):
                self.expect_kw("exists")
                if_exists = True
            return A.DropRole(self.expect_ident(), if_exists)
        if self.peek().kind == "ident" and self.peek().value == "function":
            self.next()
            if_exists = False
            if self.accept_kw("if"):
                self.expect_kw("exists")
                if_exists = True
            return A.DropFunction(self.expect_ident(), if_exists)
        if self.peek().kind == "ident" and self.peek().value == "type":
            self.next()
            if_exists = False
            if self.accept_kw("if"):
                self.expect_kw("exists")
                if_exists = True
            return A.DropType(self.expect_ident(), if_exists)
        if self.peek().kind == "ident" and self.peek().value == "policy":
            self.next()
            if_exists = False
            if self.accept_kw("if"):
                self.expect_kw("exists")
                if_exists = True
            name = self.expect_ident()
            self.expect_kw("on")
            return A.DropPolicy(name, self.parse_table_name(), if_exists)
        if self.peek().kind == "ident" and self.peek().value == "trigger":
            self.next()
            if_exists = False
            if self.accept_kw("if"):
                self.expect_kw("exists")
                if_exists = True
            name = self.expect_ident()
            self.expect_kw("on")
            return A.DropTrigger(name, self.parse_table_name(), if_exists)
        if self.peek().kind == "ident" and self.peek().value == "text":
            self.next()
            for word in ("search", "configuration"):
                if not (self.peek().kind == "ident"
                        and self.peek().value == word):
                    self.error(f"expected {word.upper()}")
                self.next()
            if_exists = False
            if self.accept_kw("if"):
                self.expect_kw("exists")
                if_exists = True
            return A.DropTsConfig(self.expect_ident(), if_exists)
        if self.peek().kind == "ident" and self.peek().value == "index":
            self.next()
            if_exists = False
            if self.accept_kw("if"):
                self.expect_kw("exists")
                if_exists = True
            return A.DropIndex(self.expect_ident(), if_exists)
        if self.peek().kind == "ident" and self.peek().value in (
                "extension", "domain", "collation", "publication",
                "statistics"):
            kind = self.next().value
            if_exists = False
            if self.accept_kw("if"):
                self.expect_kw("exists")
                if_exists = True
            node = {"extension": A.DropExtension, "domain": A.DropDomain,
                    "collation": A.DropCollation,
                    "publication": A.DropPublication,
                    "statistics": A.DropStatistics}[kind]
            return node(self.expect_ident(), if_exists)
        if self.peek().kind == "ident" and self.peek().value in ("view", "sequence"):
            kind = self.next().value
            if_exists = False
            if self.accept_kw("if"):
                self.expect_kw("exists")
                if_exists = True
            name = self.parse_table_name()
            return (A.DropView if kind == "view" else A.DropSequence)(name, if_exists)
        self.expect_kw("table")
        if_exists = False
        if self.accept_kw("if"):
            self.expect_kw("exists")
            if_exists = True
        return A.DropTable(self.parse_table_name(), if_exists)

    def parse_insert(self) -> A.Insert:
        self.expect_kw("insert")
        self.expect_kw("into")
        name = self.parse_table_name()
        cols = None
        if self.at_op("("):
            self.next()
            cols = []
            while True:
                cols.append(self.expect_ident())
                if not self.accept_op(","):
                    break
            self.expect_op(")")
        if self.at_kw("select"):
            sel = self.parse_select()
            return A.Insert(name, cols, [], select=sel,
                            on_conflict=self._parse_on_conflict(),
                            returning=self._parse_returning())
        if self.peek().kind == "ident" \
                and self.peek().value == "default" \
                and self.peek(1).kind == "kw" \
                and self.peek(1).value == "values":
            # INSERT INTO t DEFAULT VALUES: one row, all defaults
            self.next()
            self.next()
            return A.Insert(name, [], [[]],
                            on_conflict=self._parse_on_conflict(),
                            returning=self._parse_returning())
        self.expect_kw("values")
        rows = []
        while True:
            self.expect_op("(")
            row = []
            while True:
                row.append(self.parse_expr())
                if not self.accept_op(","):
                    break
            self.expect_op(")")
            rows.append(row)
            if not self.accept_op(","):
                break
        return A.Insert(name, cols, rows,
                        on_conflict=self._parse_on_conflict(),
                        returning=self._parse_returning())

    def _parse_on_conflict(self):
        """ON CONFLICT [(col, ...)] DO NOTHING | DO UPDATE SET col =
        expr [, ...] [WHERE cond] — expressions may reference
        ``excluded.col`` (the proposed row, as in PostgreSQL)."""
        save = self.i
        if not self.accept_kw("on"):
            return None
        if not (self.peek().kind == "ident" and self.peek().value == "conflict"):
            self.i = save
            return None
        self.next()
        targets = []
        if self.accept_op("("):
            while True:
                targets.append(self.expect_ident())
                if not self.accept_op(","):
                    break
            self.expect_op(")")
        self.expect_kw("do")
        if self.accept_kw("nothing"):
            return A.OnConflict(tuple(targets), "nothing")
        self.expect_kw("update")
        self.expect_kw("set")
        assignments = []
        while True:
            col = self.expect_ident()
            self.expect_op("=")
            assignments.append((col, self.parse_expr()))
            if not self.accept_op(","):
                break
        where = self.parse_expr() if self.accept_kw("where") else None
        return A.OnConflict(tuple(targets), "update", tuple(assignments),
                            where)

    def _parse_returning(self):
        """RETURNING expr [AS alias] [, ...] on INSERT/UPDATE/DELETE —
        reference: RETURNING support in the adaptive executor's DML path
        (distributed/executor/adaptive_executor.c returns tuples from
        worker DML)."""
        if not self.accept_kw("returning"):
            return None
        items = []
        while True:
            if self.at_op("*"):
                self.next()
                items.append(A.SelectItem(A.Star()))
            else:
                e = self.parse_expr()
                alias = None
                if self.accept_kw("as"):
                    alias = self.expect_ident()
                elif self.peek().kind == "ident":
                    alias = self.expect_ident()
                items.append(A.SelectItem(e, alias))
            if not self.accept_op(","):
                break
        return items

    # -- SELECT ----------------------------------------------------------
    _UTILITY_FNS = {
        "create_distributed_table", "create_reference_table",
        "undistribute_table", "citus_add_node", "citus_remove_node",
        "citus_set_coordinator_host", "rebalance_table_shards",
        "get_rebalance_table_shards_plan", "citus_rebalance_start",
        "citus_job_wait", "citus_cleanup_orphaned_resources",
        "citus_move_shard_placement", "citus_copy_shard_placement",
        "citus_table_size", "citus_shard_sizes",
        "master_get_active_worker_nodes",
        "citus_stat_counters", "citus_stat_counters_reset",
        "citus_stat_statements", "citus_stat_statements_reset",
        "citus_metrics", "citus_slow_queries", "citus_slow_queries_reset",
        "citus_cluster_metrics", "citus_cluster_slow_queries",
        "citus_stat_activity", "citus_locks", "citus_lock_waits",
        "citus_shards", "citus_tables", "recover_prepared_transactions",
        "nextval", "currval", "setval", "citus_views", "citus_sequences",
        "citus_cdc_events", "citus_roles", "citus_grants",
        "citus_version", "citus_dist_stat_activity", "citus_types",
        "citus_policies", "citus_triggers", "citus_text_search_configs",
        "get_shard_id_for_distribution_column", "citus_relation_size",
        "citus_total_relation_size", "citus_disable_node",
        "citus_activate_node", "citus_get_active_worker_nodes",
        "citus_get_node_clock", "citus_get_transaction_clock",
        "citus_create_restore_point", "citus_list_restore_points",
        "alter_distributed_table", "citus_check_cluster_node_health",
        "citus_stat_tenants", "get_rebalance_progress", "citus_schemas",
        "citus_split_shard_by_split_points", "isolate_tenant_to_new_shard",
        "citus_schema_tenant_set", "citus_schema_tenant_unset",
        "run_command_on_workers", "run_command_on_shards",
        "run_command_on_placements", "master_get_table_ddl_events",
        "citus_backend_gpid", "citus_coordinator_nodeid",
        "create_time_partitions", "drop_old_time_partitions",
        "time_partitions", "citus_stat_pool", "citus_megabatch_stats",
        "citus_shard_move_stats", "citus_remote_stats",
        "citus_add_tenant_quota", "citus_remove_tenant_quota",
        "citus_tenant_quotas", "citus_isolate_tenant_to_node",
        "citus_add_priority_class", "citus_priority_classes",
        "citus_activate_node_metadata", "citus_sync_metadata",
        "citus_extensions",
        "citus_domains", "citus_collations", "citus_publications",
        "citus_statistics_objects",
        "citus_stat_history", "citus_health_events",
        "citus_device_memory",
        "citus_shard_load", "citus_rebalance_plan", "citus_autopilot_log",
        "citus_create_rollup", "citus_drop_rollup",
        "citus_refresh_rollups", "citus_rollups",
    }

    def parse_select_or_utility(self) -> A.Statement:
        save = self.i
        self.expect_kw("select")
        t = self.peek()
        if (t.kind == "ident" and t.value in self._UTILITY_FNS
                and self.peek(1).kind == "op" and self.peek(1).value == "("):
            self.next()
            self.expect_op("(")
            args = []
            if not self.at_op(")"):
                while True:
                    args.append(self.parse_utility_arg())
                    if not self.accept_op(","):
                        break
            self.expect_op(")")
            return A.UtilityCall(t.value, args)
        self.i = save
        return self.parse_select()

    def parse_utility_arg(self):
        t = self.next()
        if t.kind == "op" and t.value == "-":
            nt = self.next()
            if nt.kind != "num":
                self.error("expected number after '-'")
            return -(int(nt.value) if "." not in nt.value else float(nt.value))
        if t.kind == "str":
            return t.value[1:-1].replace("''", "'")
        if t.kind == "num":
            return int(t.value) if "." not in t.value else float(t.value)
        if t.kind == "kw" and t.value in ("true", "false"):
            return t.value == "true"
        if t.kind == "ident" and self.at_op("="):  # named arg: name => ignored
            self.error("named utility arguments not supported")
        if t.kind == "ident":
            return t.value
        self.error("bad utility argument")

    def parse_select(self):
        """select_core (UNION|INTERSECT|EXCEPT [ALL] select_core)*
        [ORDER BY ...] [LIMIT ...] [OFFSET ...] — INTERSECT binds
        tighter, as in PostgreSQL; trailing ORDER BY/LIMIT bind to the
        whole set operation.  Returns A.Select or A.SetOp."""
        node = self._parse_setop_union()
        order_by, limit, offset = self._parse_order_limit()
        if order_by or limit is not None or offset is not None:
            if node.order_by or node.limit is not None or node.offset is not None:
                self.error("ORDER BY/LIMIT may only follow the last SELECT "
                           "of a set operation")
            node.order_by = order_by
            node.limit = limit
            node.offset = offset
        return node

    def _parse_setop_union(self):
        left = self._parse_setop_intersect()
        while self.at_kw("union", "except"):
            op = self.next().value
            all_ = bool(self.accept_kw("all"))
            self.accept_kw("distinct")
            right = self._parse_setop_intersect()
            left = A.SetOp(op, all_, left, right)
        return left

    def _parse_setop_intersect(self):
        left = self._parse_select_core()
        while self.accept_kw("intersect"):
            all_ = bool(self.accept_kw("all"))
            self.accept_kw("distinct")
            right = self._parse_select_core()
            left = A.SetOp("intersect", all_, left, right)
        return left

    def _parse_order_limit(self):
        order_by: list[A.OrderItem] = []
        if self.accept_kw("order"):
            self.expect_kw("by")
            while True:
                e = self.parse_expr()
                asc = True
                if self.accept_kw("asc"):
                    pass
                elif self.accept_kw("desc"):
                    asc = False
                nulls_first = None
                if self.accept_kw("nulls"):
                    if self.accept_kw("first"):
                        nulls_first = True
                    else:
                        self.expect_kw("last")
                        nulls_first = False
                order_by.append(A.OrderItem(e, asc, nulls_first))
                if not self.accept_op(","):
                    break
        limit = offset = None
        if self.accept_kw("limit"):
            t = self.next()
            if t.kind != "num":
                self.error("expected number after LIMIT")
            limit = int(t.value)
        if self.accept_kw("offset"):
            t = self.next()
            if t.kind != "num":
                self.error("expected number after OFFSET")
            offset = int(t.value)
        return order_by, limit, offset

    def _parse_select_core(self):
        if self.at_op("("):
            # parenthesized select / set operation as an operand
            save = self.i
            self.next()
            if self.at_kw("select", "with") or self.at_op("("):
                node = self.parse_select()
                self.expect_op(")")
                return node
            self.i = save
            self.error("expected SELECT")
        self.expect_kw("select")
        distinct = bool(self.accept_kw("distinct"))
        distinct_on: tuple = ()
        if distinct and self.accept_kw("on"):
            # SELECT DISTINCT ON (expr, ...): first row per key
            self.expect_op("(")
            on_list = [self.parse_expr()]
            while self.accept_op(","):
                on_list.append(self.parse_expr())
            self.expect_op(")")
            distinct_on = tuple(on_list)
            distinct = False
        items = []
        while True:
            if self.at_op("*"):
                self.next()
                items.append(A.SelectItem(A.Star()))
            else:
                e = self.parse_expr()
                alias = None
                if self.accept_kw("as"):
                    alias = self.expect_ident()
                elif self.peek().kind == "ident":
                    alias = self.expect_ident()
                items.append(A.SelectItem(e, alias))
            if not self.accept_op(","):
                break
        from_ = None
        if self.accept_kw("from"):
            from_ = self.parse_from()
        where = None
        if self.accept_kw("where"):
            where = self.parse_expr()
        group_by: list[A.Expr] = []
        if self.accept_kw("group"):
            self.expect_kw("by")
            spec = self._maybe_grouping_sets()
            if spec is not None:
                group_by = [spec]
            else:
                while True:
                    group_by.append(self.parse_expr())
                    if not self.accept_op(","):
                        break
        having = None
        if self.accept_kw("having"):
            having = self.parse_expr()
        windows = []
        if self.accept_kw("window"):
            # WINDOW w AS (spec) [, w2 AS (spec)]: named windows for
            # OVER w / OVER (w ...) references
            while True:
                wname = self.expect_ident()
                self.expect_kw("as")
                part, order, frame, base = self._parse_window_spec()
                windows.append((wname, A.WindowCall(
                    None, part, order, frame, ref_name=base)))
                if not self.accept_op(","):
                    break
        return A.Select(items, from_, where, group_by, having, [],
                        None, None, distinct, tuple(windows), distinct_on)

    def parse_from(self):
        left = self.parse_table_ref()
        while True:
            if self.accept_kw("cross"):
                self.expect_kw("join")
                right = self.parse_table_ref()
                left = A.Join(left, right, "cross", None)
                continue
            kind = None
            if self.accept_kw("join") or self.accept_kw("inner"):
                if self.peek(-1).value == "inner":
                    self.expect_kw("join")
                kind = "inner"
            elif self.at_kw("left", "right", "full"):
                kind = self.next().value
                self.accept_kw("outer")
                self.expect_kw("join")
            if kind is None:
                if self.accept_op(","):  # comma join = cross join
                    right = self.parse_table_ref()
                    left = A.Join(left, right, "cross", None)
                    continue
                break
            right = self.parse_table_ref()
            self.expect_kw("on")
            cond = self.parse_expr()
            left = A.Join(left, right, kind, cond)
        return left

    def parse_table_ref(self):
        if self.at_op("("):
            # derived table: FROM (SELECT ...) [AS] alias
            self.next()
            sel = self.parse_select()
            self.expect_op(")")
            self.accept_kw("as")
            if self.peek().kind != "ident":
                self.error("derived table requires an alias")
            alias = self.expect_ident()
            return A.SubqueryRef(sel, alias)
        if self.peek().kind == "ident" and self.peek(1).kind == "op" \
                and self.peek(1).value == "(":
            # set-returning function: FROM generate_series(1, 10) g
            fname = self.expect_ident()
            self.expect_op("(")
            args = []
            if not self.at_op(")"):
                while True:
                    args.append(self.parse_expr())
                    if not self.accept_op(","):
                        break
            self.expect_op(")")
            alias = None
            if self.accept_kw("as"):
                alias = self.expect_ident()
            elif self.peek().kind == "ident":
                alias = self.expect_ident()
            return A.FunctionRef(fname, tuple(args), alias)
        name = self.parse_table_name()
        alias = None
        if self.accept_kw("as"):
            alias = self.expect_ident()
        elif self.peek().kind == "ident":
            alias = self.expect_ident()
        return A.TableRef(name, alias)

    # ---- expressions: precedence climbing ------------------------------
    def parse_expr(self) -> A.Expr:
        return self.parse_or()

    def parse_or(self) -> A.Expr:
        left = self.parse_and()
        while self.accept_kw("or"):
            left = A.BinOp("or", left, self.parse_and())
        return left

    def parse_and(self) -> A.Expr:
        left = self.parse_not()
        while self.accept_kw("and"):
            left = A.BinOp("and", left, self.parse_not())
        return left

    def parse_not(self) -> A.Expr:
        if self.accept_kw("not"):
            return A.UnOp("not", self.parse_not())
        return self.parse_comparison()

    def parse_comparison(self) -> A.Expr:
        left = self.parse_additive()
        while True:
            if self.at_op("=", "<>", "!=", "<", "<=", ">", ">="):
                op = self.next().value
                if op == "!=":
                    op = "<>"
                left = A.BinOp(op, left, self.parse_additive())
                continue
            negated = False
            save = self.i
            if self.accept_kw("not"):
                if self.at_kw("between", "in", "like", "ilike"):
                    negated = True
                else:
                    self.i = save
                    break
            if self.accept_kw("between"):
                lo = self.parse_additive()
                self.expect_kw("and")
                hi = self.parse_additive()
                left = A.Between(left, lo, hi, negated)
                continue
            if self.accept_kw("in"):
                self.expect_op("(")
                if self.at_kw("select"):
                    sub = self.parse_select()
                    self.expect_op(")")
                    left = A.InList(left, (A.Subquery(sub),), negated)
                    continue
                items = []
                while True:
                    items.append(self.parse_additive())
                    if not self.accept_op(","):
                        break
                self.expect_op(")")
                left = A.InList(left, tuple(items), negated)
                continue
            if self.at_kw("like", "ilike"):
                fname = self.next().value
                pattern = self.parse_additive()
                left = A.FuncCall(fname, (left, pattern))
                if negated:
                    left = A.UnOp("not", left)
                continue
            if self.accept_kw("is"):
                neg = bool(self.accept_kw("not"))
                if self.accept_kw("distinct"):
                    self.expect_kw("from")
                    right = self.parse_additive()
                    # null-safe equality: never yields NULL
                    same = A.BinOp(
                        "or",
                        A.BinOp("and", A.IsNull(left), A.IsNull(right)),
                        A.BinOp("and",
                                A.BinOp("and", A.IsNull(left, True),
                                        A.IsNull(right, True)),
                                A.BinOp("=", left, right)))
                    left = same if neg else A.UnOp("not", same)
                    continue
                self.expect_kw("null")
                left = A.IsNull(left, neg)
                continue
            break
        return left

    def parse_additive(self) -> A.Expr:
        left = self.parse_multiplicative()
        while self.at_op("+", "-"):
            op = self.next().value
            left = A.BinOp(op, left, self.parse_multiplicative())
        return left

    def parse_multiplicative(self) -> A.Expr:
        left = self.parse_unary()
        while self.at_op("*", "/", "%"):
            op = self.next().value
            left = A.BinOp(op, left, self.parse_unary())
        return left

    def parse_unary(self) -> A.Expr:
        if self.at_op("-"):
            self.next()
            return A.UnOp("-", self.parse_unary())
        if self.at_op("+"):
            self.next()
            return self.parse_unary()
        return self.parse_postfix()

    def parse_postfix(self) -> A.Expr:
        e = self.parse_primary()
        while self.accept_op("::"):
            tname, targs = self.parse_type_name()
            e = A.Cast(e, tname, tuple(targs))
        return e

    def parse_case(self) -> A.Expr:
        self.expect_kw("case")
        operand = None
        if not self.at_kw("when"):
            # simple CASE: CASE x WHEN v THEN ... desugars to the
            # searched form CASE WHEN x = v THEN ...
            operand = self.parse_expr()
        whens = []
        while self.accept_kw("when"):
            cond = self.parse_expr()
            if operand is not None:
                cond = A.BinOp("=", operand, cond)
            self.expect_kw("then")
            whens.append((cond, self.parse_expr()))
        else_ = None
        if self.accept_kw("else"):
            else_ = self.parse_expr()
        self.expect_kw("end")
        return A.CaseExpr(tuple(whens), else_)

    def parse_primary(self) -> A.Expr:
        t = self.peek()
        if t.kind == "num":
            self.next()
            if "." in t.value or "e" in t.value.lower():
                if "e" in t.value.lower():
                    return A.Literal(float(t.value), "float")
                return A.Literal(decimal.Decimal(t.value), "decimal")
            return A.Literal(int(t.value), "int")
        if t.kind == "str":
            self.next()
            return A.Literal(t.value[1:-1].replace("''", "'"), "string")
        if t.kind == "kw":
            if t.value in ("true", "false"):
                self.next()
                return A.Literal(t.value == "true", "bool")
            if t.value == "null":
                self.next()
                return A.Literal(None, "null")
            if t.value == "case":
                return self.parse_case()
            if t.value == "cast":
                self.next()
                self.expect_op("(")
                e = self.parse_expr()
                self.expect_kw("as")
                tname, targs = self.parse_type_name()
                self.expect_op(")")
                return A.Cast(e, tname, tuple(targs))
            if t.value == "not":
                self.next()
                return A.UnOp("not", self.parse_comparison())
            if t.value == "exists":
                self.next()
                self.expect_op("(")
                sel = self.parse_select()
                self.expect_op(")")
                return A.Exists(sel)
            if t.value in ("left", "right") and self.peek(1).kind == "op" \
                    and self.peek(1).value == "(":
                # left()/right() string functions share spellings with the
                # join keywords; the call parenthesis disambiguates
                self.next()
                self.expect_op("(")
                args = [self.parse_expr()]
                while self.accept_op(","):
                    args.append(self.parse_expr())
                self.expect_op(")")
                return A.FuncCall(t.value, tuple(args))
        if t.kind == "param":
            self.next()
            return A.Param(int(t.value[1:]))
        if t.kind == "op" and t.value == "(":
            self.next()
            if self.at_kw("select"):
                sub = self.parse_select()
                self.expect_op(")")
                return A.Subquery(sub)
            e = self.parse_expr()
            self.expect_op(")")
            return e
        if t.kind == "ident" \
                and t.value in ("date", "timestamp", "timestamptz",
                                "time", "uuid", "bytea") \
                and self.peek(1).kind == "str":
            # typed literal: date '1998-12-01' / uuid 'a0ee...' / ...
            tname = t.value
            self.next()
            lit = self.next()
            return A.Cast(A.Literal(lit.value[1:-1], "string"), tname, ())
        if t.kind == "ident" and t.value == "interval" \
                and self.peek(1).kind == "str":
            self.next()
            body = self.next().value[1:-1].strip()
            # optional trailing unit token: INTERVAL '90' day
            unit = None
            if self.peek().kind == "ident" and self.peek().value in _IVL_UNITS:
                unit = self.next().value
            return _parse_interval(body, unit, self.error)
        if t.kind == "ident" and t.value == "array" \
                and self.peek(1).kind == "op" and self.peek(1).value == "[":
            # ARRAY[e1, e2, ...] literal (1-D, literal elements)
            self.next()
            self.next()
            items = []
            if not (self.peek().kind == "op" and self.peek().value == "]"):
                while True:
                    e = self.parse_expr()
                    v = _const_literal_value(e)
                    if v is _NOT_CONST:
                        self.error("ARRAY elements must be literals")
                    items.append(v)
                    if not self.accept_op(","):
                        break
            self.expect_op("]")
            return A.Literal(items, "array")
        if t.kind == "ident" and t.value in ("current_date",
                                             "current_timestamp"):
            self.next()
            return A.FuncCall(t.value, ())
        if t.kind == "ident" and t.value == "position" and \
                self.peek(1).kind == "op" and self.peek(1).value == "(":
            # position(substring IN string) -> strpos(string, substring)
            self.next()
            self.expect_op("(")
            sub = self.parse_additive()
            self.expect_kw("in")
            s = self.parse_expr()
            self.expect_op(")")
            return A.FuncCall("strpos", (s, sub))
        if t.kind == "ident" and t.value == "extract" and \
                self.peek(1).kind == "op" and self.peek(1).value == "(":
            self.next()
            self.expect_op("(")
            ft = self.next()
            if ft.kind not in ("ident", "kw"):
                self.error("expected EXTRACT field")
            self.expect_kw("from")
            inner = self.parse_expr()
            self.expect_op(")")
            return A.FuncCall("extract", (A.Literal(ft.value, "string"), inner))
        if t.kind == "ident":
            self.next()
            if self.at_op("("):  # function call
                self.next()
                distinct = bool(self.accept_kw("distinct"))
                args: list[A.Expr] = []
                if self.at_op("*"):
                    self.next()
                    args.append(A.Star())
                elif not self.at_op(")"):
                    while True:
                        args.append(self.parse_expr())
                        if not self.accept_op(","):
                            break
                agg_order = []
                if self.accept_kw("order"):
                    # ordered aggregate: fn(args ORDER BY expr [DESC], ...)
                    self.expect_kw("by")
                    while True:
                        oe = self.parse_expr()
                        asc = True
                        if self.accept_kw("asc"):
                            pass
                        elif self.accept_kw("desc"):
                            asc = False
                        agg_order.append((oe, asc))
                        if not self.accept_op(","):
                            break
                self.expect_op(")")
                fc = A.FuncCall(t.value, tuple(args), distinct, tuple(agg_order))
                if self.at_kw("within"):
                    # ordered-set aggregate: percentile_cont(f) WITHIN
                    # GROUP (ORDER BY x) desugars to fn(f, x)
                    self.next()
                    self.expect_kw("group")
                    self.expect_op("(")
                    self.expect_kw("order")
                    self.expect_kw("by")
                    sort_expr = self.parse_expr()
                    if self.accept_kw("desc"):
                        self.error("WITHIN GROUP (ORDER BY ... DESC) is not "
                                   "supported; use 1 - fraction")
                    self.accept_kw("asc")
                    self.expect_op(")")
                    fc = A.FuncCall(t.value, tuple(args) + (sort_expr,), distinct)
                if self.peek().kind == "ident" and self.peek().value == "filter" \
                        and self.peek(1).kind == "op" and self.peek(1).value == "(":
                    # agg(...) FILTER (WHERE cond) [OVER ...]
                    self.next()
                    self.expect_op("(")
                    self.expect_kw("where")
                    cond = self.parse_expr()
                    self.expect_op(")")
                    fc = dataclasses.replace(fc, filter=cond)
                if self.at_kw("over"):
                    self.next()
                    if self.peek().kind == "ident":
                        # OVER w: use the named window verbatim
                        return A.WindowCall(fc, ref_name=self.expect_ident(),
                                            ref_verbatim=True)
                    part, order, frame, base = self._parse_window_spec()
                    return A.WindowCall(fc, part, order, frame, ref_name=base)
                return fc
            if self.accept_op("."):
                col = self.expect_ident()
                return A.ColumnRef(col, table=t.value)
            return A.ColumnRef(t.value)
        self.error("expected expression")


_IVL_UNITS = {
    "year": ("months", 12), "years": ("months", 12),
    "month": ("months", 1), "months": ("months", 1),
    "week": ("days", 7), "weeks": ("days", 7),
    "day": ("days", 1), "days": ("days", 1),
    "hour": ("micros", 3_600_000_000), "hours": ("micros", 3_600_000_000),
    "minute": ("micros", 60_000_000), "minutes": ("micros", 60_000_000),
    "second": ("micros", 1_000_000), "seconds": ("micros", 1_000_000),
}


_NOT_CONST = object()


def _const_literal_value(e):
    """Literal (or negated numeric literal) -> Python value, else
    _NOT_CONST."""
    if isinstance(e, A.Literal):
        return e.value
    if isinstance(e, A.UnOp) and e.op == "-" \
            and isinstance(e.operand, A.Literal) \
            and isinstance(e.operand.value, (int, float)):
        return -e.operand.value
    return _NOT_CONST


def _parse_interval(body: str, unit, error) -> A.IntervalLiteral:
    """'90' + unit, or PostgreSQL's verbose form '1 year 2 days'."""
    parts = {"months": 0, "days": 0, "micros": 0}
    toks = body.split()
    if unit is not None:
        try:
            qty = int(body)
        except ValueError:
            error(f"bad interval quantity {body!r}")
        field, mult = _IVL_UNITS[unit]
        parts[field] += qty * mult
        return A.IntervalLiteral(**parts)
    if len(toks) == 1:
        # bare number means days? PostgreSQL: seconds for interval-only;
        # analytics usage virtually always writes a unit — require one
        error("interval requires a unit (e.g. interval '90 days')")
    i = 0
    while i < len(toks):
        try:
            qty = int(toks[i])
        except ValueError:
            error(f"bad interval {body!r}")
        if i + 1 >= len(toks) or toks[i + 1].lower() not in _IVL_UNITS:
            error(f"bad interval {body!r}")
        field, mult = _IVL_UNITS[toks[i + 1].lower()]
        parts[field] += qty * mult
        i += 2
    return A.IntervalLiteral(**parts)


def parse_sql(text: str) -> list[A.Statement]:
    return Parser(text).parse_statements()


def parse_statement(text: str) -> A.Statement:
    stmts = parse_sql(text)
    if len(stmts) != 1:
        raise SqlSyntaxError(f"expected exactly one statement, got {len(stmts)}")
    return stmts[0]
