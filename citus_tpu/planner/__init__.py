"""SQL planner stack.

Mirrors the reference's layered planner (src/backend/distributed/planner/,
see planner/README.md there): parse -> analyze/bind -> logical plan ->
worker/combine aggregate split (multi_logical_optimizer.c) -> physical
distributed plan (shard pruning + per-shard task list).  The output is a
DistributedPlan consumed by citus_tpu.executor.
"""

from citus_tpu.planner.parser import parse_sql, parse_statement
from citus_tpu.planner import ast_nodes as ast

__all__ = ["parse_sql", "parse_statement", "ast"]
