"""Join planning.

The reference supports three physical join strategies for distributed
relations (src/backend/distributed/planner/ — query_pushdown_planning.c,
multi_join_order.c, multi_physical_planner.c MapMergeJob):

1. *colocated* joins — equality on distribution columns within one
   colocation group: each shard joins locally with its colocated peers.
2. *broadcast* joins — reference/local tables are replicated, so any
   relation can join against them shard-locally.
3. *repartition* joins — equality on non-distribution columns: both
   sides are re-hashed on the join key (MapMergeJob / all_to_all).

This planner classifies a left-deep join tree into those strategies and
pushes single-relation WHERE conjuncts down to each scan (with chunk
pruning intervals), mirroring the reference's qual pushdown.  When
colocation cannot be proven, the executor falls back to a repartitioned
or pull-to-coordinator join — same degradation ladder as the reference.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from citus_tpu import types as T
from citus_tpu.catalog import Catalog, TableMeta
from citus_tpu.errors import AnalysisError, UnsupportedFeatureError
from citus_tpu.planner import ast_nodes as A
from citus_tpu.planner.bind import AggSpec, Binder, _contains_agg, _default_name
from citus_tpu.planner.bound import (
    BBinOp, BColumn, BExpr, BKeyRef, BLiteral, walk,
)
from citus_tpu.planner.physical import (
    AggExtract, PartialOp, extract_intervals, lower_aggregates,
)
from citus_tpu.storage.reader import Interval


@dataclass
class RelPlan:
    """Per-relation scan spec."""
    alias: str
    table: TableMeta
    columns: list[str] = field(default_factory=list)   # unqualified
    filter: Optional[BExpr] = None                     # single-rel conjuncts
    intervals: list[Interval] = field(default_factory=list)


@dataclass
class JoinStep:
    right_alias: str
    kind: str                                   # inner | left | right | full | cross
    left_keys: list[BExpr] = field(default_factory=list)
    right_keys: list[BExpr] = field(default_factory=list)
    residual: Optional[BExpr] = None            # non-equi ON conjuncts


@dataclass
class BoundJoinSelect:
    rels: list[tuple[str, TableMeta]]
    rel_plans: dict[str, RelPlan]
    steps: list[JoinStep]
    post_filter: Optional[BExpr]                # cross-rel WHERE conjuncts
    group_keys: list[BExpr]
    aggs: list[AggSpec]
    final_exprs: list[BExpr]
    output_names: list[str]
    having: Optional[BExpr]
    order_by: list[tuple[int, bool, Optional[bool]]]
    limit: Optional[int]
    offset: Optional[int]
    distinct: bool
    agg_args: list[BExpr] = field(default_factory=list)
    partial_ops: list[PartialOp] = field(default_factory=list)
    agg_extract: list[AggExtract] = field(default_factory=list)
    strategy: str = "colocated"                 # colocated | repartition | pull
    # for repartition: (left_alias, right_alias, left_keys, right_keys)
    # of the step connecting the two distributed relations
    repartition_spec: Optional[tuple] = None
    binder: Optional[Binder] = None
    hidden_outputs: int = 0

    @property
    def has_aggs(self) -> bool:
        return bool(self.aggs) or bool(self.group_keys)


def _flatten_joins(item) -> tuple[list[A.TableRef], list[tuple[A.TableRef, str, Optional[A.Expr]]]]:
    """Left-deep join tree -> (base rel, [(right rel, kind, on-cond)...])."""
    if isinstance(item, A.TableRef):
        return [item], []
    if isinstance(item, A.Join):
        refs, steps = _flatten_joins(item.left)
        if not isinstance(item.right, A.TableRef):
            raise UnsupportedFeatureError("right-nested joins are not supported")
        steps.append((item.right, item.kind, item.condition))
        refs.append(item.right)
        return refs, steps
    raise AnalysisError("bad FROM item")


def _rel_of(e: BExpr, qualified: bool) -> Optional[str]:
    """The single relation alias an expression references, or None."""
    aliases = set()
    for n in walk(e):
        if isinstance(n, BColumn):
            aliases.add(n.name.split(".", 1)[0] if qualified and "." in n.name else n.name)
    if not qualified:
        return None
    return aliases.pop() if len(aliases) == 1 else None


def _conjuncts(e: Optional[BExpr]) -> list[BExpr]:
    if e is None:
        return []
    if isinstance(e, BBinOp) and e.op == "and":
        return _conjuncts(e.left) + _conjuncts(e.right)
    return [e]


def _and_all(parts: list[BExpr]) -> Optional[BExpr]:
    out = None
    for p in parts:
        out = p if out is None else BBinOp("and", out, p, T.BOOL_T)
    return out


def bind_join_select(catalog: Catalog, stmt: A.Select) -> BoundJoinSelect:
    refs, raw_steps = _flatten_joins(stmt.from_)
    rels: list[tuple[str, TableMeta]] = []
    seen = set()
    for r in refs:
        alias = r.alias or r.name
        if alias in seen:
            raise AnalysisError(f"duplicate relation alias {alias!r}")
        seen.add(alias)
        rels.append((alias, catalog.table(r.name)))
    binder = Binder(catalog, rels[0][1], rels=rels)

    def rel_alias_of_col(e: BExpr) -> Optional[str]:
        return _rel_of(e, binder.qualified)

    # ---- join steps: split ON into equi-pairs and residual ------------
    joined: list[str] = [rels[0][0]]
    steps: list[JoinStep] = []
    for (r, kind, cond) in raw_steps:
        alias = r.alias or r.name
        step = JoinStep(right_alias=alias, kind=kind)
        residual = []
        if cond is not None:
            for c in _conjuncts(binder.bind_scalar(cond)):
                ok = False
                if isinstance(c, BBinOp) and c.op == "=":
                    la, ra = rel_alias_of_col(c.left), rel_alias_of_col(c.right)
                    if la == alias and ra in joined:
                        step.left_keys.append(c.right)
                        step.right_keys.append(c.left)
                        ok = True
                    elif ra == alias and la in joined:
                        step.left_keys.append(c.left)
                        step.right_keys.append(c.right)
                        ok = True
                if not ok:
                    residual.append(c)
        if residual:
            if kind != "inner":
                raise UnsupportedFeatureError(
                    "non-equi ON conditions on outer joins are not supported yet")
            step.residual = _and_all(residual)
        if kind != "cross" and not step.left_keys and step.residual is None:
            raise AnalysisError("JOIN requires an ON condition")
        steps.append(step)
        joined.append(alias)

    # ---- WHERE: push single-relation conjuncts to scans ----------------
    where = binder.bind_scalar(stmt.where) if stmt.where is not None else None
    rel_plans = {alias: RelPlan(alias, t) for alias, t in rels}
    cross_conjuncts: list[BExpr] = []
    outer_right = {s.right_alias for s in steps if s.kind in ("left", "full")}
    left_of_right_join = set()
    for s in steps:
        if s.kind in ("right", "full"):
            left_of_right_join.update(a for a in joined if a != s.right_alias)
    for c in _conjuncts(where):
        alias = rel_alias_of_col(c)
        # pushing a filter below an outer join's null-supplying side would
        # change semantics; keep those conjuncts post-join
        if alias is not None and alias not in outer_right and alias not in left_of_right_join:
            rp = rel_plans[alias]
            rp.filter = c if rp.filter is None else BBinOp("and", rp.filter, c, T.BOOL_T)
        else:
            cross_conjuncts.append(c)
    post_filter = _and_all(cross_conjuncts)
    for rp in rel_plans.values():
        # intervals operate on unqualified column names within the relation
        rp.intervals = [Interval(c.column.split(".", 1)[-1], c.lo, c.hi,
                                 c.lo_inclusive, c.hi_inclusive)
                        for c in extract_intervals(rp.filter)]

    # ---- outputs / aggregates ------------------------------------------
    items: list[A.SelectItem] = []
    for item in stmt.items:
        if isinstance(item.expr, A.Star):
            for alias, t in rels:
                for col in t.schema:
                    items.append(A.SelectItem(A.ColumnRef(col.name, table=alias), col.name))
        else:
            items.append(item)

    group_keys = [binder.bind_scalar(g) for g in stmt.group_by]
    key_map = {k: i for i, k in enumerate(group_keys)}
    binder._ast_key_map = {}
    binder._ast_key_types = [k.type for k in group_keys]
    for i, g in enumerate(stmt.group_by):
        try:
            binder._ast_key_map.setdefault(g, i)
        except TypeError:
            pass
    has_aggs = any(_contains_agg(i.expr) for i in items) or stmt.having is not None or bool(group_keys)

    aggs: list[AggSpec] = []
    final_exprs: list[BExpr] = []
    output_names: list[str] = []
    having = None
    if has_aggs:
        for i, item in enumerate(items):
            final_exprs.append(binder.bind_select_expr(item.expr, key_map, aggs))
            output_names.append(item.alias or _default_name(item.expr, i))
        if stmt.having is not None:
            having = binder.bind_select_expr(stmt.having, key_map, aggs)
    else:
        for i, item in enumerate(items):
            final_exprs.append(binder.bind_scalar(item.expr))
            output_names.append(item.alias or _default_name(item.expr, i))

    order_by = []
    hidden = 0
    for oi in stmt.order_by:
        try:
            idx = _resolve_order(oi.expr, items, output_names, binder,
                                 final_exprs, key_map, aggs)
        except AnalysisError:
            if stmt.distinct:
                raise
            bound_e = binder.bind_select_expr(oi.expr, key_map, aggs)                 if has_aggs else binder.bind_scalar(oi.expr)
            final_exprs.append(bound_e)
            output_names.append(f"__order_{hidden}")
            idx = len(final_exprs) - 1
            hidden += 1
        order_by.append((idx, oi.ascending, oi.nulls_first))

    # enum ORDER BY keys sort by declaration rank (enumsortorder) — same
    # redirect as bind_select's: hidden rank column, functionally
    # dependent on the enum value
    from citus_tpu.planner.bound import BDictLookup, BKeyRef
    for oi_pos, (idx, asc, nf) in enumerate(order_by):
        e_b = final_exprs[idx]
        under = e_b
        if isinstance(e_b, BKeyRef) and group_keys:
            under = group_keys[e_b.index]
        if not (isinstance(under, BColumn) and under.type.is_text):
            continue
        info = binder.enum_info(under)
        if info is None:
            continue
        final_exprs.append(BDictLookup(e_b, binder.enum_rank_lut(info)))
        output_names.append(f"__order_{hidden}")
        order_by[oi_pos] = (len(final_exprs) - 1, asc, nf)
        hidden += 1

    agg_args, partial_ops, agg_extract = lower_aggregates(aggs)

    # ---- column requirements per relation ------------------------------
    def note_columns(e: Optional[BExpr]):
        if e is None:
            return
        for n in walk(e):
            if isinstance(n, BColumn):
                if binder.qualified and "." in n.name:
                    alias, col = n.name.split(".", 1)
                else:
                    # resolve bare name (only possible when unambiguous)
                    _, c, alias, _t = binder.resolve_column(n.name)
                    col = c.name
                rp = rel_plans[alias]
                if col not in rp.columns:
                    rp.columns.append(col)

    for rp in rel_plans.values():
        note_columns(rp.filter)
    note_columns(post_filter)
    for s in steps:
        for e in s.left_keys + s.right_keys:
            note_columns(e)
        note_columns(s.residual)
    for e in group_keys + agg_args:
        note_columns(e)
    if not has_aggs:
        for e in final_exprs:
            note_columns(e)
    if having is not None:
        note_columns(having)
    # (hidden ORDER BY columns were appended to final_exprs above and are
    # covered by the loop when not aggregating; grouped hidden outputs
    # reference keys/aggs already noted)

    bj = BoundJoinSelect(
        rels=rels, rel_plans=rel_plans, steps=steps, post_filter=post_filter,
        group_keys=group_keys, aggs=aggs, final_exprs=final_exprs,
        output_names=output_names, having=having, order_by=order_by,
        limit=stmt.limit, offset=stmt.offset, distinct=stmt.distinct,
        agg_args=agg_args, partial_ops=partial_ops, agg_extract=agg_extract,
        binder=binder, hidden_outputs=hidden,
    )
    bj.strategy = _choose_strategy(bj)
    return bj


def _resolve_order(e: A.Expr, items, names, binder, final_exprs, key_map, aggs) -> int:
    if isinstance(e, A.Literal) and isinstance(e.value, int):
        idx = e.value - 1
        if not (0 <= idx < len(items)):
            raise AnalysisError(f"ORDER BY position {e.value} out of range")
        return idx
    if isinstance(e, A.ColumnRef) and e.table is None and e.name in names:
        return names.index(e.name)
    for i, item in enumerate(items):
        if item.expr == e:
            return i
    # try binding and matching structurally against final exprs
    try:
        bound = binder.bind_select_expr(e, key_map, list(aggs)) if aggs or key_map \
            else binder.bind_scalar(e)
    except Exception:
        bound = None
    if bound is not None:
        for i, fe in enumerate(final_exprs):
            if fe == bound:
                return i
    raise AnalysisError("ORDER BY expression must be an output column, alias, or position")


def _dist_col_expr(alias: str, t: TableMeta, qualified: bool) -> Optional[BColumn]:
    if not t.is_distributed or t.dist_column is None:
        return None
    col = t.schema.column(t.dist_column)
    name = f"{alias}.{col.name}" if qualified else col.name
    return BColumn(name, col.type)


def _choose_strategy(bj: BoundJoinSelect) -> str:
    """colocated: every distributed relation is equi-joined on its
    distribution column to an already-aligned distributed relation in the
    same colocation group (reference/local relations are replicated and
    always alignable).  Otherwise: pull (repartition on the coordinator).
    """
    qualified = bj.binder.qualified
    dist_rels = [(a, t) for a, t in bj.rels if t.is_distributed]
    if not dist_rels:
        return "colocated"  # everything replicated/local: single task
    anchor_alias, anchor = dist_rels[0]
    aligned = {anchor_alias}
    # iterate to fixpoint over join steps
    changed = True
    while changed:
        changed = False
        for s in bj.steps:
            t_right = dict(bj.rels)[s.right_alias]
            if not t_right.is_distributed or s.right_alias in aligned:
                continue
            rd = _dist_col_expr(s.right_alias, t_right, qualified)
            for lk, rk in zip(s.left_keys, s.right_keys):
                other = None
                if rk == rd:
                    other = lk
                elif lk == rd:
                    other = rk
                if other is None:
                    continue
                oa = _rel_of(other, qualified)
                if oa is None or oa not in aligned:
                    continue
                t_other = dict(bj.rels)[oa]
                od = _dist_col_expr(oa, t_other, qualified)
                if od is not None and other == od and \
                        t_other.colocation_id == t_right.colocation_id and \
                        t_other.shard_count == t_right.shard_count:
                    aligned.add(s.right_alias)
                    changed = True
    if all(a in aligned for a, t in dist_rels):
        return "colocated"
    spec = _repartition_spec(bj)
    if spec is not None:
        bj.repartition_spec = spec
        return "repartition"
    if any(s.left_keys for s in bj.steps):
        # general case: step-wise shuffle DAG (each equi step partitions
        # both sides on its keys, joins per bucket) — always correct,
        # bounds each join's working set; repartition_spec stays None
        return "repartition"
    return "pull"


def _repartition_spec(bj: BoundJoinSelect) -> Optional[tuple]:
    """Eligibility for the hash-repartition (all_to_all) join — the
    analog of the reference's single-repartition MapMergeJob
    (multi_physical_planner.h:160): exactly two distributed relations,
    connected by an equi-join step whose keys live one per side; every
    other relation replicated (reference/local) and inner-joined.  Rows
    then match only within a hash bucket, so per-bucket joins are exact
    — including an outer dist-dist step (NULL-key rows never match and
    are preserved bucket-locally).

    Returns (left_alias, right_alias, left_key_exprs, right_key_exprs)
    or None."""
    qualified = bj.binder.qualified
    dist = [(a, t) for a, t in bj.rels if t.is_distributed]
    if len(dist) != 2:
        return None
    d_aliases = {a for a, _ in dist}
    connecting = None
    for s in bj.steps:
        if s.right_alias in d_aliases and s.left_keys:
            lks, rks = [], []
            for lk, rk in zip(s.left_keys, s.right_keys):
                la, ra = _rel_of(lk, qualified), _rel_of(rk, qualified)
                if la in d_aliases and ra in d_aliases and la != ra:
                    lks.append(lk)
                    rks.append(rk)
            if lks:
                if connecting is not None:
                    return None  # two dist-dist steps: not single-repartition
                connecting = (s, lks, rks)
        elif s.right_alias in d_aliases:
            return None  # dist rel joined without usable equi keys
        elif s.kind in ("right", "full"):
            # preserved unmatched rows of a replicated right side would
            # re-appear in every bucket
            return None
    if connecting is None:
        return None
    s, lks, rks = connecting
    left_alias = _rel_of(lks[0], qualified)
    return (left_alias, s.right_alias, lks, rks)
