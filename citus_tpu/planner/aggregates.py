"""Extended-aggregate registry: the declared partial/combine interface.

Reference: arbitrary aggregates run worker-side sfuncs and a
coordinator combinefunc (utils/aggregate_utils.c:502,847
worker_partial_agg_sfunc / coord_combine_agg_sfunc).  Here every
aggregate declares three pieces and the planner/executor stay generic:

- ``bind``   — argument typing and the AggSpec (binder phase)
- ``lower``  — which combinable partial slots the worker computes
  (physical phase).  Variance-family aggregates lower to *sum/sumsq/
  count* partials, so on device they combine with the same single psum
  as plain sums — no new collectives, no executor changes.
- ``finalize`` — partial slots -> per-group (values, valid) arrays
  (coordinator combine phase)

Aggregates that need exact value multisets (percentiles, string_agg,
array_agg) declare ``needs_exact``: their partial is an order-preserving
*collect*, which forces the host grouping path — the analog of the
reference pulling rows when an aggregate has no combinefunc.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Callable, Optional

import numpy as np

from citus_tpu import types as T
from citus_tpu.errors import AnalysisError, UnsupportedFeatureError
from citus_tpu.planner.bound import BBinOp, BCast, BExpr


@dataclass
class AggDef:
    name: str
    bind: Callable          # (binder, A.FuncCall) -> AggSpec
    lower: Callable         # (spec, arg_slot, partial_slot) -> AggExtract
    finalize: Callable      # (extract, partials, cat) -> (values, valid)
    needs_exact: bool = False  # collect-based: host grouping only
    # device partial exists only for the scalar (ungrouped) shape;
    # grouped queries route through host grouping
    host_grouped: bool = False


def _as_float(e: BExpr) -> BExpr:
    if e.type.is_float:
        return e
    return BCast(e, T.FLOAT64_T)


# ------------------------------------------------------- variance family

_VAR_CANON = {
    "variance": "var_samp", "var_samp": "var_samp", "var_pop": "var_pop",
    "stddev": "stddev_samp", "stddev_samp": "stddev_samp",
    "stddev_pop": "stddev_pop",
}


def _bind_variance(binder, e):
    from citus_tpu.planner.bind import AggSpec
    if len(e.args) != 1:
        raise AnalysisError(f"{e.name}() expects one argument")
    arg = binder.bind_scalar(e.args[0])
    if not (arg.type.is_integer or arg.type.is_float or arg.type.is_decimal):
        raise AnalysisError(f"{e.name}() over {arg.type} not supported")
    if e.distinct:
        raise UnsupportedFeatureError(f"{e.name}(DISTINCT ...) not supported")
    return AggSpec(_VAR_CANON[e.name], _as_float(arg), T.FLOAT64_T)


def _lower_variance(spec, arg_slot, partial_slot):
    from citus_tpu.planner.physical import AggExtract
    ai = arg_slot(spec.arg)
    sq = arg_slot(BBinOp("*", spec.arg, spec.arg, T.FLOAT64_T))
    s = partial_slot("sum", ai, "float64")
    ss = partial_slot("sum", sq, "float64")
    c = partial_slot("count", ai, "int64")
    return AggExtract(spec.kind, [s, ss, c], spec.out_type, param=spec.param)


def _finalize_variance(ex, partials, cat):
    s = np.asarray(partials[ex.slots[0]], np.float64)
    ss = np.asarray(partials[ex.slots[1]], np.float64)
    n = np.asarray(partials[ex.slots[2]], np.float64)
    pop = ex.kind.endswith("_pop")
    min_n = 1 if pop else 2
    valid = n >= min_n
    safe_n = np.where(n > 0, n, 1)
    # numerically: E[x^2] - E[x]^2, clamped (catastrophic cancellation
    # can dip epsilon-negative); matches PostgreSQL's float8 accumulator
    mean = s / safe_n
    m2 = ss - safe_n * mean * mean
    denom = safe_n if pop else np.where(n > 1, n - 1, 1)
    var = np.maximum(m2 / denom, 0.0)
    if ex.kind.startswith("stddev"):
        var = np.sqrt(var)
    return var, valid


# ------------------------------------------------------------- booleans


def _bind_bool(binder, e):
    from citus_tpu.planner.bind import AggSpec
    if len(e.args) != 1:
        raise AnalysisError(f"{e.name}() expects one argument")
    arg = binder.bind_scalar(e.args[0])
    if arg.type.kind != T.BOOL:
        raise AnalysisError(f"{e.name}() requires a boolean argument")
    return AggSpec(e.name, BCast(arg, T.INT64_T), T.BOOL_T)


def _lower_bool(spec, arg_slot, partial_slot):
    from citus_tpu.planner.physical import AggExtract
    ai = arg_slot(spec.arg)
    kind = "min" if spec.kind == "bool_and" else "max"
    v = partial_slot(kind, ai, "int64")
    c = partial_slot("count", ai, "int64")
    return AggExtract(spec.kind, [v, c], spec.out_type)


def _finalize_bool(ex, partials, cat):
    v = np.asarray(partials[ex.slots[0]])
    c = np.asarray(partials[ex.slots[1]])
    return v.astype(bool), c > 0


# ------------------------------------------------- collect-based family


def _bind_sort_keys(binder, e):
    """ORDER BY inside an aggregate call -> (sortable BExprs, asc flags).
    Text sort keys become lexicographic-rank lookups so plain numeric
    ordering of collected tuples matches string ordering."""
    from citus_tpu.planner.bound import BDictLookup
    exprs, ascs = [], []
    for oe, asc in getattr(e, "agg_order", ()):
        b = binder.bind_scalar(oe)
        if b.type.is_text:
            # enum columns order by declaration rank (enumsortorder)
            enum_rank = binder.enum_rank(b)
            if enum_rank is not None:
                exprs.append(enum_rank)
                ascs.append(bool(asc))
                continue
            resolved = binder._text_words(b)
            if resolved is None:
                raise UnsupportedFeatureError(
                    "aggregate ORDER BY over computed text is not supported")
            base, _t, _c, eff_words = resolved
            order = sorted(range(len(eff_words)), key=eff_words.__getitem__)
            rank = [0] * len(eff_words)
            for pos, i in enumerate(order):
                rank[i] = pos
            b = BDictLookup(base, tuple(rank), T.INT64_T)
        elif not (b.type.is_numeric or b.type.kind in (T.DATE, T.TIMESTAMP,
                                                       T.BOOL)):
            raise UnsupportedFeatureError(
                f"cannot ORDER BY {b.type} inside an aggregate")
        exprs.append(b)
        ascs.append(bool(asc))
    return tuple(exprs), tuple(ascs)


def _bind_string_agg(binder, e):
    from citus_tpu.planner import ast_nodes as A
    from citus_tpu.planner.bind import AggSpec
    from citus_tpu.planner.bound import BColumn
    if len(e.args) != 2:
        raise AnalysisError("string_agg() expects (expression, delimiter)")
    arg = binder.bind_scalar(e.args[0])
    if not arg.type.is_text:
        raise AnalysisError("string_agg() requires a text argument")
    d = e.args[1]
    if not (isinstance(d, A.Literal) and isinstance(d.value, str)):
        raise AnalysisError("string_agg() delimiter must be a string literal")
    src = None
    if isinstance(arg, BColumn):
        src = binder.text_source(arg)
    else:
        from citus_tpu.planner.bound import walk
        for nd in walk(arg):
            if isinstance(nd, BColumn) and nd.type.is_text:
                src = binder.text_source(nd)
                break
    if src is None:
        raise UnsupportedFeatureError("string_agg() over computed text")
    sort_exprs, ascs = _bind_sort_keys(binder, e)
    return AggSpec("string_agg", arg, T.TEXT_T,
                   param=(d.value, src, sort_exprs, ascs))


def _lower_collect(spec, arg_slot, partial_slot):
    from citus_tpu.planner.physical import AggExtract
    ai = arg_slot(spec.arg)
    sort_exprs = spec.param[2] if isinstance(spec.param, tuple) \
        and len(spec.param) >= 4 else ()
    extra = tuple(arg_slot(e) for e in sort_exprs)
    s = partial_slot("collect", ai, "object", extra)
    return AggExtract(spec.kind, [s], spec.out_type, param=spec.param)


def _sorted_items(vals, ascs):
    """Collected (value, key...) tuples -> values in ORDER BY order
    (PG null placement: last for ASC, first for DESC)."""
    if not vals or not isinstance(vals[0], tuple):
        return list(vals)

    def sort_key(item):
        parts = []
        for k, asc in zip(item[1:], ascs):
            null = k is None
            v = 0 if null else (k if asc else -k)
            parts.append((null if asc else not null, v))
        return tuple(parts)
    return [it[0] for it in sorted(vals, key=sort_key)]


def _finalize_string_agg(ex, partials, cat):
    delim, src = ex.param[0], ex.param[1]
    ascs = ex.param[3] if len(ex.param) >= 4 else ()
    lists = np.asarray(partials[ex.slots[0]], object)
    out = np.empty(lists.shape[0], object)
    valid = np.zeros(lists.shape[0], bool)
    for i, vals in enumerate(lists):
        if vals:
            ordered = _sorted_items(vals, ascs)
            words = cat.decode_strings(src[0], src[1],
                                       [int(v) for v in ordered])
            out[i] = delim.join(w for w in words if w is not None)
            valid[i] = True
    return out, valid


def _bind_array_agg(binder, e):
    from citus_tpu.planner.bind import AggSpec
    from citus_tpu.planner.bound import BColumn
    if len(e.args) != 1:
        raise AnalysisError("array_agg() expects one argument")
    arg = binder.bind_scalar(e.args[0])
    src = None
    if arg.type.is_text and isinstance(arg, BColumn):
        src = binder.text_source(arg)
    sort_exprs, ascs = _bind_sort_keys(binder, e)
    return AggSpec("array_agg", arg, arg.type,
                   param=("array", src, sort_exprs, ascs))


def _finalize_array_agg(ex, partials, cat):
    src = ex.param[1]
    ascs = ex.param[3] if len(ex.param) >= 4 else ()
    lists = np.asarray(partials[ex.slots[0]], object)
    out = np.empty(lists.shape[0], object)
    valid = np.zeros(lists.shape[0], bool)
    for i, vals in enumerate(lists):
        if vals:
            ordered = _sorted_items(vals, ascs)
            if src is not None:
                out[i] = cat.decode_strings(src[0], src[1],
                                            [int(v) for v in ordered])
            else:
                out[i] = [ex.out_type.from_physical(v) for v in ordered]
            valid[i] = True
    return out, valid


def _percentile_fraction(e) -> float:
    """Validate fn(frac) WITHIN GROUP desugar: two args, numeric literal
    fraction in [0, 1]."""
    import decimal
    from citus_tpu.planner import ast_nodes as A
    if len(e.args) != 2:
        raise AnalysisError(f"{e.name}() requires WITHIN GROUP (ORDER BY ...)")
    f = e.args[0]
    if not (isinstance(f, A.Literal)
            and isinstance(f.value, (int, float, decimal.Decimal))):
        raise AnalysisError(f"{e.name}() fraction must be a numeric literal")
    frac = float(f.value)
    if not (0.0 <= frac <= 1.0):
        raise AnalysisError("percentile fraction must be in [0, 1]")
    return frac


def _bind_percentile(binder, e):
    """percentile_cont(frac) WITHIN GROUP (ORDER BY x) arrives desugared
    as FuncCall(name, (frac_literal, x))."""
    from citus_tpu.planner.bind import AggSpec
    frac = _percentile_fraction(e)
    arg = binder.bind_scalar(e.args[1])
    if arg.type.is_text:
        raise UnsupportedFeatureError(f"{e.name}() over text not supported")
    out = T.FLOAT64_T if e.name == "percentile_cont" else arg.type
    return AggSpec(e.name, arg, out, param=frac)


def _finalize_percentile(ex, partials, cat):
    frac = ex.param
    lists = np.asarray(partials[ex.slots[0]], object)
    out = np.empty(lists.shape[0], object)
    valid = np.zeros(lists.shape[0], bool)
    cont = ex.kind == "percentile_cont"
    for i, vals in enumerate(lists):
        if not vals:
            continue
        v = np.sort(np.asarray(vals, np.float64 if cont else None))
        if cont:
            pos = frac * (len(v) - 1)
            lo = int(math.floor(pos))
            hi = min(lo + 1, len(v) - 1)
            out[i] = float(v[lo] + (pos - lo) * (v[hi] - v[lo]))
        else:
            # discrete: first value whose cumulative fraction >= frac
            idx = int(math.ceil(frac * len(v))) - 1 if frac > 0 else 0
            out[i] = v[max(0, min(idx, len(v) - 1))]
        valid[i] = True
    return out, valid


# -------------------------------------------------- min/max over text


def bind_text_minmax(binder, kind: str, arg):
    """min()/max() over a text expression: aggregate the lexicographic
    RANK of each word (combinable int64 min/max — still one collective),
    map the winning rank back to its word at finalize.  Built here
    because the builtin min/max branch rejects text."""
    from citus_tpu.planner.bind import AggSpec
    from citus_tpu.planner.bound import BDictLookup
    resolved = binder._text_words(arg)
    if resolved is None:
        raise UnsupportedFeatureError(
            f"{kind}() over computed text is not supported")
    base, _tname, _cname, eff_words = resolved
    order = sorted(range(len(eff_words)), key=eff_words.__getitem__)
    rank = [0] * len(eff_words)
    for pos, i in enumerate(order):
        rank[i] = pos
    sorted_words = tuple(eff_words[i] for i in order)
    ranked = BDictLookup(base, tuple(rank), T.INT64_T)
    return AggSpec(f"{kind}_text", ranked, T.TEXT_T, param=sorted_words)


def _lower_text_minmax(spec, arg_slot, partial_slot):
    from citus_tpu.planner.physical import AggExtract
    ai = arg_slot(spec.arg)
    kind = "min" if spec.kind == "min_text" else "max"
    s = partial_slot(kind, ai, "int64")
    c = partial_slot("count", ai, "int64")
    return AggExtract(spec.kind, [s, c], spec.out_type, param=spec.param)


def _finalize_text_minmax(ex, partials, cat):
    ranks = np.asarray(partials[ex.slots[0]])
    c = np.asarray(partials[ex.slots[1]])
    words = ex.param
    out = np.empty(ranks.shape[0], object)
    valid = c > 0
    for i, r in enumerate(ranks):
        if valid[i] and 0 <= int(r) < len(words):
            out[i] = words[int(r)]
    return out, valid


# ---------------------------------------- approximate distinct (HLL)

HLL_M = 128                      # registers; error ~ 1.04/sqrt(m) ≈ 9%
HLL_ALPHA = 0.7213 / (1 + 1.079 / HLL_M)


def hll_rho_buckets(xp, bits, ok):
    """int64 value bits -> (bucket [N] int32, rho [N] int32); invalid
    rows get rho 0 (neutral under max)."""
    h = bits.astype(np.uint64)
    # splitmix64 finalizer
    h = (h ^ (h >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
    h = (h ^ (h >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
    h = h ^ (h >> np.uint64(31))
    bucket = (h & np.uint64(HLL_M - 1)).astype(np.int32)
    w = h >> np.uint64(7)  # remaining 57 bits
    # rho = leading-zero count within the 57-bit window + 1
    lz = xp.zeros(w.shape, np.int32)
    x = w
    for shift in (32, 16, 8, 4, 2, 1):
        big = (x >> np.uint64(shift)) != 0
        lz = lz + xp.where(big, 0, shift).astype(np.int32)
        x = xp.where(big, x >> np.uint64(shift), x)
    lz = lz - np.int32(7)  # the window is 57 bits wide, not 64
    rho = xp.where(w == 0, np.int32(57), lz + np.int32(1))
    rho = xp.where(ok, rho, np.int32(0))
    return bucket, rho


def hll_estimate(registers: np.ndarray) -> int:
    m = float(HLL_M)
    M = np.asarray(registers, np.float64)
    E = HLL_ALPHA * m * m / float(np.sum(np.power(2.0, -M)))
    if E <= 2.5 * m:
        V = int(np.sum(M == 0))
        if V > 0:
            E = m * np.log(m / V)
    return int(round(E))


def _bind_approx_distinct(binder, e):
    from citus_tpu.planner.bind import AggSpec
    if len(e.args) != 1:
        raise AnalysisError("approx_count_distinct() expects one argument")
    arg = binder.bind_scalar(e.args[0])
    return AggSpec("approx_count_distinct", arg, T.INT64_T)


def _lower_approx_distinct(spec, arg_slot, partial_slot):
    from citus_tpu.planner.physical import AggExtract
    ai = arg_slot(spec.arg)
    s = partial_slot("hll", ai, "int32")
    return AggExtract("approx_count_distinct", [s], spec.out_type)


def _finalize_approx_distinct(ex, partials, cat):
    regs = np.asarray(partials[ex.slots[0]])
    if regs.ndim == 1:          # scalar query: one register vector
        regs = regs[None, :]
    out = np.array([hll_estimate(r) for r in regs], np.int64)
    return out, np.ones(out.shape, bool)


# ---------------------------------- approximate percentiles (DDSketch)
#
# The reference pushes percentile computation down via the t-digest
# extension (planner/tdigest_extension.c:250): workers build sketches,
# the coordinator combines them.  A t-digest's variable-size centroid
# list is a poor fit for fixed-shape device code; the TPU-native
# equivalent is a DDSketch-style log-bucketed histogram: a FIXED vector
# of bucket counts per group, built with the same one-hot segment-sum
# the other aggregates use, and combined across shards with one psum —
# identical machinery to a plain sum partial, just vector-valued.
# Relative value error is bounded by the bucket width (~2.7% here).

DDSK_HALF = 1024                      # buckets per sign
DDSK_M = 2 * DDSK_HALF                # neg 0..1022 | zero 1023 | pos 1024..
DDSK_LOG_MIN = float(np.log(1e-12))   # smallest resolved magnitude
DDSK_LNG = float(np.log(1e24)) / DDSK_HALF  # ln(gamma): 1e-12..1e12 span


def ddsk_bucket_indexes(xp, v):
    """float values -> bucket index [N] int32 (callers mask invalid
    rows themselves)."""
    val = v.astype(np.float64)
    mag = xp.abs(val)
    li = xp.clip(
        xp.floor((xp.log(xp.maximum(mag, 1e-300)) - DDSK_LOG_MIN) / DDSK_LNG),
        0, DDSK_HALF - 1).astype(np.int32)
    neg_idx = np.int32(DDSK_HALF - 2) - xp.minimum(li, np.int32(DDSK_HALF - 2))
    pos_idx = np.int32(DDSK_HALF) + li
    return xp.where(val > 0, pos_idx,
                    xp.where(val < 0, neg_idx, np.int32(DDSK_HALF - 1)))


def ddsk_bucket_values() -> np.ndarray:
    """Representative value per bucket (geometric midpoint)."""
    j = np.arange(DDSK_M, dtype=np.float64)
    pos = np.exp(DDSK_LOG_MIN + (j - DDSK_HALF + 0.5) * DDSK_LNG)
    neg = -np.exp(DDSK_LOG_MIN + ((DDSK_HALF - 2 - j) + 0.5) * DDSK_LNG)
    vals = np.where(j >= DDSK_HALF, pos, neg)
    vals[DDSK_HALF - 1] = 0.0
    return vals


def _bind_approx_percentile(binder, e):
    """approx_percentile(frac) WITHIN GROUP (ORDER BY x): sketch-based,
    device-combinable percentile (cont-style rank selection, value
    resolved to the containing log bucket)."""
    from citus_tpu.planner.bind import AggSpec
    frac = _percentile_fraction(e)
    arg = binder.bind_scalar(e.args[1])
    if not (arg.type.is_integer or arg.type.is_float or arg.type.is_decimal):
        raise AnalysisError(f"approx_percentile() over {arg.type} "
                            "not supported")
    return AggSpec("approx_percentile", _as_float(arg), T.FLOAT64_T,
                   param=frac)


def _lower_approx_percentile(spec, arg_slot, partial_slot):
    from citus_tpu.planner.physical import AggExtract
    ai = arg_slot(spec.arg)
    s = partial_slot("ddsk", ai, "int64")
    return AggExtract("approx_percentile", [s], spec.out_type,
                      param=spec.param)


def _finalize_approx_percentile(ex, partials, cat):
    counts = np.asarray(partials[ex.slots[0]], np.int64)
    if counts.ndim == 1:
        counts = counts[None, :]
    vals = ddsk_bucket_values()
    out = np.zeros(counts.shape[0], np.float64)
    valid = np.zeros(counts.shape[0], bool)
    for g in range(counts.shape[0]):
        total = int(counts[g].sum())
        if total == 0:
            continue
        valid[g] = True
        rank = int(math.floor(ex.param * (total - 1)))
        cum = np.cumsum(counts[g])
        out[g] = vals[int(np.searchsorted(cum, rank + 1, side="left"))]
    return out, valid


# ------------------------------------------- heavy hitters (approx_top_k)
#
# Same fixed-shape recipe as HLL/DDSketch above: a hashed count-array
# sketch (a one-row count-min row) instead of a variable-size
# space-saving list.  Each value hashes (splitmix64, like the HLL
# bucketing) into one of TOPK_M count buckets; a parallel value
# register keeps the max value seen per bucket so the finalizer can
# name the heavy hitter the count belongs to.  Counts combine with the
# same psum as plain sums, registers with the same elementwise max as
# plain max partials — no new collectives.  A hash collision inflates a
# bucket's count by the colliding light value's rows; with TOPK_M
# buckets the probability a given heavy hitter shares a bucket is
# ~n_distinct/TOPK_M, the usual count-min bound.

TOPK_M = 1024                        # count buckets (power of two)
TOPK_SENTINEL = np.int64(np.iinfo(np.int64).min)  # empty value register


def topk_buckets(xp, bits):
    """int64 value bits -> bucket [N] int32 (callers mask invalid rows
    themselves)."""
    h = bits.astype(np.uint64)
    # splitmix64 finalizer (same mix as hll_rho_buckets)
    h = (h ^ (h >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
    h = (h ^ (h >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
    h = h ^ (h >> np.uint64(31))
    return (h & np.uint64(TOPK_M - 1)).astype(np.int32)


def _bind_approx_top_k(binder, e):
    from citus_tpu.planner import ast_nodes as A
    from citus_tpu.planner.bind import AggSpec
    if len(e.args) != 2:
        raise AnalysisError("approx_top_k() expects (column, k)")
    kl = e.args[1]
    if not (isinstance(kl, A.Literal) and isinstance(kl.value, int)
            and not isinstance(kl.value, bool)):
        raise AnalysisError("approx_top_k() k must be an integer literal")
    k = int(kl.value)
    if not 1 <= k <= 64:
        raise AnalysisError("approx_top_k() k must be in [1, 64]")
    arg = binder.bind_scalar(e.args[0])
    if not arg.type.is_integer:
        raise AnalysisError(f"approx_top_k() over {arg.type} not supported")
    if e.distinct:
        raise UnsupportedFeatureError("approx_top_k(DISTINCT ...) not supported")
    return AggSpec("approx_top_k", arg, T.TEXT_T, param=k)


def _lower_approx_top_k(spec, arg_slot, partial_slot):
    from citus_tpu.planner.physical import AggExtract
    ai = arg_slot(spec.arg)
    counts = partial_slot("topk", ai, "int64")
    values = partial_slot("topkv", ai, "int64")
    return AggExtract("approx_top_k", [counts, values], spec.out_type,
                      param=spec.param)


def _finalize_approx_top_k(ex, partials, cat):
    import json as _json
    counts = np.asarray(partials[ex.slots[0]], np.int64)
    values = np.asarray(partials[ex.slots[1]], np.int64)
    if counts.ndim == 1:        # scalar query: one sketch
        counts = counts[None, :]
        values = values[None, :]
    out = np.empty(counts.shape[0], object)
    valid = np.zeros(counts.shape[0], bool)
    for g in range(counts.shape[0]):
        hot = np.nonzero(counts[g] > 0)[0]
        if hot.size == 0:
            continue
        valid[g] = True
        # top-k buckets by count (value as the deterministic tiebreak)
        order = sorted(hot, key=lambda b: (-int(counts[g][b]),
                                           int(values[g][b])))
        out[g] = _json.dumps(
            [{"value": int(values[g][b]), "count": int(counts[g][b])}
             for b in order[:ex.param]])
    return out, valid


# ----------------------------------------------- DISTINCT sum/avg


def _lower_set(spec, arg_slot, partial_slot):
    from citus_tpu.planner.physical import AggExtract
    ai = arg_slot(spec.arg)
    s = partial_slot("collect_set", ai, "object")
    return AggExtract(spec.kind, [s], spec.out_type, param=spec.param)


def _finalize_set_sum_avg(ex, partials, cat):
    """sum(DISTINCT)/avg(DISTINCT) over exact value sets; physical-space
    arithmetic so decimal exactness matches the non-distinct paths
    (avg scales by 10^6 like the builtin decimal average)."""
    import decimal as _dec
    sets = np.asarray(partials[ex.slots[0]], object)
    out = np.empty(sets.shape[0], object)
    valid = np.zeros(sets.shape[0], bool)
    is_avg = ex.kind == "avg_distinct"
    is_float = ex.out_type.is_float
    for i, vals in enumerate(sets):
        if not vals:
            continue
        valid[i] = True
        if is_float:
            s = float(sum(vals))
            out[i] = s / len(vals) if is_avg else s
        else:
            s = int(sum(int(v) for v in vals))
            if is_avg:
                q = _dec.Decimal(s) * 1_000_000 / _dec.Decimal(len(vals))
                out[i] = int(q.to_integral_value(rounding=_dec.ROUND_HALF_UP))
            else:
                out[i] = s
    return out, valid


AGG_REGISTRY: dict[str, AggDef] = {}


def register(defn: AggDef) -> None:
    AGG_REGISTRY[defn.name] = defn


for _n in ("variance", "var_samp", "var_pop", "stddev", "stddev_samp",
           "stddev_pop"):
    register(AggDef(_n, _bind_variance, _lower_variance, _finalize_variance))
for _n in ("bool_and", "bool_or"):
    register(AggDef(_n, _bind_bool, _lower_bool, _finalize_bool))
register(AggDef("string_agg", _bind_string_agg, _lower_collect,
                _finalize_string_agg, needs_exact=True))
register(AggDef("array_agg", _bind_array_agg, _lower_collect,
                _finalize_array_agg, needs_exact=True))
for _n in ("percentile_cont", "percentile_disc"):
    register(AggDef(_n, _bind_percentile, _lower_collect,
                    _finalize_percentile, needs_exact=True))
for _n in ("min_text", "max_text"):
    register(AggDef(_n, None, _lower_text_minmax, _finalize_text_minmax))
for _n in ("sum_distinct", "avg_distinct"):
    register(AggDef(_n, None, _lower_set, _finalize_set_sum_avg,
                    needs_exact=True))
register(AggDef("approx_count_distinct", _bind_approx_distinct,
                _lower_approx_distinct, _finalize_approx_distinct,
                host_grouped=True))
register(AggDef("approx_percentile", _bind_approx_percentile,
                _lower_approx_percentile, _finalize_approx_percentile,
                host_grouped=True))
register(AggDef("approx_top_k", _bind_approx_top_k, _lower_approx_top_k,
                _finalize_approx_top_k, host_grouped=True))


def finalize_kind(kind: str):
    """Finalizer lookup by canonical extract kind (canonical variance
    names differ from their aliases)."""
    d = AGG_REGISTRY.get(kind)
    return d.finalize if d is not None else None
