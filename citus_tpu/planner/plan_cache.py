"""Surgical plan-cache invalidation (reference: plancache.c +
CitusTableCacheEntry invalidation via relcache callbacks).

The previous cache was a plain ``{sql_text: (bound, plan, version,
epoch, backend)}`` dict whose entries all died on ANY catalog change
(~10 wholesale ``.clear()`` sites): DDL on table A evicted table B's
plans and every kernel warm-up with them.  This module scopes
invalidation to what actually changed:

- **table identity + version**: an entry pins the exact ``TableMeta``
  object it bound against; ingest/DDL that flips the table (version
  bump or object replacement) kills only that table's entries — the
  ingest-flip window is covered because validation happens on every
  lookup, not at mutation time.
- **DDL epoch + object-state token**: ``ddl_epoch`` is bumped by ~30
  catalog mutations, most of them irrelevant to a given SELECT.  On an
  epoch mismatch the entry is re-validated against a digest of every
  catalog namespace a plan could depend on beyond its table — views,
  roles AND grants (REVOKE must force a re-bind so privilege checks
  re-run), functions, enum types, row policies/RLS, triggers,
  text-search configs.  Token equal -> the epoch churn was elsewhere
  (another table's DDL, a sequence bump) and the entry is re-armed.
- **LRU bound** so ad-hoc text keys can't grow without limit.

``invalidate_table(name)`` is the targeted kill used by DML/DDL
handlers that know their table; ``clear()`` stays available as
``invalidate_all`` for multi-table transaction ends and foreign catalog
pushes.  Counters: plan_cache_invalidations / plan_cache_evictions
(hits/misses are bumped by the callers that know whether a statement
was cacheable at all).
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from dataclasses import dataclass
from typing import Optional

#: ad-hoc SQL texts are unbounded; cap the entry count (each entry is a
#: bound tree + physical plan, small next to the kernels they point at)
DEFAULT_CAPACITY = 1024

#: catalog namespaces beyond the entry's own table that can change plan
#: output or its authorization; sequences are deliberately absent
#: (nextval bumps them constantly and no SELECT plan reads them)
_TOKEN_SECTIONS = ("schemas", "views", "roles", "grants", "functions",
                   "types", "enum_columns", "policies", "rls", "triggers",
                   "ts_configs")


def _counters():
    from citus_tpu.executor.executor import GLOBAL_COUNTERS
    return GLOBAL_COUNTERS


def object_state_token(catalog) -> int:
    """Order-insensitive digest of the non-table catalog namespaces; two
    equal tokens mean no mutation in any section between them.  Table
    *topology* (which tables exist, partition parentage) rides along —
    attaching a partition must kill cached parent-query plans whose
    partition fan-out was baked in at bind time — but per-table state
    (version, indexes) does not: that is covered entry-locally, so
    ingest into table B cannot disturb table A's entries."""
    topology = sorted(
        (name, t.partition_of["parent"] if t.partition_of else None)
        for name, t in catalog.tables.items())
    return hash((repr(topology),)
                + tuple(repr(sorted(getattr(catalog, s, {}).items(),
                                    key=lambda kv: repr(kv[0])))
                        for s in _TOKEN_SECTIONS))


@dataclass
class PlanEntry:
    bound: object
    plan: object
    version: int
    epoch: int
    backend: str
    table_name: str
    obj_token: int
    #: auto-parameterized literal values (planner/auto_param.py); None
    #: for explicitly-parameterized or literal-free plans
    values: Optional[list] = None

    def __getitem__(self, i):
        # legacy tuple shape (bound, plan, version, epoch, backend) —
        # tests and tooling index entries positionally
        return (self.bound, self.plan, self.version, self.epoch,
                self.backend)[i]


class PlanCache:
    """LRU of PlanEntry with per-lookup validation.  Dict-compatible on
    the read side (get/[]/in/len) so existing introspection keeps
    working; mutation goes through put/invalidate_*."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        self._mu = threading.RLock()
        self._e: OrderedDict = OrderedDict()
        self.capacity = capacity

    def lookup(self, key, catalog, backend: str) -> Optional[PlanEntry]:
        with self._mu:
            e = self._e.get(key)
            if e is None:
                return None
            if e.backend != backend:
                return None  # stale backend: overwritten by the next put
            if (catalog.tables.get(e.table_name) is not e.bound.table
                    or e.bound.table.version != e.version):
                del self._e[key]
                _counters().bump("plan_cache_invalidations")
                return None
            if e.epoch != catalog.ddl_epoch:
                tok = object_state_token(catalog)
                if tok != e.obj_token:
                    del self._e[key]
                    _counters().bump("plan_cache_invalidations")
                    return None
                e.epoch = catalog.ddl_epoch  # churn was elsewhere: re-arm
            self._e.move_to_end(key)
            return e

    def put(self, key, bound, plan, catalog, backend: str,
            values: Optional[list] = None) -> PlanEntry:
        e = PlanEntry(bound, plan, bound.table.version, catalog.ddl_epoch,
                      backend, bound.table.name,
                      object_state_token(catalog), values)
        with self._mu:
            self._e[key] = e
            self._e.move_to_end(key)
            while len(self._e) > max(1, self.capacity):
                self._e.popitem(last=False)
                _counters().bump("plan_cache_evictions")
        return e

    def invalidate_table(self, name: str) -> None:
        with self._mu:
            dead = [k for k, e in self._e.items() if e.table_name == name]
            for k in dead:
                del self._e[k]
        if dead:
            _counters().bump("plan_cache_invalidations", len(dead))

    def invalidate_all(self) -> None:
        with self._mu:
            n = len(self._e)
            self._e.clear()
        if n:
            _counters().bump("plan_cache_invalidations", n)

    def clear(self) -> None:
        # legacy spelling at multi-table sites (transaction rollback,
        # foreign catalog push): everything really is suspect there
        self.invalidate_all()

    # ---- dict-compatible read side ----

    def get(self, key, default=None):
        with self._mu:
            return self._e.get(key, default)

    def __getitem__(self, key):
        with self._mu:
            return self._e[key]

    def __contains__(self, key) -> bool:
        with self._mu:
            return key in self._e

    def __len__(self) -> int:
        with self._mu:
            return len(self._e)
