"""Bound (typed) expressions and their compilation to array evaluators.

The binder turns parser AST into this typed tree; the executor compiles it
once per plan into a function over column arrays.  The same compiled form
runs on both backends — ``numpy`` (host oracle / small local paths, the
analog of the reference's row-at-a-time qual evaluation) and ``jax.numpy``
inside a jitted kernel (the TPU path).  SQL three-valued logic is carried
explicitly: every evaluation returns ``(values, valid)`` where ``valid``
is the not-null mask; predicates treat NULL as false at the filter
boundary, matching PostgreSQL semantics.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional

import numpy as np

from citus_tpu import types as T
from citus_tpu.errors import AnalysisError

# ---------------------------------------------------------------- nodes


class BExpr:
    type: T.ColumnType


@dataclass(frozen=True)
class BColumn(BExpr):
    name: str
    type: T.ColumnType


@dataclass(frozen=True)
class BLiteral(BExpr):
    """Physical-encoded constant (None = SQL NULL)."""
    value: Any
    type: T.ColumnType


@dataclass(frozen=True)
class BParam(BExpr):
    """Deferred $N parameter (reference: Job->deferredPruning /
    fast-path prepared statements).  Compiles to an env lookup of a 0-d
    runtime array — kernels jitted once serve every parameter value."""
    index: int  # 0-based
    type: T.ColumnType
    # "" = the whole value (uuid: its high lane); types.UUID_LANE_SUFFIX
    # = the low int64 lane of a uuid parameter
    lane: str = ""

    @property
    def env_name(self) -> str:
        return f"__param_{self.index}{self.lane}"


@dataclass(frozen=True)
class BBinOp(BExpr):
    op: str  # + - * / % = <> < <= > >= and or
    left: BExpr
    right: BExpr
    type: T.ColumnType


@dataclass(frozen=True)
class BUnOp(BExpr):
    op: str  # not | -
    operand: BExpr
    type: T.ColumnType


@dataclass(frozen=True)
class BScale(BExpr):
    """Multiply by 10**power — decimal scale alignment."""
    operand: BExpr
    power: int
    type: T.ColumnType


@dataclass(frozen=True)
class BCast(BExpr):
    operand: BExpr
    type: T.ColumnType


@dataclass(frozen=True)
class BIsNull(BExpr):
    operand: BExpr
    negated: bool
    type: T.ColumnType = T.BOOL_T


@dataclass(frozen=True)
class BCase(BExpr):
    whens: tuple[tuple[BExpr, BExpr], ...]
    else_: Optional[BExpr]
    type: T.ColumnType


@dataclass(frozen=True)
class BDictRemap(BExpr):
    """Re-encode a dictionary-id column into another relation's dictionary
    id space (cross-relation text equality/joins stay integer-valued).
    ``mapping[id]`` is the target id, or -1 when the string is absent."""
    operand: BExpr
    mapping: tuple[int, ...]
    type: T.ColumnType = T.TEXT_T


@dataclass(frozen=True)
class BDictLookup(BExpr):
    """Per-dictionary-id lookup table -> numeric value (e.g. length(s):
    the table holds each word's length, the device just gathers)."""
    operand: BExpr
    table: tuple
    type: T.ColumnType = T.INT64_T


@dataclass(frozen=True)
class BDictMask(BExpr):
    """Membership of a dictionary-encoded column in a precomputed id set
    (LIKE / IN over text evaluate the pattern against the table-global
    dictionary at bind time; the device just gathers a bool table)."""
    operand: BExpr            # int32 dictionary ids
    mask: tuple[bool, ...]    # mask[id] -> matches
    type: T.ColumnType = T.BOOL_T


@dataclass(frozen=True)
class BMathFunc(BExpr):
    """Scalar math function lowered to elementwise xp ops (reference:
    float8/numeric math in PostgreSQL's float.c / numeric.c; domain
    violations — sqrt of a negative, log of a non-positive — yield SQL
    NULL rather than a device-side error, since a traced kernel cannot
    raise data-dependent errors).

    ``param`` carries bind-time constants (digit count for round/trunc
    over decimals, the operand's decimal scale) so compilation stays
    shape-static."""
    name: str
    operands: tuple[BExpr, ...]
    type: T.ColumnType
    param: object = None


@dataclass(frozen=True)
class BAggRef(BExpr):
    """Reference to aggregate slot ``index`` in the combine/final phase."""
    index: int
    type: T.ColumnType


@dataclass(frozen=True)
class BKeyRef(BExpr):
    """Reference to GROUP BY key ``index`` in the combine/final phase."""
    index: int
    type: T.ColumnType


@dataclass(frozen=True)
class BDateTrunc(BExpr):
    """date_trunc to a fixed-width unit (device-computable on the physical
    day/microsecond encodings)."""
    unit: str  # hour | minute | day | week
    operand: BExpr
    type: T.ColumnType


@dataclass(frozen=True)
class BExtract(BExpr):
    """EXTRACT(field FROM date/timestamp) — vectorized proleptic-Gregorian
    calendar math on the integer day/microsecond encodings (no table
    lookups, fully jittable)."""
    field: str  # year | month | day | dow | doy | hour | minute | second | epoch
    operand: BExpr
    type: T.ColumnType = T.INT64_T


@dataclass(frozen=True)
class BAddMonths(BExpr):
    """date/timestamp + N months: civil month addition with day-of-month
    clamping (PostgreSQL timestamp_pl_interval semantics), vectorized on
    the integer day/microsecond encodings."""
    operand: BExpr
    months: int
    type: T.ColumnType


def py_add_interval(value, months: int, days: int, micros: int):
    """Python-side interval addition for constant folding (value is a
    datetime.date or datetime.datetime)."""
    import datetime as _dt
    d = value
    if months:
        is_date = not isinstance(d, _dt.datetime)
        y, m = d.year, d.month - 1 + months
        y += m // 12
        m = m % 12 + 1
        if m == 12:
            last = 31
        else:
            last = ((_dt.date(y, m + 1, 1) if m < 12
                     else _dt.date(y + 1, 1, 1))
                    - _dt.date(y, m, 1)).days
        day = min(d.day, last)
        d = d.replace(year=y, month=m, day=day) if not is_date \
            else _dt.date(y, m, day)
    if days:
        d = d + _dt.timedelta(days=days)
    if micros:
        if not isinstance(d, _dt.datetime):
            d = _dt.datetime(d.year, d.month, d.day)
        d = d + _dt.timedelta(microseconds=micros)
    return d


@dataclass(frozen=True)
class BDateTruncCivil(BExpr):
    """date_trunc to a calendar unit (month/quarter/year) — needs civil
    date math rather than fixed-width division."""
    unit: str  # month | quarter | year
    operand: BExpr
    type: T.ColumnType


def civil_from_days(xp, z):
    """days-since-1970 -> (year, month, day); Hinnant's algorithm with
    floor divisions kept positive via the era offset."""
    z = z.astype(np.int64) + 719468
    era = z // 146097
    doe = z - era * 146097
    yoe = (doe - doe // 1460 + doe // 36524 - doe // 146096) // 365
    y = yoe + era * 400
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)
    mp = (5 * doy + 2) // 153
    d = doy - (153 * mp + 2) // 5 + 1
    m = mp + xp.where(mp < 10, 3, -9)
    y = y + (m <= 2)
    return y, m, d


def days_from_civil(xp, y, m, d):
    y = y - (m <= 2)
    era = y // 400
    yoe = y - era * 400
    doy = (153 * (m + xp.where(m > 2, -3, 9)) + 2) // 5 + d - 1
    doe = yoe * 365 + yoe // 4 - yoe // 100 + doy
    return era * 146097 + doe - 719468


def walk(e: BExpr):
    yield e
    if isinstance(e, BBinOp):
        yield from walk(e.left)
        yield from walk(e.right)
    elif isinstance(e, (BUnOp, BScale, BCast, BIsNull, BDictMask, BDictRemap,
                        BDictLookup, BExtract, BDateTrunc, BDateTruncCivil,
                        BAddMonths)):
        yield from walk(e.operand)
    elif isinstance(e, BMathFunc):
        for o in e.operands:
            yield from walk(o)
    elif isinstance(e, BCase):
        for c, v in e.whens:
            yield from walk(c)
            yield from walk(v)
        if e.else_ is not None:
            yield from walk(e.else_)


def referenced_columns(e: BExpr) -> list[str]:
    return sorted({n.name for n in walk(e) if isinstance(n, BColumn)})


def param_env_names(param_specs) -> list[str]:
    """Worker env names for plan parameters, in positional order; a uuid
    parameter contributes its low int64 lane right after its high lane
    (matching BParam.env_name for both lanes)."""
    out: list[str] = []
    for i, spec in enumerate(param_specs):
        out.append(f"__param_{i}")
        if spec[0].kind == T.UUID:
            out.append(f"__param_{i}{T.UUID_LANE_SUFFIX}")
    return out


# ---------------------------------------------------------- compilation


def _trunc_div(xp, a, b):
    """SQL integer division truncates toward zero (numpy/jnp floor_divide
    rounds toward -inf, so do it on magnitudes)."""
    sign = xp.sign(a) * xp.sign(b)
    q = xp.abs(a) // xp.abs(xp.where(b == 0, 1, b))
    return sign * q


def compile_expr(e: BExpr, xp):
    """BExpr -> fn(env) -> (values, valid). ``env`` maps column name ->
    (values, valid) arrays; '__aggs__' -> list of (values, valid) for
    BAggRef. ``xp`` is numpy or jax.numpy."""
    if isinstance(e, BColumn):
        name = e.name
        return lambda env: env[name]
    if isinstance(e, BLiteral):
        if e.value is None:
            zero = e.type.device_dtype.type(0)
            return lambda env: (zero, False)
        val = e.type.device_dtype.type(e.value)
        return lambda env: (val, True)
    if isinstance(e, BParam):
        name = e.env_name
        return lambda env: env[name]
    if isinstance(e, BAggRef):
        idx = e.index
        return lambda env: env["__aggs__"][idx]
    if isinstance(e, BKeyRef):
        idx = e.index
        return lambda env: env["__keys__"][idx]
    if isinstance(e, BExtract):
        f = compile_expr(e.operand, xp)
        field = e.field
        is_ts = e.operand.type.kind in (T.TIMESTAMP, T.TIMESTAMPTZ)
        US_DAY = np.int64(86_400_000_000)

        def run_extract(env):
            v, valid = f(env)
            v = xp.asarray(v)
            if is_ts:
                days = v // US_DAY
                rem = v - days * US_DAY
            else:
                days = v.astype(np.int64)
                rem = None
            if field == "epoch":
                out = v.astype(np.int64) // 1_000_000 if is_ts \
                    else days * 86_400
                return (out, valid)
            if field in ("hour", "minute", "second"):
                if rem is None:
                    return (xp.zeros_like(days), valid)
                if field == "hour":
                    return (rem // 3_600_000_000, valid)
                if field == "minute":
                    return (rem // 60_000_000 % 60, valid)
                return (rem // 1_000_000 % 60, valid)
            if field == "dow":  # 0=Sunday like PostgreSQL
                return ((days + 4) % 7, valid)
            y, m, d = civil_from_days(xp, days)
            if field == "year":
                return (y, valid)
            if field == "month":
                return (m, valid)
            if field == "quarter":
                return ((m - 1) // 3 + 1, valid)
            if field == "day":
                return (d, valid)
            if field == "doy":
                jan1 = days_from_civil(xp, y, xp.ones_like(m), xp.ones_like(d))
                return (days - jan1 + 1, valid)
            raise AnalysisError(f"EXTRACT field {field!r} not supported")
        return run_extract
    if isinstance(e, BAddMonths):
        f = compile_expr(e.operand, xp)
        months = int(e.months)
        is_ts = e.operand.type.kind in (T.TIMESTAMP, T.TIMESTAMPTZ)
        US_DAY = np.int64(86_400_000_000)

        def run_add_months(env):
            v, valid = f(env)
            v = xp.asarray(v)
            if is_ts:
                days = v // US_DAY
                rem = v - days * US_DAY
            else:
                days = v.astype(np.int64)
                rem = None
            y, m, d = civil_from_days(xp, days)
            mt = (m - 1) + months
            y = y + mt // 12
            m = mt % 12 + 1
            # clamp to the target month's length (PostgreSQL semantics:
            # Jan 31 + 1 month = Feb 28/29)
            nm_y = y + (m == 12)
            nm_m = xp.where(m == 12, 1, m + 1)
            month_len = days_from_civil(xp, nm_y, nm_m, xp.ones_like(d)) \
                - days_from_civil(xp, y, m, xp.ones_like(d))
            d = xp.minimum(d, month_len)
            out_days = days_from_civil(xp, y, m, d)
            if is_ts:
                return (out_days * US_DAY + rem, valid)
            return (out_days.astype(np.int32), valid)
        return run_add_months
    if isinstance(e, BDateTruncCivil):
        f = compile_expr(e.operand, xp)
        unit = e.unit
        is_ts = e.operand.type.kind in (T.TIMESTAMP, T.TIMESTAMPTZ)
        US_DAY = np.int64(86_400_000_000)

        def run_trunc_civil(env):
            v, valid = f(env)
            v = xp.asarray(v)
            days = (v // US_DAY) if is_ts else v.astype(np.int64)
            y, m, d = civil_from_days(xp, days)
            if unit == "year":
                m = xp.ones_like(m)
            elif unit == "quarter":
                m = ((m - 1) // 3) * 3 + 1
            else:  # month
                pass
            out_days = days_from_civil(xp, y, m, xp.ones_like(d))
            if is_ts:
                return (out_days * US_DAY, valid)
            return (out_days.astype(np.int32), valid)
        return run_trunc_civil
    if isinstance(e, BDateTrunc):
        f = compile_expr(e.operand, xp)
        if e.operand.type.kind == T.DATE:
            units = {"day": 1, "week": 7}
            if e.unit not in units:
                raise AnalysisError(f"date_trunc({e.unit!r}) on date not supported")
            step = np.int32(units[e.unit])
            # epoch day 0 = Thursday; ISO weeks start Monday (epoch day -3)
            off = np.int32(3 if e.unit == "week" else 0)
            return lambda env: ((lambda v: (((v[0] + off) // step) * step - off, v[1]))(f(env)))
        units = {"minute": 60_000_000, "hour": 3_600_000_000,
                 "day": 86_400_000_000, "week": 7 * 86_400_000_000}
        if e.unit not in units:
            raise AnalysisError(f"date_trunc({e.unit!r}) not supported")
        step = np.int64(units[e.unit])
        off = np.int64(3 * 86_400_000_000 if e.unit == "week" else 0)
        return lambda env: ((lambda v: (((v[0] + off) // step) * step - off, v[1]))(f(env)))
    if isinstance(e, BScale):
        f = compile_expr(e.operand, xp)
        factor = e.type.device_dtype.type(10 ** e.power)
        return lambda env: ((lambda v: (v[0] * factor, v[1]))(f(env)))
    if isinstance(e, BCast):
        f = compile_expr(e.operand, xp)
        src, dst = e.operand.type, e.type
        dt = dst.device_dtype
        if src.is_decimal and dst.is_decimal:
            diff = dst.scale - src.scale
            if diff >= 0:
                factor = dt.type(10 ** diff)
                return lambda env: ((lambda v: (v[0].astype(dt) * factor, v[1]))(f(env)))
            factor = dt.type(10 ** (-diff))
            return lambda env: ((lambda v: (_trunc_div(xp, v[0], factor).astype(dt), v[1]))(f(env)))
        if src.is_decimal and dst.is_float:
            scale = 10.0 ** src.scale
            return lambda env: ((lambda v: ((v[0] / scale).astype(dt), v[1]))(f(env)))
        if dst.is_decimal and not src.is_decimal:
            factor = 10 ** dst.scale
            if src.is_float:
                return lambda env: ((lambda v: (xp.round(v[0] * factor).astype(dt), v[1]))(f(env)))
            return lambda env: ((lambda v: (v[0].astype(dt) * dt.type(factor), v[1]))(f(env)))
        if src.is_decimal and dst.is_integer:
            factor = np.int64(10 ** src.scale)
            return lambda env: ((lambda v: (_trunc_div(xp, v[0], factor).astype(dt), v[1]))(f(env)))
        return lambda env: ((lambda v: (v[0].astype(dt), v[1]))(f(env)))
    if isinstance(e, BIsNull):
        f = compile_expr(e.operand, xp)
        neg = e.negated

        def run_isnull(env):
            _, valid = f(env)
            if valid is True or valid is False:
                out = valid if neg else not valid
                return (np.bool_(out), True)
            v = valid if neg else ~valid
            return (v, True)
        return run_isnull
    if isinstance(e, BDictRemap):
        f = compile_expr(e.operand, xp)
        mapping = xp.asarray(np.array(e.mapping, dtype=np.int32)) if e.mapping \
            else xp.asarray(np.zeros(1, np.int32) - 1)

        def run_remap(env):
            ids, valid = f(env)
            n = mapping.shape[0]
            safe = xp.clip(ids, 0, max(n - 1, 0))
            return (mapping[safe], valid)
        return run_remap
    if isinstance(e, BDictLookup):
        f = compile_expr(e.operand, xp)
        table = xp.asarray(np.array(e.table, dtype=np.int64)) if e.table \
            else xp.zeros(1, np.int64)

        def run_dictlookup(env):
            ids, valid = f(env)
            n = table.shape[0]
            safe = xp.clip(ids, 0, max(n - 1, 0))
            return (table[safe], valid)
        return run_dictlookup
    if isinstance(e, BDictMask):
        f = compile_expr(e.operand, xp)
        table = xp.asarray(np.array(e.mask, dtype=bool))

        def run_dictmask(env):
            ids, valid = f(env)
            n = table.shape[0]
            safe = xp.clip(ids, 0, max(n - 1, 0))
            return (table[safe] if n else xp.zeros_like(ids, dtype=bool), valid)
        return run_dictmask
    if isinstance(e, BMathFunc):
        return _compile_math(e, xp)
    if isinstance(e, BUnOp):
        f = compile_expr(e.operand, xp)
        if e.op == "-":
            return lambda env: ((lambda v: (-v[0], v[1]))(f(env)))
        if e.op == "not":
            # three-valued NOT: NULL stays NULL (valid mask unchanged)
            return lambda env: ((lambda v: (~v[0] if v[0].dtype == bool else v[0] == 0, v[1]))(f(env)))
        raise AnalysisError(f"unknown unary op {e.op}")
    if isinstance(e, BCase):
        conds = [(compile_expr(c, xp), compile_expr(v, xp)) for c, v in e.whens]
        felse = compile_expr(e.else_, xp) if e.else_ is not None else None
        dt = e.type.device_dtype

        def run_case(env):
            result = None
            valid = None
            taken = None
            for fc, fv in conds:
                cv, cvalid = fc(env)
                vv, vvalid = fv(env)
                # NULL condition = branch not taken (SQL CASE semantics)
                cond = _as_bool(xp, cv) & _as_mask(xp, cvalid, cv)
                vv = xp.asarray(vv).astype(dt)
                if result is None:
                    result = xp.where(cond, vv, dt.type(0))
                    valid = xp.where(cond, _as_mask(xp, vvalid, vv), False)
                    taken = cond
                else:
                    take = cond & ~taken
                    result = xp.where(take, vv, result)
                    valid = xp.where(take, _as_mask(xp, vvalid, vv), valid)
                    taken = taken | cond
            if felse is not None:
                ev, evalid = felse(env)
                ev = xp.asarray(ev).astype(dt)
                result = xp.where(taken, result, ev)
                valid = xp.where(taken, valid, _as_mask(xp, evalid, ev))
            else:
                valid = valid & taken
            return (result, valid)
        return run_case
    if isinstance(e, BBinOp):
        fl = compile_expr(e.left, xp)
        fr = compile_expr(e.right, xp)
        op = e.op
        if op in ("and", "or"):
            def run_logic(env):
                lv, lvalid = fl(env)
                rv, rvalid = fr(env)
                lv = _as_bool(xp, lv)
                rv = _as_bool(xp, rv)
                lval = _as_mask(xp, lvalid, lv)
                rval = _as_mask(xp, rvalid, rv)
                if op == "and":
                    # three-valued: NULL AND false = false, NULL AND true = NULL
                    value = lv & rv
                    valid = (lval & rval) | (lval & ~lv) | (rval & ~rv)
                else:
                    # NULL OR true = true, NULL OR false = NULL
                    value = lv | rv
                    valid = (lval & rval) | (lval & lv) | (rval & rv)
                return (value, valid)
            return run_logic
        if op in ("=", "<>", "<", "<=", ">", ">="):
            fn = {"=": lambda a, b: a == b, "<>": lambda a, b: a != b,
                  "<": lambda a, b: a < b, "<=": lambda a, b: a <= b,
                  ">": lambda a, b: a > b, ">=": lambda a, b: a >= b}[op]
            return lambda env: _binary(xp, fl, fr, env, fn)
        dt = e.type.device_dtype
        if op == "+":
            return lambda env: _binary(xp, fl, fr, env, lambda a, b: (a + b).astype(dt))
        if op == "-":
            return lambda env: _binary(xp, fl, fr, env, lambda a, b: (a - b).astype(dt))
        if op == "*":
            return lambda env: _binary(xp, fl, fr, env, lambda a, b: (a * b).astype(dt))
        if op == "/":
            if e.type.is_float:
                return lambda env: _binary(xp, fl, fr, env,
                                           lambda a, b: (a / xp.where(b == 0, 1, b)).astype(dt),
                                           null_if=lambda a, b: b == 0)
            return lambda env: _binary(xp, fl, fr, env,
                                       lambda a, b: _trunc_div(xp, a, b).astype(dt),
                                       null_if=lambda a, b: b == 0)
        if op == "%":
            return lambda env: _binary(xp, fl, fr, env,
                                       lambda a, b: (a - _trunc_div(xp, a, b) * b).astype(dt),
                                       null_if=lambda a, b: b == 0)
        raise AnalysisError(f"unknown operator {op}")
    raise AnalysisError(f"cannot compile {type(e).__name__}")


def _compile_math(e, xp):
    name = e.name
    fs = [compile_expr(o, xp) for o in e.operands]
    dt = e.type.device_dtype

    if name in ("sqrt", "exp", "ln", "log10", "log2"):
        f = fs[0]
        fn = {"sqrt": lambda x: xp.sqrt(x), "exp": lambda x: xp.exp(x),
              "ln": lambda x: xp.log(x), "log10": lambda x: xp.log10(x),
              "log2": lambda x: xp.log2(x)}[name]
        # domain violations -> NULL (PostgreSQL raises; a traced kernel
        # can't, and NULL matches the sqlite oracle)
        if name == "exp":
            dom = None
        elif name == "sqrt":
            dom = lambda x: x >= 0  # noqa: E731
        else:
            dom = lambda x: x > 0  # noqa: E731

        def run_unary(env):
            v, valid = f(env)
            v = xp.asarray(v).astype(np.float64)
            if dom is None:
                return (fn(v), valid)
            ok = dom(v)
            out = fn(xp.where(ok, v, 1.0))
            return (out, _as_mask(xp, valid, out) & ok)
        return run_unary
    if name == "power":
        fa, fb = fs

        def run_power(env):
            a, avalid = fa(env)
            b, bvalid = fb(env)
            a = xp.asarray(a).astype(np.float64)
            b = xp.asarray(b).astype(np.float64)
            # 0^negative and negative^non-integer are domain errors
            ok = ~((a == 0) & (b < 0)) & ~((a < 0) & (b != xp.floor(b)))
            out = xp.power(xp.where(ok, a, 1.0), xp.where(ok, b, 1.0))
            valid = _as_mask(xp, avalid, out) & _as_mask(xp, bvalid, out) & ok
            return (out, valid)
        return run_power
    if name in ("floor", "ceil", "round", "trunc"):
        f = fs[0]
        src_scale, digits = e.param  # operand decimal scale, round digits
        if e.operands[0].type.is_float:
            # round(double precision) breaks ties to even in PostgreSQL
            # (xp.round is half-to-even); half-away-from-zero applies
            # only to the numeric/decimal path below.
            fn = {"floor": xp.floor, "ceil": xp.ceil,
                  "round": xp.round,
                  "trunc": xp.trunc}[name]
            if digits:
                factor = np.float64(10.0 ** digits)
                return lambda env: ((lambda v: (fn(v[0] * factor) / factor,
                                                v[1]))(f(env)))
            return lambda env: ((lambda v: (fn(v[0]), v[1]))(f(env)))
        # decimal (scaled int64) path: exact integer arithmetic.  The
        # binder only emits this node when digits < operand scale
        # (digits >= scale is an exact rescale handled at bind time).
        drop = src_scale - max(digits, 0)
        assert drop > 0, "binder emits BMathFunc only for digits < scale"
        p = np.int64(10 ** drop)

        def run_dec(env):
            v, valid = f(env)
            v = xp.asarray(v)
            q = v // p                       # toward -inf
            r = v - q * p
            if name == "floor":
                out = q
            elif name == "ceil":
                out = q + (r > 0)
            elif name == "trunc":
                out = xp.where(v >= 0, q, q + (r > 0))
            else:  # round half away from zero
                qt = xp.where(v >= 0, q, q + (r > 0))   # toward zero
                rt = v - qt * p                          # remainder, sign of v
                out = qt + xp.sign(rt) * (2 * xp.abs(rt) >= p)
            return (out.astype(dt), valid)
        return run_dec
    if name == "sign":
        f = fs[0]
        return lambda env: ((lambda v: (xp.sign(v[0]).astype(dt), v[1]))(f(env)))
    if name in ("greatest", "least"):
        take_right = (lambda a, b: b > a) if name == "greatest" \
            else (lambda a, b: b < a)

        def run_fold(env):
            acc, acc_valid = fs[0](env)
            acc = xp.asarray(acc).astype(dt)
            acc_valid = _as_mask(xp, acc_valid, acc)
            for f in fs[1:]:
                v, valid = f(env)
                v = xp.asarray(v).astype(dt)
                valid = _as_mask(xp, valid, v)
                # NULLs are ignored: take the other side when one is null
                pick = valid & (~acc_valid | take_right(acc, v))
                acc = xp.where(pick, v, acc)
                acc_valid = acc_valid | valid
            return (acc, acc_valid)
        return run_fold
    raise AnalysisError(f"cannot compile math function {name}")


def _as_bool(xp, v):
    if hasattr(v, "dtype") and v.dtype != bool:
        return v != 0
    if isinstance(v, (bool, np.bool_)):
        return np.bool_(v)
    return v


def _as_mask(xp, valid, like):
    """Normalize python bool validity to an array mask matching ``like``."""
    if valid is True:
        return xp.ones_like(_as_bool(xp, like), dtype=bool) if hasattr(like, "shape") and like.shape else np.True_
    if valid is False:
        return xp.zeros_like(_as_bool(xp, like), dtype=bool) if hasattr(like, "shape") and like.shape else np.False_
    return valid


def _binary(xp, fl, fr, env, fn, null_if=None):
    lv, lvalid = fl(env)
    rv, rvalid = fr(env)
    value = fn(lv, rv)
    if lvalid is True and rvalid is True:
        valid = True
    elif lvalid is False or rvalid is False:
        valid = False
    else:
        valid = _as_mask(xp, lvalid, value) & _as_mask(xp, rvalid, value)
    if null_if is not None:
        bad = null_if(lv, rv)
        if hasattr(bad, "shape") or bad:
            valid = _as_mask(xp, valid, value) & ~bad if hasattr(bad, "shape") else (False if bad else valid)
    return (value, valid)


def predicate_mask(xp, fn, env, n_rows_like):
    """Evaluate a predicate; NULL -> false (WHERE semantics)."""
    v, valid = fn(env)
    v = _as_bool(xp, v)
    if valid is True:
        out = v
    elif valid is False:
        out = xp.zeros_like(v, dtype=bool) if hasattr(v, "shape") and v.shape else np.False_
    else:
        out = v & valid
    if not (hasattr(out, "shape") and out.shape):
        # 0-d predicate (param-only / hoisted-literal comparison): keep
        # it symbolic — bool() would fail on a traced scalar under jit
        out = xp.full(n_rows_like.shape, out) if hasattr(n_rows_like, "shape") else out
    return out
