"""Literal auto-parameterization: every query is a prepared statement.

The reference plans ad-hoc SQL from scratch per statement and only
prepared statements reach the deferred-pruning generic-plan path
(``Job->deferredPruning``, local_plan_cache.c, plancache.c's
``plan_cache_mode``).  Here the compile being amortized is an XLA
program, so the payoff is much larger: hoisting the comparison and
arithmetic literals of a bound filter into synthetic trailing ``$N``
params makes ``WHERE v < 100`` and ``WHERE v < 200`` byte-identical
plan structures — one structural fingerprint, one set of compiled
kernels (executor/kernel_cache.py) for the whole query family.

Hoisting happens at the BOUND level, after the binder's literal
coercion/alignment: each ``BLiteral`` already carries its exact
physical value (dates -> epoch days, decimals -> scaled ints, text ->
dictionary ids), so the synthetic param spec is ``(type, "__physical__")``
and ``encode_params`` ships the value straight to the device dtype with
no re-coercion.  ``substitute_params`` is the inverse: at bind time the
hoisted values are substituted back so interval extraction, shard
pruning and index-equality matching (planner/physical.py) see exactly
the tree the binder would have produced for the literal SQL — generic
plan, custom-plan pruning.

Gated by ``citus.plan_cache_mode``: ``auto`` (default) hoists ad-hoc
SELECT literals, ``force_custom`` disables hoisting (every literal
variant plans and compiles on its own), ``force_generic`` is the
explicit-prepared behavior both share once params exist.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from citus_tpu.planner.bound import (
    BBinOp, BCast, BExpr, BLiteral, BParam, BScale, BUnOp,
)

#: param_specs source marker: the stored value is already physical
#: (bound-level), encode_params must not re-coerce it
PHYSICAL_SRC = "__physical__"

_LOGIC_OPS = ("and", "or")
#: literal operands of these ops are safe to hoist: the kernel consumes
#: them as 0-d env arrays and the pruning passes re-see them at bind
#: time via substitute_params
_HOIST_OPS = ("=", "<>", "<", "<=", ">", ">=", "+", "-", "*", "/", "%")


def auto_parameterize(bound) -> Optional[tuple]:
    """Hoist filter literals into synthetic trailing params.

    Returns ``(generic_bound, values)`` where ``values`` are the
    bound-level physical literal values (positionally matching the new
    specs), or ``None`` when the filter holds nothing hoistable — the
    custom plan is already as generic as it gets.
    """
    if bound.filter is None:
        return None
    start = len(bound.param_specs)
    specs: list = []
    values: list = []

    def hoist(lit: BLiteral) -> BParam:
        p = BParam(start + len(values), lit.type)
        specs.append((lit.type, PHYSICAL_SRC))
        values.append(lit.value)
        return p

    def rewrite(e: BExpr, hoistable: bool) -> BExpr:
        # ``hoistable``: this position is a direct operand of a
        # comparison/arithmetic op (possibly through the binder's
        # scale/cast alignment wrappers)
        if isinstance(e, BLiteral):
            return hoist(e) if hoistable and e.value is not None else e
        if isinstance(e, BBinOp):
            if e.op in _LOGIC_OPS:
                l = rewrite(e.left, False)
                r = rewrite(e.right, False)
            elif e.op in _HOIST_OPS:
                l = rewrite(e.left, True)
                r = rewrite(e.right, True)
            else:
                return e
            if l is e.left and r is e.right:
                return e
            return dataclasses.replace(e, left=l, right=r)
        if isinstance(e, BUnOp) and e.op == "not":
            op = rewrite(e.operand, False)
            return e if op is e.operand else dataclasses.replace(e, operand=op)
        if isinstance(e, (BScale, BCast)):
            op = rewrite(e.operand, hoistable)
            return e if op is e.operand else dataclasses.replace(e, operand=op)
        return e

    new_filter = rewrite(bound.filter, False)
    if not values:
        return None
    generic = dataclasses.replace(
        bound, filter=new_filter,
        param_specs=list(bound.param_specs) + specs)
    return generic, values


def substitute_params(e: Optional[BExpr], values: list) -> Optional[BExpr]:
    """Replace every ``BParam`` with a ``BLiteral`` of its bind-time
    physical value (None for absent/NULL), recovering the literal tree
    the pruning passes understand.  Identity-preserving: returns the
    original node when nothing underneath changed."""
    if e is None or not isinstance(e, BExpr):
        return e
    if isinstance(e, BParam):
        v = values[e.index] if e.index < len(values) else None
        return BLiteral(v, e.type)
    changed = {}
    for f in dataclasses.fields(e):
        val = getattr(e, f.name)
        new = _sub_value(val, values)
        if new is not val:
            changed[f.name] = new
    return dataclasses.replace(e, **changed) if changed else e


def _sub_value(v, values):
    if isinstance(v, BExpr):
        return substitute_params(v, values)
    if isinstance(v, tuple):
        subbed = tuple(_sub_value(x, values) for x in v)
        if any(a is not b for a, b in zip(subbed, v)):
            return subbed
    return v
