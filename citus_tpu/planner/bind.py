"""Binder / semantic analysis: parser AST -> typed BoundSelect.

This is the stand-in for PostgreSQL's analyzer plus the front half of the
reference's logical planner: it resolves columns against the catalog,
types every expression, desugars (BETWEEN, IN, LIKE-over-dictionary,
text equality -> dictionary ids, decimal scale alignment), classifies
aggregates, and validates GROUP BY semantics.  The result is ready for
the worker/combine split (reference: multi_logical_optimizer.c's
WorkerExtendedOpNode/MasterExtendedOpNode construction).
"""

from __future__ import annotations

import decimal
import re
from dataclasses import dataclass, field
from typing import Optional

from citus_tpu import types as T
from citus_tpu.catalog import Catalog, TableMeta
from citus_tpu.errors import AnalysisError, UnsupportedFeatureError
from citus_tpu.planner import ast_nodes as A
from citus_tpu.planner.bound import (
    BAggRef, BBinOp, BCase, BCast, BColumn, BDateTrunc, BDateTruncCivil,
    BDictMask, BExpr, BExtract, BIsNull, BKeyRef, BLiteral, BScale, BUnOp,
    referenced_columns,
)

AGG_FUNCS = {"sum", "count", "avg", "min", "max"}


@dataclass(frozen=True)
class AggSpec:
    kind: str              # sum | count | count_star | avg | min | max | registry name
    arg: Optional[BExpr]   # None for count_star
    out_type: T.ColumnType
    distinct: bool = False
    # extra aggregate parameter (percentile fraction, string_agg
    # delimiter + dictionary source, ...) — hashable for dedup
    param: object = None


@dataclass
class BoundSelect:
    table: TableMeta
    filter: Optional[BExpr]
    group_keys: list[BExpr]
    aggs: list[AggSpec]
    # grouped/agg query: final_exprs over BKeyRef/BAggRef (host combine phase)
    # plain query: final_exprs over columns (device projection)
    final_exprs: list[BExpr]
    output_names: list[str]
    having: Optional[BExpr]
    order_by: list[tuple[int, bool, Optional[bool]]]  # (output index, asc, nulls_first)
    limit: Optional[int]
    offset: Optional[int]
    distinct: bool
    # trailing final_exprs appended only for ORDER BY on non-output
    # expressions; trimmed from the result after sorting
    hidden_outputs: int = 0
    # parameterized plan: per-$N (ColumnType, text_source|None); values
    # arrive at execute time as 0-d env arrays (deferred pruning)
    param_specs: list = field(default_factory=list)

    @property
    def has_aggs(self) -> bool:
        return bool(self.aggs) or bool(self.group_keys)

    @property
    def scan_columns(self) -> list[str]:
        cols: set[str] = set()
        for e in [self.filter, *self.group_keys, *(a.arg for a in self.aggs if a.arg is not None)]:
            if e is not None:
                cols.update(referenced_columns(e))
        for a in self.aggs:
            # ordered aggregates carry sort-key expressions in param
            if isinstance(a.param, tuple) and len(a.param) >= 4 \
                    and isinstance(a.param[2], tuple):
                for e in a.param[2]:
                    if isinstance(e, BExpr):
                        cols.update(referenced_columns(e))
        if not self.has_aggs:
            for e in self.final_exprs:
                cols.update(referenced_columns(e))
        # a uuid column always scans with its low int64 lane (projection
        # and grouping recombine the pair); lane refs from rewritten
        # filters pass through unchanged
        return sorted(self.table.schema.physical_names(sorted(cols)))


def _like_to_regex(pattern: str) -> "re.Pattern":
    out = []
    for ch in pattern:
        if ch == "%":
            out.append(".*")
        elif ch == "_":
            out.append(".")
        else:
            out.append(re.escape(ch))
    return re.compile("^" + "".join(out) + "$", re.DOTALL)


def rewrite_agg_filter(e: A.FuncCall) -> A.FuncCall:
    """agg(x) FILTER (WHERE f) -> agg(CASE WHEN f THEN x END): every
    supported aggregate ignores NULL inputs, so masking the value
    argument is exactly the reference's filter semantics (PostgreSQL
    evaluates FILTER before the transition function)."""
    import dataclasses
    f = e.filter
    if e.name == "count" and (not e.args or isinstance(e.args[0], A.Star)):
        new_args = (A.CaseExpr(((f, A.Literal(1, "int")),), None),)
        return dataclasses.replace(e, args=new_args, filter=None)
    if not e.args:
        raise AnalysisError(f"{e.name}() requires an argument")
    # ordered-set aggregates carry the value expression last
    vi = len(e.args) - 1 if e.name in (
        "percentile_cont", "percentile_disc", "approx_percentile") else 0
    args = list(e.args)
    args[vi] = A.CaseExpr(((f, args[vi]),), None)
    return dataclasses.replace(e, args=tuple(args), filter=None)


class Binder:
    """Resolves expressions against a range table of (alias, TableMeta).

    Single-relation queries use bare column names as environment keys;
    multi-relation (join) queries use qualified ``alias.column`` keys so
    two relations' same-named columns never collide.
    """

    def __init__(self, catalog: Catalog, table: TableMeta,
                 rels: Optional[list[tuple[str, TableMeta]]] = None):
        self.catalog = catalog
        self.table = table
        self.rels = rels or [(table.name, table)]
        self.qualified = len(self.rels) > 1
        # $N parameter slots: 0-based index -> (ColumnType, text_source)
        # populated by infer_param_types before a parameterized bind
        self.param_types: dict[int, tuple] = {}
        # (key_map, aggs) while binding scalar-function arguments in a
        # grouped query's select list: lets round(avg(x), 2) resolve the
        # nested aggregate to a BAggRef and group-key references to
        # BKeyRef (PostgreSQL allows arbitrary expressions over
        # aggregates/keys above the Agg node)
        self._agg_ctx = None

    def resolve_column(self, name: str, rel_alias: Optional[str] = None):
        """-> (env_key, Column, alias, TableMeta)."""
        if rel_alias is not None:
            for alias, t in self.rels:
                if alias == rel_alias:
                    col = t.schema.column(name)
                    key = f"{alias}.{name}" if self.qualified else name
                    return key, col, alias, t
            raise AnalysisError(f"unknown relation alias {rel_alias!r}")
        hits = [(alias, t) for alias, t in self.rels if t.schema.has(name)]
        if not hits:
            raise AnalysisError(f"column {name!r} does not exist")
        if len(hits) > 1:
            raise AnalysisError(f"column reference {name!r} is ambiguous")
        alias, t = hits[0]
        key = f"{alias}.{name}" if self.qualified else name
        return key, t.schema.column(name), alias, t

    def _text_words(self, target):
        """Resolve a text expression that is a BColumn or a chain of
        BDictRemap transforms over one: -> (base column, table, column,
        effective word per base dictionary id) or None.  This is what
        lets string functions compose (upper(trim(s))): each wrapper's
        mapping applies to the bind-time word table, and the final remap
        is expressed over the base column's ids."""
        from citus_tpu.planner.bound import BDictRemap
        chain = []
        base = target
        while isinstance(base, BDictRemap):
            chain.append(base.mapping)
            base = base.operand
        if not (isinstance(base, BColumn) and base.type.is_text):
            return None
        tname, cname = self.text_source(base)
        words = self.catalog.dictionary(tname, cname)
        eff = list(range(len(words)))
        for mapping in reversed(chain):  # innermost transform first
            eff = [mapping[i] if i < len(mapping) else i for i in eff]
        return base, tname, cname, [words[i] for i in eff]

    def enum_info(self, target):
        """BARE enum-typed column -> (base column, type name, labels,
        dictionary words) or None.  A string-function remap over an enum
        column (upper(s), ...) produces plain text, not enum values —
        it must NOT get declaration-rank semantics."""
        if not (isinstance(target, BColumn) and target.type.is_text):
            return None
        tname, cname = self.text_source(target)
        type_name = self.catalog.enum_columns.get(f"{tname}.{cname}")
        if type_name is None:
            return None
        labels = list(self.catalog.types.get(type_name, ()))
        words = self.catalog.dictionary(tname, cname)
        return target, type_name, labels, words

    @staticmethod
    def enum_rank_lut(info) -> tuple:
        """(enum_info) -> per-dictionary-id declaration rank table."""
        _base, _type_name, labels, words = info
        rank_of = {w: i for i, w in enumerate(labels)}
        return tuple(rank_of.get(w, -1) for w in words)

    def enum_rank(self, target) -> Optional[BExpr]:
        """Enum column -> its declaration-order rank (int64), via a
        per-dictionary-id lookup table (reference: enum comparisons use
        enumsortorder, not label text)."""
        from citus_tpu.planner.bound import BDictLookup
        info = self.enum_info(target)
        if info is None:
            return None
        return BDictLookup(info[0], self.enum_rank_lut(info))

    def _try_enum_ordered(self, op: str, left: BExpr,
                          right: BExpr) -> Optional[BExpr]:
        """Ordered comparison where a side is an enum column: compare
        declaration-order ranks.  Literal labels validate against the
        type; mismatched enum types reject."""
        linfo = self.enum_info(left) if left.type.is_text else None
        rinfo = self.enum_info(right) if right.type.is_text else None
        if linfo is None and rinfo is None:
            return None

        def side(e, info, other_info):
            if info is not None:
                return self.enum_rank(e), info[1]
            if isinstance(e, BLiteral) and isinstance(e.value, str):
                _b, type_name, labels, _w = other_info
                if e.value not in labels:
                    raise AnalysisError(
                        f"invalid input value for enum {type_name}: "
                        f"{e.value!r}")
                return BLiteral(labels.index(e.value), T.INT64_T), type_name
            return None, None

        lr, lt_name = side(left, linfo, rinfo)
        rr, rt_name = side(right, rinfo, linfo)
        if lr is None or rr is None:
            return None
        if lt_name != rt_name:
            raise AnalysisError(
                f"cannot compare enum types {lt_name} and {rt_name}")
        return BBinOp(op, lr, rr, T.BOOL_T)

    def _remap_text(self, fname: str, target, op):
        """Bind a string function as a dictionary remap on the base
        column (composable with other remap-family functions).  String
        literals constant-fold."""
        from citus_tpu.planner.bound import BDictRemap
        if isinstance(target, BLiteral) and isinstance(target.value, str):
            return BLiteral(op(target.value), target.type)
        resolved = self._text_words(target)
        if resolved is None:
            raise UnsupportedFeatureError(
                f"{fname}() requires a text column (or a string function "
                "over one)")
        base, tname, cname, eff_words = resolved
        out_words = [op(w) for w in eff_words]
        mapping = tuple(int(x) for x in self.catalog.encode_strings(
            tname, cname, out_words))
        return BDictRemap(base, mapping)

    def text_source(self, bcol: BColumn) -> tuple[str, str]:
        """Env key of a text column -> (table_name, column_name)."""
        if "." in bcol.name:
            alias, col = bcol.name.split(".", 1)
            for a, t in self.rels:
                if a == alias:
                    return t.name, col
            raise AnalysisError(f"unknown alias {alias!r}")
        return self.table.name, bcol.name

    # ---------------------------------------------------------------- expr
    def bind_scalar(self, e: A.Expr, allow_agg: bool = False) -> BExpr:
        if isinstance(e, A.ColumnRef):
            key, col, _, _ = self.resolve_column(e.name, e.table)
            b = BColumn(key, col.type)
            if self._agg_ctx is not None:
                idx = self._agg_ctx[0].get(b)
                if idx is not None:
                    return BKeyRef(idx, b.type)
            return b
        if isinstance(e, A.Param):
            from citus_tpu.planner.bound import BParam
            spec = self.param_types.get(e.index - 1)
            if spec is None:
                raise UnsupportedFeatureError(
                    f"cannot infer a type for parameter ${e.index}; "
                    "bind it by comparing against a typed column")
            return BParam(e.index - 1, spec[0])
        if isinstance(e, A.Literal):
            return self._bind_literal(e)
        if isinstance(e, A.UnOp):
            inner = self.bind_scalar(e.operand, allow_agg)
            if e.op == "-":
                if not inner.type.is_numeric:
                    raise AnalysisError(f"cannot negate {inner.type}")
                return BUnOp("-", inner, inner.type)
            if e.op == "not":
                return BUnOp("not", self._to_bool(inner), T.BOOL_T)
        if isinstance(e, A.BinOp):
            return self._bind_binop(e, allow_agg)
        if isinstance(e, A.Between):
            lo = A.BinOp(">=", e.expr, e.lo)
            hi = A.BinOp("<=", e.expr, e.hi)
            both = A.BinOp("and", lo, hi)
            return self.bind_scalar(A.UnOp("not", both) if e.negated else both, allow_agg)
        if isinstance(e, A.InList):
            return self._bind_in(e, allow_agg)
        if isinstance(e, A.IsNull):
            return BIsNull(self.bind_scalar(e.expr, allow_agg), e.negated)
        if isinstance(e, A.Cast):
            inner = self.bind_scalar(e.expr, allow_agg)
            target = T.type_from_sql(e.type_name, list(e.type_args) or None)
            if target.kind == T.UUID:
                if isinstance(inner, BLiteral) \
                        and isinstance(inner.value, str):
                    # typed literal: uuid '...' folds to its 128-bit int
                    return BLiteral(target.to_physical(inner.value), target)
                if inner.type.kind == T.UUID:
                    return inner
                raise UnsupportedFeatureError(
                    "cast to uuid requires a uuid value or string literal")
            if target.is_text:
                if isinstance(inner, BLiteral) \
                        and isinstance(inner.value, str):
                    # typed literal of a dictionary kind (uuid '...'):
                    # stays a string until _align coerces it into the
                    # column's dictionary-id space (normalized there)
                    return BLiteral(inner.value, target)
                raise UnsupportedFeatureError("cast to text not supported")
            if target.kind in (T.DATE, T.TIMESTAMP, T.TIMESTAMPTZ,
                               T.TIME, T.INTERVAL) \
                    and isinstance(inner, BLiteral) \
                    and isinstance(inner.value, str):
                # typed literal: date '1998-12-01' folds at bind time
                try:
                    return BLiteral(target.to_physical(inner.value), target)
                except (ValueError, TypeError):
                    raise AnalysisError(
                        f"invalid input syntax for type {e.type_name}: "
                        f"{inner.value!r}")
            return BCast(inner, target)
        if isinstance(e, A.CaseExpr):
            return self._bind_case(e, allow_agg)
        if isinstance(e, A.FuncCall):
            return self._bind_func(e, allow_agg)
        raise AnalysisError(f"cannot bind expression {e}")

    def _bind_literal(self, e: A.Literal) -> BLiteral:
        v = e.value
        if v is None:
            return BLiteral(None, T.INT64_T)
        if e.type_name == "int":
            return BLiteral(int(v), T.INT64_T)
        if e.type_name == "decimal":
            d = v if isinstance(v, decimal.Decimal) else decimal.Decimal(str(v))
            scale = max(0, -d.as_tuple().exponent)
            t = T.decimal_t(38, scale)
            return BLiteral(t.to_physical(d), t)
        if e.type_name == "float":
            return BLiteral(float(v), T.FLOAT64_T)
        if e.type_name == "bool":
            return BLiteral(1 if v else 0, T.BOOL_T)
        if e.type_name == "string":
            # untyped until coerced against the other side of a comparison
            return BLiteral(v, T.TEXT_T)
        if e.type_name == "array":
            # stays a Python list until _align coerces it into an array
            # column's dictionary-id space (canonical JSON word)
            return BLiteral(list(v), T.array_t())
        raise AnalysisError(f"bad literal {e}")

    def _coerce_string_literal(self, lit: BLiteral, target: T.ColumnType,
                               column: Optional[BColumn]) -> BLiteral:
        """'1994-01-01' vs date column, 'AIR' vs text column, etc."""
        if target.kind in (T.DATE, T.TIMESTAMP, T.TIMESTAMPTZ, T.TIME,
                           T.INTERVAL):
            return BLiteral(target.to_physical(lit.value), target)
        if target.kind == T.UUID:
            # dictionary bypass: the literal folds to its 128-bit integer;
            # _bind_uuid_compare splits it into int64 lane literals
            return BLiteral(target.to_physical(lit.value), target)
        if target.is_text:
            if column is None:
                raise AnalysisError("cannot compare two string literals from different tables")
            tname, cname = self.text_source(column)
            did = self.catalog.lookup_string_id(tname, cname, lit.value)
            # unseen string: id -1 never matches any row
            return BLiteral(-1 if did is None else did, T.TEXT_T)
        if target.is_numeric:
            d = decimal.Decimal(lit.value)
            scale = max(0, -d.as_tuple().exponent)
            t = T.decimal_t(38, scale) if scale else T.INT64_T
            return BLiteral(t.to_physical(d), t)
        raise AnalysisError(f"cannot coerce string literal to {target}")

    def _align(self, left: BExpr, right: BExpr) -> tuple[BExpr, BExpr]:
        """Insert scale/cast adjustments so both sides share physical space."""
        lt, rt = left.type, right.type
        # string literal coercion
        if isinstance(right, BLiteral) and rt.is_text and not lt.is_text \
                and isinstance(right.value, str):
            right = self._coerce_string_literal(right, lt, None)
            rt = right.type
        if isinstance(left, BLiteral) and lt.is_text and not rt.is_text \
                and isinstance(left.value, str):
            left = self._coerce_string_literal(left, rt, None)
            lt = left.type
        if lt.is_text and rt.is_text:
            def text_base(e):
                from citus_tpu.planner.bound import BDictRemap
                while isinstance(e, BDictRemap):
                    e = e.operand  # remapped ids live in the base dictionary
                return e if isinstance(e, BColumn) else None
            col = text_base(left) or text_base(right)
            if isinstance(right, BLiteral) \
                    and isinstance(right.value, (str, list, bytes)):
                right = self._coerce_string_literal(right, lt, col)
            elif isinstance(left, BLiteral) \
                    and isinstance(left.value, (str, list, bytes)):
                left = self._coerce_string_literal(left, rt, col)
            elif isinstance(left, BColumn) and isinstance(right, BColumn):
                lsrc = self.text_source(left)
                rsrc = self.text_source(right)
                if lsrc != rsrc:
                    # different dictionaries: re-encode the right side into
                    # the left dictionary's id space
                    from citus_tpu.planner.bound import BDictRemap
                    lwords = self.catalog.dictionary(*lsrc)
                    lindex = {w: i for i, w in enumerate(lwords)}
                    rwords = self.catalog.dictionary(*rsrc)
                    mapping = tuple(lindex.get(w, -1) for w in rwords)
                    right = BDictRemap(right, mapping)
            return left, right
        # mixed decimal/float: the decimal side must leave scaled-int space
        if lt.is_float and rt.is_decimal:
            right = BCast(right, T.FLOAT64_T)
            rt = right.type
        elif rt.is_float and lt.is_decimal:
            left = BCast(left, T.FLOAT64_T)
            lt = left.type
        # decimal scale alignment (comparisons, +, -)
        ls = lt.scale if lt.is_decimal else 0
        rs = rt.scale if rt.is_decimal else 0
        if (lt.is_decimal or rt.is_decimal) and not (lt.is_float or rt.is_float):
            if ls < rs:
                left = self._rescale(left, rs)
            elif rs < ls:
                right = self._rescale(right, ls)
        return left, right

    def _rescale(self, e: BExpr, scale: int) -> BExpr:
        cur = e.type.scale if e.type.is_decimal else 0
        t = T.decimal_t(38, scale)
        if isinstance(e, BLiteral):
            if e.value is None:
                return BLiteral(None, t)
            return BLiteral(int(e.value) * 10 ** (scale - cur), t)
        return BScale(e, scale - cur, t)

    def _to_bool(self, e: BExpr) -> BExpr:
        if e.type.kind != T.BOOL:
            raise AnalysisError(f"expected boolean expression, got {e.type}")
        return e

    def _bind_interval_arith(self, e: A.BinOp, allow_agg: bool) -> BExpr:
        """date/timestamp ± INTERVAL.  Literal dates fold; expressions
        lower to civil month addition (BAddMonths) plus fixed-width
        day/microsecond offsets.  A DATE result stays DATE when the
        interval has no sub-day component (the reference promotes to
        timestamp; for comparisons at midnight the value is identical)."""
        from citus_tpu.planner.bound import BAddMonths, py_add_interval
        if e.op not in ("+", "-"):
            raise UnsupportedFeatureError(
                f"operator {e.op} is not defined for intervals")
        if isinstance(e.left, A.IntervalLiteral):
            if isinstance(e.right, A.IntervalLiteral) or e.op != "+":
                raise UnsupportedFeatureError(
                    "interval arithmetic supports date/timestamp ± interval")
            ivl, other_ast = e.left, e.right
        else:
            ivl, other_ast = e.right, e.left
        sign = 1 if e.op == "+" else -1
        other = self.bind_scalar(other_ast, allow_agg)
        if other.type.kind not in (T.DATE, T.TIMESTAMP, T.TIMESTAMPTZ):
            raise AnalysisError(
                f"cannot add interval to {other.type}")
        months = sign * ivl.months
        days = sign * ivl.days
        micros = sign * ivl.micros
        if other.type.kind == T.DATE and micros:
            raise UnsupportedFeatureError(
                "sub-day intervals on date values are not supported")
        if isinstance(other, BLiteral):
            if other.value is None:
                return other
            v = other.type.from_physical(other.value)
            out = py_add_interval(v, months, days, micros)
            return BLiteral(other.type.to_physical(out), other.type)
        result: BExpr = other
        if months:
            result = BAddMonths(result, months, other.type)
        if other.type.kind == T.DATE:
            if days:
                result = BBinOp("+", result, BLiteral(days, T.INT64_T),
                                other.type)
        else:
            total = days * 86_400_000_000 + micros
            if total:
                result = BBinOp("+", result, BLiteral(total, T.INT64_T),
                                other.type)
        return result

    def _bind_binop(self, e: A.BinOp, allow_agg: bool) -> BExpr:
        op = e.op
        if isinstance(e.left, A.IntervalLiteral) \
                or isinstance(e.right, A.IntervalLiteral):
            # against an INTERVAL-typed expression the literal is just a
            # microsecond scalar (comparisons, +, -); month components
            # have no fixed us length and stay in the civil-arithmetic
            # path below
            lit = e.left if isinstance(e.left, A.IntervalLiteral) else e.right
            other_ast = e.right if lit is e.left else e.left
            if not isinstance(other_ast, A.IntervalLiteral):
                try:
                    other = self.bind_scalar(other_ast, allow_agg)
                except AnalysisError:
                    other = None
                if other is not None and other.type.kind == T.INTERVAL \
                        and lit.months == 0:
                    us = lit.days * 86_400_000_000 + lit.micros
                    blit = BLiteral(us, T.INTERVAL_T)
                    left, right = (blit, other) if lit is e.left \
                        else (other, blit)
                    if op in ("=", "<>", "<", "<=", ">", ">="):
                        return BBinOp(op, left, right, T.BOOL_T)
                    rt = T.arith_result_type(op, left.type, right.type)
                    return BBinOp(op, left, right, rt)
            return self._bind_interval_arith(e, allow_agg)
        left = self.bind_scalar(e.left, allow_agg)
        right = self.bind_scalar(e.right, allow_agg)
        if op in ("and", "or"):
            return BBinOp(op, self._to_bool(left), self._to_bool(right), T.BOOL_T)
        if op in ("<", "<=", ">", ">=") \
                and (left.type.is_text or right.type.is_text):
            # enum columns order by declaration rank (before _align
            # coerces the literal side into dictionary-id space)
            enum_cmp = self._try_enum_ordered(op, left, right)
            if enum_cmp is not None:
                return enum_cmp
        left, right = self._align(left, right)
        if op in ("=", "<>", "<", "<=", ">", ">=") \
                and (left.type.kind == T.UUID or right.type.kind == T.UUID):
            return self._bind_uuid_compare(op, left, right)
        if op in ("=", "<>", "<", "<=", ">", ">="):
            if left.type.is_text and op not in ("=", "<>"):
                raise UnsupportedFeatureError("ordered comparison on text columns")
            if not left.type.is_text and not right.type.is_numeric and left.type.kind != right.type.kind:
                raise AnalysisError(f"cannot compare {left.type} and {right.type}")
            return BBinOp(op, left, right, T.BOOL_T)
        out = T.arith_result_type(op, left.type, right.type)
        if op in ("+", "-") and out.is_decimal:
            # operands already aligned to out.scale
            out = T.decimal_t(38, max(left.type.scale if left.type.is_decimal else 0,
                                      right.type.scale if right.type.is_decimal else 0))
        return BBinOp(op, left, right, out)

    def _uuid_lane_exprs(self, e: BExpr) -> tuple[BExpr, BExpr]:
        """uuid-typed operand -> (hi, lo) int64 lane expressions.  The
        base column stream carries the high 64 bits; the companion
        "<name>::lo" stream carries the low 64."""
        from citus_tpu.planner.bound import BParam
        if isinstance(e, BColumn):
            return (BColumn(e.name, T.INT64_T),
                    BColumn(T.uuid_lane_name(e.name), T.INT64_T))
        if isinstance(e, BLiteral):
            if e.value is None:
                return BLiteral(None, T.INT64_T), BLiteral(None, T.INT64_T)
            hi, lo = T.uuid_int_to_lanes(int(e.value))
            return BLiteral(hi, T.INT64_T), BLiteral(lo, T.INT64_T)
        if isinstance(e, BParam):
            return (BParam(e.index, T.INT64_T),
                    BParam(e.index, T.INT64_T, lane=T.UUID_LANE_SUFFIX))
        raise UnsupportedFeatureError(
            f"uuid comparison over {type(e).__name__} not supported")

    def _bind_uuid_compare(self, op: str, left: BExpr,
                           right: BExpr) -> BExpr:
        """uuid comparisons lower onto the two int64 lane streams (the
        dictionary-bypass path): equality is lane-wise AND; ordering is
        lexicographic on (hi, lo) — the offset-binary lane encoding
        makes signed int64 order match unsigned 128-bit order."""
        if left.type.kind != T.UUID or right.type.kind != T.UUID:
            raise AnalysisError(
                f"cannot compare {left.type} and {right.type}")
        lh, ll = self._uuid_lane_exprs(left)
        rh, rl = self._uuid_lane_exprs(right)

        def eq(a, b):
            return BBinOp("=", a, b, T.BOOL_T)

        if op == "=":
            return BBinOp("and", eq(lh, rh), eq(ll, rl), T.BOOL_T)
        if op == "<>":
            return BBinOp("or", BBinOp("<>", lh, rh, T.BOOL_T),
                          BBinOp("<>", ll, rl, T.BOOL_T), T.BOOL_T)
        strict = "<" if op in ("<", "<=") else ">"
        return BBinOp(
            "or", BBinOp(strict, lh, rh, T.BOOL_T),
            BBinOp("and", eq(lh, rh), BBinOp(op, ll, rl, T.BOOL_T),
                   T.BOOL_T),
            T.BOOL_T)

    def _bind_in(self, e: A.InList, allow_agg: bool) -> BExpr:
        target = self.bind_scalar(e.expr, allow_agg)
        if target.type.is_text and isinstance(target, BColumn):
            words = self.catalog.dictionary(*self.text_source(target))
            values = {it.value for it in e.items if isinstance(it, A.Literal)}
            if len(values) != len(e.items):
                raise UnsupportedFeatureError("non-literal IN items on text")
            mask = [w in values for w in words]
            out: BExpr = BDictMask(target, tuple(mask))
            return BUnOp("not", out, T.BOOL_T) if e.negated else out
        parts = None
        for item in e.items:
            eq = self._bind_binop(A.BinOp("=", e.expr, item), allow_agg)
            parts = eq if parts is None else BBinOp("or", parts, eq, T.BOOL_T)
        if parts is None:
            parts = BLiteral(0, T.BOOL_T)
        return BUnOp("not", parts, T.BOOL_T) if e.negated else parts

    def _bind_case_from_bound(self, whens, else_, out: T.ColumnType) -> BExpr:
        """CASE over already-bound branches with scale alignment."""
        if out.is_decimal:
            whens = tuple(
                (c, self._rescale(v, out.scale)
                 if (v.type.is_decimal or v.type.is_integer) else v)
                for c, v in whens)
            if else_ is not None and (else_.type.is_decimal or else_.type.is_integer):
                else_ = self._rescale(else_, out.scale)
        return BCase(tuple(whens), else_, out)

    def _bind_case(self, e: A.CaseExpr, allow_agg: bool) -> BExpr:
        whens = [(self._to_bool(self.bind_scalar(c, allow_agg)), self.bind_scalar(v, allow_agg))
                 for c, v in e.whens]
        else_ = self.bind_scalar(e.else_, allow_agg) if e.else_ is not None else None
        result_types = [v.type for _, v in whens] + ([else_.type] if else_ is not None else [])
        out = result_types[0]
        for t in result_types[1:]:
            out = T.common_super_type(out, t)
        # align decimal scales of branches
        if out.is_decimal:
            whens = [(c, self._rescale(v, out.scale) if v.type.is_decimal or v.type.is_integer else v)
                     for c, v in whens]
            if else_ is not None and (else_.type.is_decimal or else_.type.is_integer):
                else_ = self._rescale(else_, out.scale)
        return BCase(tuple(whens), else_, out)

    def _bind_func(self, e: A.FuncCall, allow_agg: bool) -> BExpr:
        name = e.name
        if self._agg_ctx is not None:
            from citus_tpu.planner.aggregates import AGG_REGISTRY
            if name in AGG_FUNCS or name in AGG_REGISTRY:
                return self._bind_agg_call(e, self._agg_ctx[1])
        if name in AGG_FUNCS:
            raise AnalysisError(f"aggregate {name}() not allowed here")
        if e.filter is not None:
            raise AnalysisError(
                f"FILTER specified, but {name}() is not an aggregate "
                "function")
        if name in ("like", "ilike"):
            target = self.bind_scalar(e.args[0], allow_agg)
            pat = e.args[1]
            resolved = self._text_words(target) \
                if target.type.is_text else None
            if not (resolved is not None and isinstance(pat, A.Literal)
                    and isinstance(pat.value, str)):
                raise UnsupportedFeatureError(
                    "LIKE requires a text column (or string function over "
                    "one) and a literal pattern")
            base, _t, _c, eff_words = resolved
            rx = _like_to_regex(pat.value.lower() if name == "ilike"
                                else pat.value)
            # pattern evaluates against the TRANSFORMED word per base id
            if name == "ilike":
                return BDictMask(base, tuple(bool(rx.match(w.lower()))
                                             for w in eff_words))
            return BDictMask(base, tuple(bool(rx.match(w)) for w in eff_words))
        if name in ("current_date", "current_timestamp", "now"):
            import datetime as _dt
            if name == "current_date":
                return BLiteral(T.DATE_T.to_physical(_dt.date.today()),
                                T.DATE_T)
            return BLiteral(T.TIMESTAMP_T.to_physical(_dt.datetime.now()),
                            T.TIMESTAMP_T)
        if name == "date_trunc":
            if len(e.args) != 2 or not isinstance(e.args[0], A.Literal):
                raise AnalysisError("date_trunc(unit, expr) expects a literal unit")
            unit = str(e.args[0].value)
            inner = self.bind_scalar(e.args[1], allow_agg)
            if inner.type.kind not in (T.DATE, T.TIMESTAMP, T.TIMESTAMPTZ):
                raise AnalysisError("date_trunc expects date/timestamp")
            if unit in ("month", "quarter", "year"):
                return BDateTruncCivil(unit, inner, inner.type)
            return BDateTrunc(unit, inner, inner.type)
        if name == "extract":
            field = str(e.args[0].value).lower()
            inner = self.bind_scalar(e.args[1], allow_agg)
            if inner.type.kind not in (T.DATE, T.TIMESTAMP, T.TIMESTAMPTZ):
                raise AnalysisError("EXTRACT expects date/timestamp")
            return BExtract(field, inner)
        if name in ("upper", "lower"):
            target = self.bind_scalar(e.args[0], allow_agg)
            fn = str.upper if name == "upper" else str.lower
            return self._remap_text(name, target, fn)
        if name == "substring":
            target = self.bind_scalar(e.args[0], allow_agg)
            if not all(isinstance(a, A.Literal) for a in e.args[1:]):
                raise UnsupportedFeatureError("substring() bounds must be literals")
            start = int(e.args[1].value) if len(e.args) > 1 else 1
            ln = int(e.args[2].value) if len(e.args) > 2 else None
            i0 = max(start - 1, 0)
            return self._remap_text(
                name, target,
                lambda w: (w[i0:i0 + ln] if ln is not None else w[i0:]))
        if name == "concat":
            bound = [self.bind_scalar(a, allow_agg) for a in e.args]
            texts = [x for x in bound
                     if x.type.is_text and not isinstance(x, BLiteral)]
            if len(texts) != 1 or not all(
                    (isinstance(x, BLiteral) and isinstance(x.value, str)) or x is texts[0]
                    for x in bound):
                raise UnsupportedFeatureError(
                    "concat() supports one text expression plus string literals")
            def cat_op(w, _parts=bound, _t=texts[0]):
                return "".join(x.value if isinstance(x, BLiteral) else w
                               for x in _parts)
            return self._remap_text(name, texts[0], cat_op)
        if name in ("length", "char_length"):
            target = self.bind_scalar(e.args[0], allow_agg)
            from citus_tpu.planner.bound import BDictLookup
            resolved = self._text_words(target)
            if resolved is None:
                raise UnsupportedFeatureError("length() requires a text column")
            base, _, _, eff_words = resolved
            lut = tuple(len(w) for w in eff_words)
            # lookup table indexes by the BASE column's ids
            return BDictLookup(base, lut)
        if name in ("trim", "btrim", "ltrim", "rtrim", "replace", "left",
                    "right", "initcap", "reverse"):
            # dictionary-remap family: apply the python string op to every
            # dictionary word once at bind time; rows keep their ids
            target = self.bind_scalar(e.args[0], allow_agg)
            extras = []
            for a in e.args[1:]:
                lit = self.bind_scalar(a, allow_agg)
                if isinstance(lit, BUnOp) and lit.op == "-" \
                        and isinstance(lit.operand, BLiteral):
                    lit = BLiteral(-lit.operand.value, lit.type)
                if not isinstance(lit, BLiteral):
                    raise UnsupportedFeatureError(
                        f"{name}() extra arguments must be literals")
                extras.append(lit.value)
            if name in ("trim", "btrim"):
                chars = str(extras[0]) if extras else None
                op = lambda w: w.strip(chars)  # noqa: E731
            elif name == "ltrim":
                chars = str(extras[0]) if extras else None
                op = lambda w: w.lstrip(chars)  # noqa: E731
            elif name == "rtrim":
                chars = str(extras[0]) if extras else None
                op = lambda w: w.rstrip(chars)  # noqa: E731
            elif name == "replace":
                if len(extras) != 2:
                    raise AnalysisError("replace() requires (text, from, to)")
                frm, to = str(extras[0]), str(extras[1])
                op = lambda w: w.replace(frm, to)  # noqa: E731
            elif name == "left":
                n_ = int(extras[0])
                op = lambda w: w[:n_]  # noqa: E731  (negative: drop from end)
            elif name == "right":
                n_ = int(extras[0])
                # right(w, n): last n chars; negative drops from the front
                op = (lambda w: w[max(0, len(w) - n_):]) if n_ >= 0 \
                    else (lambda w: w[-n_:])  # noqa: E731
            elif name == "initcap":
                op = lambda w: w.title()  # noqa: E731
            else:  # reverse
                op = lambda w: w[::-1]  # noqa: E731
            return self._remap_text(name, target, op)
        if name == "coalesce":
            if not e.args:
                raise AnalysisError("coalesce() requires arguments")
            bound = [self.bind_scalar(a, allow_agg) for a in e.args]
            # text branches: encode raw string literals into the dictionary
            # of the first text column argument
            text_col = next((x for x in bound
                             if isinstance(x, BColumn) and x.type.is_text), None)
            if text_col is not None:
                tname, cname = self.text_source(text_col)
                bound = [BLiteral(int(self.catalog.encode_strings(
                             tname, cname, [x.value])[0]), T.TEXT_T)
                         if isinstance(x, BLiteral) and isinstance(x.value, str)
                         else x for x in bound]
            out = bound[0].type
            for x in bound[1:]:
                out = T.common_super_type(out, x.type)
            whens = tuple((BIsNull(x, negated=True), x) for x in bound[:-1])
            return self._bind_case_from_bound(whens, bound[-1], out)
        if name == "nullif":
            if len(e.args) != 2:
                raise AnalysisError("nullif() requires two arguments")
            a = self.bind_scalar(e.args[0], allow_agg)
            bdy = self.bind_scalar(e.args[1], allow_agg)
            a2, b2 = self._align(a, bdy)
            cond = BBinOp("=", a2, b2, T.BOOL_T)
            return BCase(((cond, BLiteral(None, a.type)),), a, a.type)
        if name == "abs":
            inner = self.bind_scalar(e.args[0], allow_agg)
            return BCase(((BBinOp("<", inner, BLiteral(0, T.INT64_T) if not inner.type.is_float
                                  else BLiteral(0.0, T.FLOAT64_T), T.BOOL_T),
                           BUnOp("-", inner, inner.type)),), inner, inner.type)
        bound_math = self._bind_math_func(name, e, allow_agg)
        if bound_math is not None:
            return bound_math
        raise UnsupportedFeatureError(f"function {name}() not supported")

    def _bind_math_func(self, name: str, e: A.FuncCall,
                        allow_agg: bool) -> Optional[BExpr]:
        """PostgreSQL's scalar math surface (float.c / numeric.c):
        floor/ceil/round/trunc are exact on the decimal scaled-int
        representation; transcendentals go through float64."""
        from citus_tpu.planner.bound import BMathFunc

        def to_f(x: BExpr) -> BExpr:
            return x if x.type.is_float else BCast(x, T.FLOAT64_T)

        def literal_int(a: A.Expr, what: str) -> int:
            lit = self.bind_scalar(a, allow_agg)
            if isinstance(lit, BUnOp) and lit.op == "-" \
                    and isinstance(lit.operand, BLiteral):
                lit = BLiteral(-lit.operand.value, lit.type)
            if not isinstance(lit, BLiteral) or lit.value is None:
                raise UnsupportedFeatureError(f"{what} must be a literal")
            return int(lit.value)

        if name in ("floor", "ceil", "ceiling", "round", "trunc"):
            fname = "ceil" if name == "ceiling" else name
            if not e.args:
                raise AnalysisError(f"{fname}() requires an argument")
            inner = self.bind_scalar(e.args[0], allow_agg)
            digits = 0
            if len(e.args) > 1:
                if fname in ("floor", "ceil"):
                    raise AnalysisError(f"{fname}() takes one argument")
                digits = literal_int(e.args[1], f"{fname}() digit count")
                if digits < 0:
                    raise UnsupportedFeatureError(
                        f"{fname}() negative digit counts not supported")
            t = inner.type
            if t.is_float:
                return BMathFunc(fname, (inner,), T.FLOAT64_T,
                                 param=(0, digits))
            if t.is_integer:
                return inner
            if t.is_decimal:
                if digits >= t.scale:
                    return self._rescale(inner, digits) \
                        if digits != t.scale else inner
                return BMathFunc(fname, (inner,), T.decimal_t(38, digits),
                                 param=(t.scale, digits))
            raise AnalysisError(f"{fname}() expects a numeric argument")
        if name in ("sqrt", "exp", "ln", "log", "log10", "log2",
                    "power", "pow"):
            args = [self.bind_scalar(a, allow_agg) for a in e.args]
            if any(not a.type.is_numeric for a in args):
                raise AnalysisError(f"{name}() expects numeric arguments")
            if name in ("power", "pow"):
                if len(args) != 2:
                    raise AnalysisError("power() requires two arguments")
                return BMathFunc("power", (to_f(args[0]), to_f(args[1])),
                                 T.FLOAT64_T)
            if name == "log" and len(args) == 2:
                # log(base, x) = ln(x) / ln(base)
                lx = BMathFunc("ln", (to_f(args[1]),), T.FLOAT64_T)
                lb = BMathFunc("ln", (to_f(args[0]),), T.FLOAT64_T)
                return BBinOp("/", lx, lb, T.FLOAT64_T)
            if len(args) != 1:
                raise AnalysisError(f"{name}() requires one argument")
            fname = "log10" if name == "log" else name
            return BMathFunc(fname, (to_f(args[0]),), T.FLOAT64_T)
        if name == "mod":
            if len(e.args) != 2:
                raise AnalysisError("mod() requires two arguments")
            return self._bind_binop(A.BinOp("%", e.args[0], e.args[1]),
                                    allow_agg)
        if name == "sign":
            if len(e.args) != 1:
                raise AnalysisError("sign() requires one argument")
            inner = self.bind_scalar(e.args[0], allow_agg)
            if not inner.type.is_numeric:
                raise AnalysisError("sign() expects a numeric argument")
            out = T.FLOAT64_T if inner.type.is_float else T.INT64_T
            return BMathFunc("sign", (inner,), out)
        if name == "pi":
            import math
            if e.args:
                raise AnalysisError("pi() takes no arguments")
            return BLiteral(math.pi, T.FLOAT64_T)
        if name in ("degrees", "radians"):
            import math
            if len(e.args) != 1:
                raise AnalysisError(f"{name}() requires one argument")
            factor = 180.0 / math.pi if name == "degrees" else math.pi / 180.0
            inner = self.bind_scalar(e.args[0], allow_agg)
            if not inner.type.is_numeric:
                raise AnalysisError(f"{name}() expects a numeric argument")
            return BBinOp("*", to_f(inner), BLiteral(factor, T.FLOAT64_T),
                          T.FLOAT64_T)
        if name in ("greatest", "least"):
            if not e.args:
                raise AnalysisError(f"{name}() requires arguments")
            bound = [self.bind_scalar(a, allow_agg) for a in e.args]
            # string literals coerce against the first typed argument
            anchor = next((x.type for x in bound
                           if not (isinstance(x, BLiteral) and x.type.is_text)),
                          None)
            if anchor is not None and not anchor.is_text:
                bound = [self._coerce_string_literal(x, anchor, None)
                         if isinstance(x, BLiteral) and x.type.is_text
                         and isinstance(x.value, str) else x for x in bound]
            out = bound[0].type
            for x in bound[1:]:
                out = T.common_super_type(out, x.type)
            if out.is_text:
                raise UnsupportedFeatureError(
                    f"{name}() over text not supported")
            if out.is_decimal:
                bound = [self._rescale(x, out.scale)
                         if (x.type.is_decimal or x.type.is_integer) else x
                         for x in bound]
            elif out.is_float:
                bound = [to_f(x) for x in bound]
            return BMathFunc(name, tuple(bound), out)
        if name in ("strpos", "position"):
            if len(e.args) != 2:
                raise AnalysisError(f"{name}() requires two arguments")
            target = self.bind_scalar(e.args[0], allow_agg)
            sub = e.args[1]
            if not (isinstance(sub, A.Literal) and isinstance(sub.value, str)):
                raise UnsupportedFeatureError(
                    f"{name}() substring must be a string literal")
            resolved = self._text_words(target)
            if resolved is None:
                if isinstance(target, BLiteral) and isinstance(target.value, str):
                    return BLiteral(target.value.find(sub.value) + 1, T.INT64_T)
                raise UnsupportedFeatureError(
                    f"{name}() requires a text column")
            from citus_tpu.planner.bound import BDictLookup
            base, _t, _c, eff_words = resolved
            lut = tuple(w.find(sub.value) + 1 for w in eff_words)
            return BDictLookup(base, lut)
        return None

    # ---------------------------------------------------------------- aggs
    def _agg_output_type(self, kind: str, arg: Optional[BExpr]) -> T.ColumnType:
        if kind in ("count", "count_star"):
            return T.INT64_T
        t = arg.type
        if kind == "sum":
            if t.is_decimal:
                return T.decimal_t(38, t.scale)
            if t.is_integer:
                return T.INT64_T
            if t.is_float:
                return T.FLOAT64_T
            raise AnalysisError(f"sum() over {t} not supported")
        if kind == "avg":
            if t.is_float:
                return T.FLOAT64_T
            if t.is_decimal or t.is_integer:
                scale = (t.scale if t.is_decimal else 0) + 6
                return T.decimal_t(38, scale)
            raise AnalysisError(f"avg() over {t} not supported")
        if kind in ("min", "max"):
            if t.is_text:
                raise UnsupportedFeatureError("min/max over text not supported yet")
            if t.kind == T.UUID:
                raise UnsupportedFeatureError(
                    "min/max over uuid not supported yet")
            return t
        raise AnalysisError(f"unknown aggregate {kind}")

    def _bind_agg_call(self, e: A.FuncCall, aggs: list[AggSpec]) -> BExpr:
        """Aggregate call -> AggSpec (deduplicated) -> BAggRef slot."""
        from citus_tpu.planner.aggregates import AGG_REGISTRY
        if e.filter is not None:
            e = rewrite_agg_filter(e)
        # the aggregate's own argument binds in row space, not key space
        saved_ctx, self._agg_ctx = self._agg_ctx, None
        try:
            if e.name in AGG_REGISTRY:
                spec = AGG_REGISTRY[e.name].bind(self, e)
            elif e.distinct and e.name in ("sum", "avg"):
                arg = self.bind_scalar(e.args[0])
                spec = AggSpec(f"{e.name}_distinct", arg,
                               self._agg_output_type(e.name, arg),
                               distinct=True)
            elif e.distinct and e.name in ("min", "max"):
                # DISTINCT is a no-op for extrema
                arg = self.bind_scalar(e.args[0])
                if arg.type.is_text:
                    from citus_tpu.planner.aggregates import bind_text_minmax
                    spec = bind_text_minmax(self, e.name, arg)
                else:
                    spec = AggSpec(e.name, arg,
                                   self._agg_output_type(e.name, arg))
            elif e.distinct and e.name not in ("count",):
                raise UnsupportedFeatureError(
                    f"DISTINCT is not supported for {e.name}()")
            elif e.name == "count" and (not e.args or isinstance(e.args[0], A.Star)):
                spec = AggSpec("count_star", None, T.INT64_T)
            else:
                if len(e.args) != 1:
                    raise AnalysisError(f"{e.name}() expects one argument")
                arg = self.bind_scalar(e.args[0])
                if e.name in ("min", "max") and arg.type.is_text:
                    from citus_tpu.planner.aggregates import bind_text_minmax
                    spec = bind_text_minmax(self, e.name, arg)
                else:
                    spec = AggSpec(e.name, arg, self._agg_output_type(e.name, arg),
                                   distinct=e.distinct)
            for i, existing in enumerate(aggs):
                if existing == spec:
                    return BAggRef(i, spec.out_type)
            aggs.append(spec)
            return BAggRef(len(aggs) - 1, spec.out_type)
        finally:
            self._agg_ctx = saved_ctx

    def bind_select_expr(self, e: A.Expr, key_map: dict[BExpr, int],
                         aggs: list[AggSpec]) -> BExpr:
        """Bind an output/having expression of a grouped query: aggregates
        become BAggRef slots, grouping-key subexpressions become BKeyRef."""
        from citus_tpu.planner.aggregates import AGG_REGISTRY
        if isinstance(e, A.FuncCall) and (e.name in AGG_FUNCS
                                          or e.name in AGG_REGISTRY):
            return self._bind_agg_call(e, aggs)
        # non-aggregate: try matching a group key by source expression
        # first (stable under dictionary growth), then structurally
        am = getattr(self, "_ast_key_map", None)
        if am is not None:
            try:
                idx = am.get(e)
            except TypeError:
                idx = None
            if idx is not None:
                return BKeyRef(idx, self._ast_key_types[idx])
        bound = self._try_bind_as_key(e, key_map)
        if bound is not None:
            return bound
        if isinstance(e, A.BinOp):
            left = self.bind_select_expr(e.left, key_map, aggs)
            right = self.bind_select_expr(e.right, key_map, aggs)
            return self._rebind_binop_from_bound(e.op, left, right)
        if isinstance(e, A.UnOp):
            inner = self.bind_select_expr(e.operand, key_map, aggs)
            if e.op == "-":
                return BUnOp("-", inner, inner.type)
            return BUnOp("not", self._to_bool(inner), T.BOOL_T)
        if isinstance(e, A.Cast):
            inner = self.bind_select_expr(e.expr, key_map, aggs)
            return BCast(inner, T.type_from_sql(e.type_name, list(e.type_args) or None))
        if isinstance(e, A.Literal):
            return self._bind_literal(e)
        if isinstance(e, (A.FuncCall, A.CaseExpr, A.Between, A.InList,
                          A.IsNull)):
            # scalar expression over aggregates / group keys —
            # round(avg(x), 2), coalesce(sum(x), 0), CASE WHEN count(*)...
            # Nested aggregates resolve to BAggRef and key references to
            # BKeyRef via the binding context; any raw column that
            # survives is a semantic error.
            saved_ctx, self._agg_ctx = self._agg_ctx, (key_map, aggs)
            try:
                bound = self.bind_scalar(e, allow_agg=True)
            finally:
                self._agg_ctx = saved_ctx
            stray = [n for n in referenced_columns(bound)]
            if stray:
                raise AnalysisError(
                    f"column {stray[0]!r} must appear in GROUP BY or be "
                    "used in an aggregate")
            return bound
        raise AnalysisError(
            f"expression {e} must appear in GROUP BY or be used in an aggregate")

    def _rebind_binop_from_bound(self, op: str, left: BExpr, right: BExpr) -> BExpr:
        if op in ("and", "or"):
            return BBinOp(op, self._to_bool(left), self._to_bool(right), T.BOOL_T)
        left, right = self._align(left, right)
        if op in ("=", "<>", "<", "<=", ">", ">="):
            return BBinOp(op, left, right, T.BOOL_T)
        out = T.arith_result_type(op, left.type, right.type)
        return BBinOp(op, left, right, out)

    def _try_bind_as_key(self, e: A.Expr, key_map: dict[BExpr, int]) -> Optional[BExpr]:
        try:
            bound = self.bind_scalar(e)
        except (AnalysisError, UnsupportedFeatureError):
            return None
        idx = key_map.get(bound)
        if idx is not None:
            return BKeyRef(idx, bound.type)
        if isinstance(bound, BLiteral):
            return bound
        return None


# ------------------------------------------------------------------ select


def infer_param_types(binder: Binder, stmt: A.Select, n_params: int) -> dict:
    """Infer $N parameter types from their comparison/arithmetic context
    (the reference gets them from the protocol's Bind message; we derive
    them from the query shape).  -> {0-based index: (type, text_src)}."""
    types: dict[int, tuple] = {}

    def try_bind(e):
        try:
            return binder.bind_scalar(e)
        except Exception:
            return None

    def note(pi: int, other: A.Expr):
        if pi in types:
            return
        bexp = try_bind(other)
        if bexp is None:
            return
        src = None
        if bexp.type.is_text:
            from citus_tpu.planner.bound import BColumn
            from citus_tpu.planner.bound import walk as bwalk
            for nd in bwalk(bexp):
                if isinstance(nd, BColumn) and nd.type.is_text:
                    src = binder.text_source(nd)
                    break
            if src is None:
                return
        types[pi] = (bexp.type, src)

    def visit(e):
        if not isinstance(e, A.Expr):
            return
        if isinstance(e, A.BinOp):
            if isinstance(e.left, A.Param) and not isinstance(e.right, A.Param):
                note(e.left.index - 1, e.right)
            if isinstance(e.right, A.Param) and not isinstance(e.left, A.Param):
                note(e.right.index - 1, e.left)
            visit(e.left)
            visit(e.right)
        elif isinstance(e, A.Between):
            for x in (e.lo, e.hi):
                if isinstance(x, A.Param):
                    note(x.index - 1, e.expr)
            if isinstance(e.expr, A.Param):
                for x in (e.lo, e.hi):
                    if not isinstance(x, A.Param):
                        note(e.expr.index - 1, x)
            visit(e.expr), visit(e.lo), visit(e.hi)
        elif isinstance(e, A.InList):
            for it in e.items:
                if isinstance(it, A.Param):
                    note(it.index - 1, e.expr)
            visit(e.expr)
            for it in e.items:
                visit(it)
        elif isinstance(e, A.Cast):
            if isinstance(e.expr, A.Param):
                types.setdefault(
                    e.expr.index - 1,
                    (T.type_from_sql(e.type_name, list(e.type_args) or None), None))
            visit(e.expr)
        elif isinstance(e, A.UnOp):
            visit(e.operand)
        elif isinstance(e, A.IsNull):
            visit(e.expr)
        elif isinstance(e, A.CaseExpr):
            for c, v in e.whens:
                visit(c), visit(v)
            if e.else_ is not None:
                visit(e.else_)
        elif isinstance(e, A.FuncCall):
            for a in e.args:
                visit(a)

    for item in stmt.items:
        visit(item.expr)
    visit(stmt.where)
    visit(stmt.having)
    for g in stmt.group_by:
        visit(g)
    for o in stmt.order_by:
        visit(o.expr)
    return types


def bind_select(catalog: Catalog, stmt: A.Select,
                param_count: int = 0) -> BoundSelect:
    if stmt.from_ is None:
        raise UnsupportedFeatureError("SELECT without FROM not supported")
    if isinstance(stmt.from_, A.Join):
        raise UnsupportedFeatureError("joins are handled by the join planner")
    assert isinstance(stmt.from_, A.TableRef)
    table = catalog.table(stmt.from_.name)
    # single relation: env keys stay unqualified, but qualified references
    # through the FROM alias (or table name) must still resolve
    alias = stmt.from_.alias or stmt.from_.name
    b = Binder(catalog, table, rels=[(alias, table)])
    if param_count:
        b.param_types = infer_param_types(b, stmt, param_count)
        if len(b.param_types) < param_count:
            missing = [i + 1 for i in range(param_count)
                       if i not in b.param_types]
            raise UnsupportedFeatureError(
                f"cannot infer types for parameters {missing}")

    # expand * early
    items: list[A.SelectItem] = []
    for item in stmt.items:
        if isinstance(item.expr, A.Star):
            for col in table.schema:
                items.append(A.SelectItem(A.ColumnRef(col.name), col.name))
        else:
            items.append(item)

    where = b.bind_scalar(stmt.where) if stmt.where is not None else None
    if where is not None and where.type.kind != T.BOOL:
        raise AnalysisError("WHERE must be boolean")

    # GROUP BY ordinals (GROUP BY 1, 2) refer to select-list positions
    group_exprs = []
    for g in stmt.group_by:
        if isinstance(g, A.Literal) and g.type_name == "int":
            idx = int(g.value) - 1
            if not (0 <= idx < len(items)):
                raise AnalysisError(f"GROUP BY position {g.value} out of range")
            group_exprs.append(items[idx].expr)
        else:
            group_exprs.append(g)
    group_keys = [b.bind_scalar(g) for g in group_exprs]
    key_map = {k: i for i, k in enumerate(group_keys)}
    # AST-level key matching: dictionary-remap expressions (lower(s), ...)
    # are not structurally stable across binds when the dictionary grew,
    # but the source expression text is
    b._ast_key_map = {}
    b._ast_key_types = [k.type for k in group_keys]
    for i, g in enumerate(group_exprs):
        try:
            b._ast_key_map.setdefault(g, i)
        except TypeError:
            pass
    # a uuid group key carries its low int64 lane as a hidden trailing
    # key, so grouping is exact over all 128 bits; finalize recombines
    # the pair by lane name.  Appending after key_map keeps BKeyRef
    # indices of the visible keys stable.
    for k in list(group_keys):
        if isinstance(k, BColumn) and k.type.kind == T.UUID:
            group_keys.append(BColumn(T.uuid_lane_name(k.name), T.INT64_T))

    has_agg_funcs = any(_contains_agg(i.expr) for i in items) or \
        (stmt.having is not None) or bool(group_keys)

    aggs: list[AggSpec] = []
    output_names: list[str] = []
    final_exprs: list[BExpr] = []
    if has_agg_funcs:
        for i, item in enumerate(items):
            final_exprs.append(b.bind_select_expr(item.expr, key_map, aggs))
            output_names.append(item.alias or _default_name(item.expr, i))
        having = None
        if stmt.having is not None:
            having = b.bind_select_expr(stmt.having, key_map, aggs)
            if having.type.kind != T.BOOL:
                raise AnalysisError("HAVING must be boolean")
    else:
        for i, item in enumerate(items):
            final_exprs.append(b.bind_scalar(item.expr))
            output_names.append(item.alias or _default_name(item.expr, i))
        having = None

    order_by: list[tuple[int, bool, Optional[bool]]] = []
    hidden = 0
    for oi in stmt.order_by:
        try:
            idx = _resolve_order_ref(oi.expr, items, output_names)
        except AnalysisError:
            # ORDER BY a non-output expression: append as a hidden column
            # (PostgreSQL semantics; forbidden with DISTINCT, like PG)
            if stmt.distinct:
                raise AnalysisError(
                    "for SELECT DISTINCT, ORDER BY expressions must appear "
                    "in the select list")
            if has_agg_funcs:
                bound_e = b.bind_select_expr(oi.expr, key_map, aggs)
            else:
                bound_e = b.bind_scalar(oi.expr)
            final_exprs.append(bound_e)
            output_names.append(f"__order_{hidden}")
            idx = len(final_exprs) - 1
            hidden += 1
        order_by.append((idx, oi.ascending, oi.nulls_first))

    # enum ORDER BY keys sort by declaration rank, not label text
    # (reference: enum ordering via enumsortorder): redirect to a hidden
    # rank column — functionally dependent on the enum value, so
    # DISTINCT results are unchanged
    from citus_tpu.planner.bound import BDictLookup
    for oi_pos, (idx, asc, nf) in enumerate(order_by):
        e_b = final_exprs[idx]
        under = e_b
        if isinstance(e_b, BKeyRef) and group_keys:
            under = group_keys[e_b.index]
        if not (isinstance(under, BColumn) and under.type.is_text):
            continue
        info = b.enum_info(under)
        if info is None:
            continue
        final_exprs.append(BDictLookup(e_b, Binder.enum_rank_lut(info)))
        output_names.append(f"__order_{hidden}")
        order_by[oi_pos] = (len(final_exprs) - 1, asc, nf)
        hidden += 1

    return BoundSelect(
        table=table, filter=where, group_keys=group_keys, aggs=aggs,
        final_exprs=final_exprs, output_names=output_names, having=having,
        order_by=order_by, limit=stmt.limit, offset=stmt.offset,
        distinct=stmt.distinct, hidden_outputs=hidden,
        param_specs=[b.param_types[i] for i in range(param_count)],
    )


def _contains_agg(e: A.Expr) -> bool:
    if isinstance(e, A.FuncCall):
        from citus_tpu.planner.aggregates import AGG_REGISTRY
        if e.name in AGG_FUNCS or e.name in AGG_REGISTRY:
            return True
        return any(_contains_agg(a) for a in e.args)
    if isinstance(e, A.BinOp):
        return _contains_agg(e.left) or _contains_agg(e.right)
    if isinstance(e, A.UnOp):
        return _contains_agg(e.operand)
    if isinstance(e, A.Cast):
        return _contains_agg(e.expr)
    if isinstance(e, A.Between):
        return _contains_agg(e.expr) or _contains_agg(e.lo) or _contains_agg(e.hi)
    if isinstance(e, A.CaseExpr):
        return any(_contains_agg(c) or _contains_agg(v) for c, v in e.whens) or \
            (e.else_ is not None and _contains_agg(e.else_))
    return False


def _default_name(e: A.Expr, i: int) -> str:
    if isinstance(e, A.ColumnRef):
        return e.name
    if isinstance(e, A.FuncCall):
        return e.name
    return f"column{i + 1}"


def _resolve_order_ref(e: A.Expr, items: list[A.SelectItem], names: list[str]) -> int:
    if isinstance(e, A.Literal) and isinstance(e.value, int) and e.value is not True:
        idx = e.value - 1
        if not (0 <= idx < len(items)):
            raise AnalysisError(f"ORDER BY position {e.value} out of range")
        return idx
    if isinstance(e, A.ColumnRef) and e.table is None and e.name in names:
        return names.index(e.name)
    # structural match against select items
    for i, item in enumerate(items):
        if item.expr == e:
            return i
    raise AnalysisError("ORDER BY expression must be an output column, alias, or position")
