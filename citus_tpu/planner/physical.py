"""Physical distributed planning.

From a BoundSelect this derives everything the executor needs:

- shard pruning: equality on the distribution column routes to one shard
  (reference: shard_pruning.c's PruneShards + the fast-path router)
- chunk pruning intervals from WHERE conjuncts (reference: the columnar
  CustomScan's ExtractPushdownClause + BuildBaseConstraint)
- the worker/combine aggregate split: every SQL aggregate lowers to a set
  of combinable partial ops — sum/count/min/max over expressions
  (reference: multi_logical_optimizer.c WorkerExtendedOpNode /
  MasterExtendedOpNode; avg becomes sum+count exactly as there)
- the GROUP BY strategy:
    * scalar  — no GROUP BY, one global group
    * direct  — composite key domain provably small (from skip-list
                stats / text dictionary sizes): exact gid scatter-add,
                combinable with a single psum — the north-star lowering
    * hash_host — unbounded key domain: the device still does scan,
                filter and agg-input evaluation; grouping happens on the
                host per shard and merges on the coordinator (analog of
                the reference pulling worker rows when aggregates can't
                be pushed down)
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from citus_tpu import types as T
from citus_tpu.catalog import Catalog, TableMeta
from citus_tpu.catalog.hashing import hash_int64_scalar
from citus_tpu.catalog.stats import column_bounds
from citus_tpu.planner.bind import AggSpec, BoundSelect
from citus_tpu.planner.bound import (
    BBinOp, BCast, BColumn, BDateTrunc, BExpr, BLiteral, BScale, BUnOp,
)
from citus_tpu.storage.reader import Interval


@dataclass(frozen=True)
class PartialOp:
    """One combinable per-shard accumulator."""
    kind: str        # sum | count | min | max | distinct | collect
    arg_index: int   # index into PhysicalPlan.agg_args; -1 = count rows
    dtype: str       # numpy dtype name of the accumulator
    # collect only: additional agg_arg indexes gathered alongside the
    # value (ordered aggregates collect (value, sortkey...) tuples)
    extra_args: tuple = ()


@dataclass
class AggExtract:
    """How to produce one SQL aggregate's value from partial slots."""
    kind: str        # sum | count | count_star | avg | min | max | registry
    slots: list[int] # indexes into partial op results
    out_type: T.ColumnType
    param: object = None  # registry-aggregate parameter (fraction, delim, ...)


@dataclass
class KeyDomain:
    lo: int          # physical minimum (code 0 is reserved for NULL)
    size: int        # number of codes including the NULL slot
    step: int = 1    # code stride in physical space (e.g. date_trunc unit)


@dataclass
class GroupMode:
    kind: str                      # scalar | direct | hash_host
    domains: list[KeyDomain] = field(default_factory=list)
    strides: list[int] = field(default_factory=list)
    n_groups: int = 1


@dataclass
class PhysicalPlan:
    bound: BoundSelect
    scan_columns: list[str]
    intervals: list[Interval]
    shard_indexes: list[int]        # shards that survived pruning
    group_mode: GroupMode
    agg_args: list[BExpr]           # deduped aggregate input expressions
    partial_ops: list[PartialOp]
    agg_extract: list[AggExtract]
    # executor-populated cache of jitted kernels; lives with the plan so a
    # plan cache hit skips XLA recompilation (the analog of the reference's
    # prepared-statement local plan cache, local_plan_cache.c)
    runtime_cache: dict = field(default_factory=dict)
    # distribution-key literal when the router path was chosen (tenant id)
    router_key: Optional[object] = None
    # deferred router pruning (reference: Job->deferredPruning): the
    # filter pins the distribution column to $N — the executor prunes to
    # one shard once the parameter value is bound, reusing this plan and
    # its jitted kernels across values
    router_param: Optional[int] = None
    # (column, physical value, index name) when an equality conjunct hits
    # a secondary index: the scan gathers exact rows via per-stripe
    # segments instead of reading every chunk
    index_eq: Optional[tuple] = None
    # shard-map size at plan time: a mismatch against the live table at
    # execution detects a shard split's catalog flip racing the scan
    # (shard_indexes would resolve against the NEW list) -> re-plan
    table_shard_count: int = -1

    @property
    def is_router(self) -> bool:
        return len(self.shard_indexes) == 1 and self.bound.table.is_distributed

    def resolve_shards(self, param_values: Optional[list]) -> list[int]:
        """Shard indexes for one execution; applies deferred pruning."""
        if self.router_param is None or param_values is None:
            return self.shard_indexes
        v = param_values[self.router_param]
        if v is None:
            return []  # dist = NULL matches nothing
        h = hash_int64_scalar(int(v))
        return [self.bound.table.route_hash(h)]


# ------------------------------------------------------------ pruning


def _conjuncts(e: Optional[BExpr]) -> list[BExpr]:
    if e is None:
        return []
    if isinstance(e, BBinOp) and e.op == "and":
        return _conjuncts(e.left) + _conjuncts(e.right)
    return [e]


def _strip_scale(e: BExpr) -> tuple[BExpr, int]:
    """Peel BScale so `col` compared at an adjusted scale still prunes."""
    if isinstance(e, BScale):
        return e.operand, e.power
    return e, 0


def extract_intervals(filter_: Optional[BExpr]) -> list[Interval]:
    """Chunk-pruning intervals from top-level AND conjuncts of the form
    column <op> literal (possibly scale-adjusted)."""
    out: list[Interval] = []
    for c in _conjuncts(filter_):
        if not (isinstance(c, BBinOp) and c.op in ("=", "<", "<=", ">", ">=")):
            continue
        left, lpow = _strip_scale(c.left)
        right, rpow = _strip_scale(c.right)
        col, lit, op = None, None, c.op
        if isinstance(left, BColumn) and isinstance(right, BLiteral):
            col, lit, colpow, litpow = left, right, lpow, rpow
        elif isinstance(right, BColumn) and isinstance(left, BLiteral):
            col, lit, colpow, litpow = right, left, rpow, lpow
            op = {"<": ">", "<=": ">=", ">": "<", ">=": "<="}.get(op, op)
        if col is None or lit is None or lit.value is None:
            continue
        if col.type.is_text:
            continue  # dictionary ids are not value-ordered
        # value seen by comparison = col * 10^colpow vs lit * 10^litpow
        # -> compare col against lit * 10^(litpow - colpow); only safe when
        # the adjustment is an integer scale-up of the literal
        shift = litpow - colpow
        v = lit.value
        if shift > 0:
            v = v * (10 ** shift)
        elif shift < 0:
            continue
        if op == "=":
            out.append(Interval(col.name, lo=v, hi=v))
        elif op == "<":
            out.append(Interval(col.name, hi=v, hi_inclusive=False))
        elif op == "<=":
            out.append(Interval(col.name, hi=v))
        elif op == ">":
            out.append(Interval(col.name, lo=v, lo_inclusive=False))
        elif op == ">=":
            out.append(Interval(col.name, lo=v))
    return out


def prune_shards(table: TableMeta, filter_: Optional[BExpr],
                 return_key: bool = False):
    """Route to a single shard on distcol = const (reference fast path:
    fast_path_router_planner.c); otherwise all shards."""
    all_idx = list(range(table.shard_count))
    key = None
    if not table.is_distributed or table.dist_column is None:
        return (all_idx, key) if return_key else all_idx
    for c in _conjuncts(filter_):
        if not (isinstance(c, BBinOp) and c.op == "="):
            continue
        left, right = c.left, c.right
        if isinstance(right, BColumn) and isinstance(left, BLiteral):
            left, right = right, left
        if (isinstance(left, BColumn) and left.name == table.dist_column
                and isinstance(right, BLiteral) and right.value is not None
                and not isinstance(right.value, float)):
            h = hash_int64_scalar(int(right.value))
            idx = table.route_hash(h)
            return ([idx], right.value) if return_key else [idx]
    return (all_idx, key) if return_key else all_idx


# ------------------------------------------------------ group strategy


def _key_domain(cat: Catalog, table: TableMeta, key: BExpr,
                bounds: dict[str, tuple]) -> Optional[KeyDomain]:
    """Provable physical domain of a group key, or None."""
    if isinstance(key, BColumn):
        if key.type.kind == T.UUID or T.is_uuid_lane(key.name):
            # 128-bit lane pairs have no enumerable domain
            return None
        if key.type.is_text:
            size = len(cat.dictionary(table.name, key.name))
            return KeyDomain(lo=0, size=size + 1)
        if key.type.kind == T.BOOL:
            return KeyDomain(lo=0, size=3)
        if key.type.is_float:
            # never direct-encode floats: -0.0/0.0 and NaN payloads
            # need the hash path's canonical equality, and NaN poisons
            # min/max stats (which would masquerade as "all null" here)
            return None
        b = bounds.get(key.name)
        if b is None:
            return KeyDomain(lo=0, size=1)  # no rows / all null
        lo, hi, _ = b
        return KeyDomain(lo=int(lo), size=int(hi) - int(lo) + 2)
    if isinstance(key, BDateTrunc):
        inner = _key_domain(cat, table, key.operand, bounds)
        if inner is None:
            return None
        units_date = {"day": 1, "week": 7}
        units_ts = {"minute": 60_000_000, "hour": 3_600_000_000,
                    "day": 86_400_000_000, "week": 7 * 86_400_000_000}
        unit = (units_date if key.operand.type.kind == T.DATE else units_ts).get(key.unit)
        if unit is None:
            return None
        off = 3 * (1 if key.operand.type.kind == T.DATE else 86_400_000_000) if key.unit == "week" else 0
        lo_t = ((inner.lo + off) // unit) * unit - off
        hi_raw = inner.lo + inner.size - 2
        hi_t = ((hi_raw + off) // unit) * unit - off
        n = (hi_t - lo_t) // unit + 1
        return KeyDomain(lo=int(lo_t), size=int(n) + 1, step=int(unit))
    return None


def choose_group_mode(cat: Catalog, bound: BoundSelect, direct_limit: int) -> GroupMode:
    # distinct and collect-based aggregates need exact value multisets:
    # only the host grouping path carries them (reference:
    # worker_partial_agg cannot combine DISTINCT either and falls back to
    # pulling rows)
    from citus_tpu.planner.aggregates import AGG_REGISTRY
    if any(a.distinct or (a.kind in AGG_REGISTRY
                          and AGG_REGISTRY[a.kind].needs_exact)
           for a in bound.aggs):
        return GroupMode(kind="hash_host")
    if not bound.group_keys:
        return GroupMode(kind="scalar")
    # sketch partials whose device shape exists only ungrouped route
    # grouped queries through host grouping
    if any(a.kind in AGG_REGISTRY and AGG_REGISTRY[a.kind].host_grouped
           for a in bound.aggs):
        return GroupMode(kind="hash_host")
    bounds = column_bounds(cat, bound.table)
    domains: list[KeyDomain] = []
    for key in bound.group_keys:
        d = _key_domain(cat, bound.table, key, bounds)
        if d is None:
            return GroupMode(kind="hash_host")
        domains.append(d)
    total = 1
    for d in domains:
        total *= d.size
        if total > direct_limit:
            return GroupMode(kind="hash_host")
    strides = []
    acc = 1
    for d in reversed(domains):
        strides.append(acc)
        acc *= d.size
    strides.reverse()
    return GroupMode(kind="direct", domains=domains, strides=strides, n_groups=total)


# ------------------------------------------------------ aggregate split


def lower_aggregates(aggs: list[AggSpec]) -> tuple[list[BExpr], list[PartialOp], list[AggExtract]]:
    """SQL aggregates -> deduped partial ops (the worker half) and
    extraction recipes (the combine/final half)."""
    agg_args: list[BExpr] = []
    partials: list[PartialOp] = []
    extracts: list[AggExtract] = []

    def arg_slot(e: BExpr) -> int:
        for i, a in enumerate(agg_args):
            if a == e:
                return i
        agg_args.append(e)
        return len(agg_args) - 1

    def partial_slot(kind: str, arg_index: int, dtype: str,
                     extra_args: tuple = ()) -> int:
        op = PartialOp(kind, arg_index, dtype, tuple(extra_args))
        for i, p in enumerate(partials):
            if p == op:
                return i
        partials.append(op)
        return len(partials) - 1

    for spec in aggs:
        if spec.kind == "count_star":
            s = partial_slot("count", -1, "int64")
            extracts.append(AggExtract("count_star", [s], spec.out_type))
            continue
        ai = arg_slot(spec.arg)
        acc_dtype = "float64" if spec.arg.type.is_float else "int64"
        if spec.kind == "count" and spec.distinct:
            s = partial_slot("distinct", ai, "int64")
            extracts.append(AggExtract("count_distinct", [s], spec.out_type))
        elif spec.kind == "count":
            s = partial_slot("count", ai, "int64")
            extracts.append(AggExtract("count", [s], spec.out_type))
        elif spec.kind in ("sum", "avg"):
            s = partial_slot("sum", ai, acc_dtype)
            c = partial_slot("count", ai, "int64")
            slots = [s, c]
            if acc_dtype == "int64" and spec.arg.type.is_numeric:
                # overflow guard (round-4 weak #7): an int64 partial sum
                # wraps silently; a float64 SHADOW sum of the same
                # argument rides alongside — int64 addition is exact mod
                # 2^64, so the final value is correct iff the true sum
                # fits, and |shadow| >= 2^62 proves it cannot (float
                # error is relative, far below the 2x margin).  The
                # reference's NUMERIC never overflows; we error instead
                # of silently wrapping.
                from citus_tpu.planner.bound import BCast
                fa = arg_slot(BCast(spec.arg, T.FLOAT64_T))
                slots.append(partial_slot("sum", fa, "float64"))
            extracts.append(AggExtract(spec.kind, slots, spec.out_type))
        elif spec.kind in ("min", "max"):
            dt = str(spec.arg.type.device_dtype)
            s = partial_slot(spec.kind, ai, dt)
            c = partial_slot("count", ai, "int64")
            extracts.append(AggExtract(spec.kind, [s, c], spec.out_type))
        else:
            from citus_tpu.planner.aggregates import AGG_REGISTRY
            defn = AGG_REGISTRY.get(spec.kind)
            if defn is None:
                raise AssertionError(spec.kind)
            extracts.append(defn.lower(spec, arg_slot, partial_slot))
    return agg_args, partials, extracts


# ------------------------------------------------------------ entry


def _deferred_router_param(table: TableMeta, filter_: Optional[BExpr]) -> Optional[int]:
    """distcol = $N in the filter -> parameter index for deferred pruning."""
    from citus_tpu.planner.bound import BParam
    if not table.is_distributed or table.dist_column is None:
        return None
    for c in _conjuncts(filter_):
        if not (isinstance(c, BBinOp) and c.op == "="):
            continue
        left, right = c.left, c.right
        if isinstance(right, BColumn) and isinstance(left, BParam):
            left, right = right, left
        if (isinstance(left, BColumn) and left.name == table.dist_column
                and isinstance(right, BParam) and not right.type.is_float):
            return right.index
    return None


def _index_eq(table: TableMeta, filter_: Optional[BExpr]):
    """(column, physical value, index name) when an AND conjunct pins an
    indexed column to a literal — the index point-lookup path (reference:
    an index path winning over ColumnarScan in the planner,
    columnar_customscan.c costing vs btree)."""
    for c in _conjuncts(filter_):
        if not (isinstance(c, BBinOp) and c.op == "="):
            continue
        left, right = c.left, c.right
        if isinstance(right, BColumn) and isinstance(left, BLiteral):
            left, right = right, left
        if not (isinstance(left, BColumn) and isinstance(right, BLiteral)
                and right.value is not None):
            continue
        ix = table.index_on(left.name)
        if ix is not None:
            return (left.name, right.value, ix["name"])
    return None


def plan_select(cat: Catalog, bound: BoundSelect, *, direct_limit: int = 65536) -> PhysicalPlan:
    intervals = extract_intervals(bound.filter)
    shard_indexes, router_key = prune_shards(bound.table, bound.filter, return_key=True)
    group_mode = choose_group_mode(cat, bound, direct_limit)
    agg_args, partial_ops, agg_extract = lower_aggregates(bound.aggs)
    return PhysicalPlan(
        bound=bound,
        scan_columns=bound.scan_columns,
        intervals=intervals,
        shard_indexes=shard_indexes,
        group_mode=group_mode,
        agg_args=agg_args,
        partial_ops=partial_ops,
        agg_extract=agg_extract,
        router_key=router_key,
        router_param=_deferred_router_param(bound.table, bound.filter),
        index_eq=_index_eq(bound.table, bound.filter),
        table_shard_count=len(bound.table.shards),
    )
