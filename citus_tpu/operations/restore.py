"""Cluster-consistent restore points.

Reference: citus_create_restore_point
(src/backend/distributed/operations/citus_create_restore_point.c) —
quiesces 2PC and creates a named WAL restore point on every node so
external backup tooling can restore the whole cluster to one instant.

Here data stripes are immutable-append, so a consistent snapshot is just
the metadata closure at one instant: the catalog document, every
placement's shard_meta/deletes side files, and the transaction log
position.  Restoring (external tooling's job in the reference; we ship
it) copies the metadata back — stripe files referenced by the snapshot
still exist unless VACUUM/TRUNCATE cleanup dropped them.
"""

from __future__ import annotations

import json
import os
import shutil
import time
from citus_tpu.utils.clock import now as wall_now

from citus_tpu.catalog import Catalog
from citus_tpu.errors import CatalogError
from citus_tpu.storage.deletes import DELETES_FILE
from citus_tpu.storage.writer import SHARD_META


def _root(cat: Catalog) -> str:
    return os.path.join(cat.data_dir, "restore_points")


def create_restore_point(cat: Catalog, name: str) -> str:
    if "/" in name or name.startswith("."):
        raise CatalogError(f"invalid restore point name {name!r}")
    dst = os.path.join(_root(cat), name)
    if os.path.isdir(dst):
        raise CatalogError(f"restore point {name!r} already exists")
    os.makedirs(dst)
    shutil.copy2(os.path.join(cat.data_dir, Catalog.FILE), os.path.join(dst, Catalog.FILE))
    # dictionaries (small) + every placement's metadata side files
    for f in os.listdir(cat.data_dir):
        if f.startswith("dict__"):
            shutil.copy2(os.path.join(cat.data_dir, f), os.path.join(dst, f))
    metas = []
    data_root = os.path.join(cat.data_dir, "data")
    if os.path.isdir(data_root):
        for root, _dirs, files in os.walk(data_root):
            rel = os.path.relpath(root, cat.data_dir)
            for f in files:
                if f in (SHARD_META, DELETES_FILE):
                    os.makedirs(os.path.join(dst, rel), exist_ok=True)
                    shutil.copy2(os.path.join(root, f), os.path.join(dst, rel, f))
                    metas.append(os.path.join(rel, f))
    with open(os.path.join(dst, "restore_point.json"), "w") as fh:
        json.dump({"name": name, "created_at": wall_now(), "metas": metas}, fh)
    return dst


def list_restore_points(cat: Catalog) -> list[tuple]:
    root = _root(cat)
    if not os.path.isdir(root):
        return []
    out = []
    for name in sorted(os.listdir(root)):
        info = os.path.join(root, name, "restore_point.json")
        if os.path.exists(info):
            with open(info) as fh:
                d = json.load(fh)
            out.append((name, d["created_at"]))
    return out


def restore_to_point(cat: Catalog, name: str) -> None:
    """Copy the snapshot's metadata back over the live cluster.  The
    caller must reopen the Cluster afterwards."""
    src = os.path.join(_root(cat), name)
    if not os.path.isdir(src):
        raise CatalogError(f"restore point {name!r} does not exist")
    with open(os.path.join(src, "restore_point.json")) as fh:
        info = json.load(fh)
    shutil.copy2(os.path.join(src, Catalog.FILE), os.path.join(cat.data_dir, Catalog.FILE))
    for f in os.listdir(src):
        if f.startswith("dict__"):
            shutil.copy2(os.path.join(src, f), os.path.join(cat.data_dir, f))
    # restore side files; remove deletes files that didn't exist then
    for rel in info["metas"]:
        live = os.path.join(cat.data_dir, rel)
        os.makedirs(os.path.dirname(live), exist_ok=True)
        shutil.copy2(os.path.join(src, rel), live)
    snap_metas = set(info["metas"])
    data_root = os.path.join(cat.data_dir, "data")
    if os.path.isdir(data_root):
        for root, _dirs, files in os.walk(data_root):
            rel_dir = os.path.relpath(root, cat.data_dir)
            for f in files:
                if f == DELETES_FILE and os.path.join(rel_dir, f) not in snap_metas:
                    os.remove(os.path.join(root, f))
