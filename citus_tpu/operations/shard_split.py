"""Shard splitting.

Reference: citus_split_shard_by_split_points / SplitShard
(src/backend/distributed/operations/shard_split.c:441) — a shard's hash
range splits at given points; colocated shards split together; data
redistributes into the new shards; old shards are deferred-dropped.

The reference needs a blocking or logical-replication flavor; here the
split reads the immutable stripes, routes rows into the new sub-ranges
by distribution-column hash, and flips the catalog atomically.
"""

from __future__ import annotations

import os

import numpy as np

from citus_tpu.catalog import Catalog
from citus_tpu.catalog.hashing import hash_int64
from citus_tpu.errors import CatalogError
from citus_tpu.operations.cleaner import DEFERRED_ON_SUCCESS, record_cleanup
from citus_tpu.operations.shard_transfer import _colocated_shards, _find_shard
from citus_tpu.services.background_jobs import report_progress
from citus_tpu.storage import ShardReader, ShardWriter


def split_shard(cat: Catalog, shard_id: int, split_points: list[int],
                target_nodes: list[int] | None = None,
                lock_manager=None) -> list[int]:
    """Split a hash shard at ``split_points`` (inclusive upper bounds of
    the leading sub-ranges).  Returns the new shard ids of the first
    table in the colocation group.

    Blocking split (reference: BlockingShardSplit, shard_split.c:554):
    the data redistribution reads a point-in-time snapshot, so writers
    are excluded for the whole redistribute + flip via the colocation
    group's write lock."""
    from citus_tpu.transaction.write_locks import EXCLUSIVE, group_write_lock

    table, shard = _find_shard(cat, shard_id)
    with group_write_lock(cat, table, EXCLUSIVE, lock_manager=lock_manager):
        return _split_shard_locked(cat, table, shard, shard_id, split_points,
                                   target_nodes)


def _split_shard_locked(cat, table, shard, shard_id, split_points,
                        target_nodes) -> list[int]:
    if not table.is_distributed:
        raise CatalogError("can only split shards of hash-distributed tables")
    lo, hi = shard.hash_min, shard.hash_max
    points = sorted(set(int(p) for p in split_points))
    for p in points:
        if not (lo <= p < hi):
            raise CatalogError(
                f"split point {p} outside shard range [{lo}, {hi})")
    if not points:
        raise CatalogError("no split points given")
    bounds = []
    cur = lo
    for p in points:
        bounds.append((cur, p))
        cur = p + 1
    bounds.append((cur, hi))
    n_new = len(bounds)
    if target_nodes is None:
        target_nodes = [shard.placements[0]] * n_new
    if len(target_nodes) != n_new:
        raise CatalogError(f"expected {n_new} target nodes")
    for nid in target_nodes:
        if nid not in cat.nodes:
            raise CatalogError(f"node {nid} does not exist")

    group = _colocated_shards(cat, table, shard)
    new_ids_first: list[int] = []
    # allocate new shard ids per table, identical sub-range layout
    plan = []  # (t, old_shard, [new ShardMeta])
    from citus_tpu.catalog.catalog import ShardMeta
    for t, s in group:
        news = []
        for bi, (blo, bhi) in enumerate(bounds):
            news.append(ShardMeta(cat._alloc_shard_id(), 0, blo, bhi,
                                  [target_nodes[bi]]))
        plan.append((t, s, news))
        if t.name == table.name:
            new_ids_first = [n.shard_id for n in news]

    # phase 1: write redistributed data for every member table
    bytes_total = 0
    for t, s, _news in plan:
        for node in s.placements:
            src = cat.shard_dir(t.name, s.shard_id, node)
            if os.path.isdir(src):
                bytes_total += sum(
                    os.path.getsize(os.path.join(src, n))
                    for n in os.listdir(src) if n.endswith(".cts"))
                break  # mirror the single-source redistribute below
    report_progress(phase="copy", bytes_done=0, bytes_total=bytes_total)
    for t, s, news in plan:
        if t.dist_column is None:
            raise CatalogError(f"table {t.name} has no distribution column")
        for node in s.placements:
            src = cat.shard_dir(t.name, s.shard_id, node)
            if not os.path.isdir(src):
                continue
            reader = ShardReader(src, t.schema)
            writers = {}
            for bi, ns in enumerate(news):
                writers[bi] = ShardWriter(
                    cat.shard_dir(t.name, ns.shard_id, target_nodes[bi]),
                    t.schema, chunk_row_limit=t.chunk_row_limit,
                    stripe_row_limit=t.stripe_row_limit,
                    codec=t.compression, level=t.compression_level,
                    index_columns=tuple(t.index_columns))
            for batch in reader.scan(t.schema.names):
                h = hash_int64(batch.values[t.dist_column].astype(np.int64))
                for bi, (blo, bhi) in enumerate(bounds):
                    sel = (h >= blo) & (h <= bhi)
                    if not sel.any():
                        continue
                    vals = {c: batch.values[c][sel] for c in t.schema.names}
                    valid = {c: (batch.validity[c][sel]
                                 if batch.validity[c] is not None
                                 else np.ones(int(sel.sum()), bool))
                             for c in t.schema.names}
                    writers[bi].append_batch(vals, valid)
            for w in writers.values():
                w.flush()
            # whole source placement redistributed: book its stripe bytes
            report_progress(add_bytes=sum(
                os.path.getsize(os.path.join(src, n))
                for n in os.listdir(src) if n.endswith(".cts")))
            break  # one placement is the source of truth; replicas re-copy later

    # phase 2: catalog flip (atomic commit covers the whole group).
    # Bracketed in the snapshot flip generation: a reader whose scan
    # overlaps the shard-map swap would otherwise resolve its planned
    # shard indexes against the NEW shard list (torn: half-shards read
    # as whole, the tail shard missed) — the generation bump makes it
    # retry with a re-planned shard set (executor/executor.py).
    from citus_tpu.transaction.snapshot import flip_generation
    report_progress(phase="flip")
    with flip_generation(cat.data_dir, table):
        for t, s, news in plan:
            idx = t.shards.index(s)
            t.shards = t.shards[:idx] + news + t.shards[idx + 1:]
            for i, sh in enumerate(t.shards):
                sh.index = i
            t.version += 1
        cat.ddl_epoch += 1
        cat.commit()

    # phase 3: deferred drop of old placements
    report_progress(phase="cleanup")
    for t, s, _news in plan:
        for node in s.placements:
            d = cat.shard_dir(t.name, s.shard_id, node)
            if os.path.isdir(d):
                record_cleanup(cat, d, DEFERRED_ON_SUCCESS)
    return new_ids_first
