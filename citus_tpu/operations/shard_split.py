"""Shard splitting.

Reference: citus_split_shard_by_split_points / SplitShard
(src/backend/distributed/operations/shard_split.c:441) — a shard's hash
range splits at given points; colocated shards split together; data
redistributes into the new shards; old shards are deferred-dropped.

The split rides the same non-blocking sequence as a shard move
(operations/shard_transfer.py, reference: NonBlockingShardSplit,
shard_split.c:1100): the snapshot redistribute runs with writers live,
then catch-up rounds route only the stripes that appeared since the
last pass (stripes are immutable-append, so new data IS new stripe
files, scanned via ``only_stripes``), and only the final micro
catch-up + catalog flip runs under the colocation group's EXCLUSIVE
write lock.  Deletion bitmaps are the one mutable input: every routed
stripe is read against the bitmap snapshot taken when it was
processed, and a later DELETE against an already-routed stripe marks
the redistribute dirty — rows can't be un-routed, so the pass restarts
from a fresh snapshot (unlocked, bounded by the catch-up round budget)
or redoes the redistribute under the lock as the blocking fallback.
Failure/crash recovery is the move's: operation registry + ON_FAILURE
targets + pre-flip ON_SUCCESS sources, resolved by complete_operation
or adopted by the cleaner against the committed catalog.
"""

from __future__ import annotations

import os

import numpy as np

from citus_tpu.catalog import Catalog
from citus_tpu.catalog.hashing import hash_int64
from citus_tpu.errors import CatalogError
from citus_tpu.operations.cleaner import (
    ON_FAILURE, ON_SUCCESS, complete_operation, mark_operation_phase,
    record_cleanup, register_operation, try_drop_orphaned_resources,
)
from citus_tpu.operations.shard_transfer import (
    MOVE_STATS, _colocated_shards, _counters, _find_shard, run_catchup_loop,
)
from citus_tpu.services.background_jobs import report_progress
from citus_tpu.storage import ShardReader, ShardWriter
from citus_tpu.storage.deletes import _decode, load_deletes


def _snapshot_mask(src: str, batch, snapshot: dict[str, str]):
    """Deleted-rows mask for one chunk batch, decoded from the bitmap
    SNAPSHOT recorded when this pass started — not the live file — so
    every stripe is routed against exactly one point-in-time bitmap
    and a racing DELETE can only surface as a dirty restart, never as
    a half-applied mask."""
    h = snapshot.get(batch.stripe_file)
    if h is None:
        return None
    n = batch.chunk_row_offset + batch.row_count
    m = _decode(h, n)
    if m.size < n:  # defensive: bitmap shorter than the stripe grew
        m = np.pad(m, (0, n - m.size))
    return m[batch.chunk_row_offset:]


def _route_pass(cat: Catalog, t, src: str, new_files: list[str],
                snapshot: dict[str, str], bounds, news,
                target_nodes) -> int:
    """Route ``new_files``'s rows of one source placement into the new
    sub-range shards; returns stripe bytes processed.  Writers append
    to the target placements (ShardWriter continues an existing dir),
    so each catch-up round only pays for the delta."""
    reader = ShardReader(src, t.schema)
    writers = {}
    for bi, ns in enumerate(news):
        writers[bi] = ShardWriter(
            cat.shard_dir(t.name, ns.shard_id, target_nodes[bi]),
            t.schema, chunk_row_limit=t.chunk_row_limit,
            stripe_row_limit=t.stripe_row_limit,
            codec=t.compression, level=t.compression_level,
            index_columns=tuple(t.index_columns))
    only = set(new_files)
    pnames = t.schema.physical_names()
    for batch in reader.scan(pnames, apply_deletes=False,
                             only_stripes=only):
        keep = _snapshot_mask(src, batch, snapshot)
        h = hash_int64(batch.values[t.dist_column].astype(np.int64))
        alive = ~keep if keep is not None else None
        for bi, (blo, bhi) in enumerate(bounds):
            sel = (h >= blo) & (h <= bhi)
            if alive is not None:
                sel = sel & alive
            if not sel.any():
                continue
            vals = {c: batch.values[c][sel] for c in pnames}
            valid = {c: (batch.validity[c][sel]
                         if batch.validity[c] is not None
                         else np.ones(int(sel.sum()), bool))
                     for c in pnames}
            writers[bi].append_batch(vals, valid)
    for w in writers.values():
        w.flush()
    bytes_done = sum(os.path.getsize(os.path.join(src, n))
                     for n in new_files
                     if os.path.exists(os.path.join(src, n)))
    report_progress(add_bytes=bytes_done)
    return bytes_done


def _clear_targets(cat: Catalog, plan, target_nodes) -> None:
    """Dirty restart: drop everything routed so far (a DELETE landed on
    an already-routed stripe; its rows can't be un-routed in place)."""
    import shutil
    for t, _s, news in plan:
        for bi, ns in enumerate(news):
            d = cat.shard_dir(t.name, ns.shard_id, target_nodes[bi])
            if os.path.isdir(d):
                shutil.rmtree(d)


def _redistribute_pass(cat: Catalog, plan, bounds, target_nodes,
                       state: dict, *, locked: bool) -> int | str:
    """One incremental redistribute pass over every member table.
    ``state`` maps source dir -> {stripe_file: deletes hex (or None) at
    the time the stripe was routed}.  Returns bytes processed, or the
    sentinel "dirty" when an already-routed stripe's bitmap changed and
    the caller must restart from scratch (unlocked) — under the lock
    the restart happens inline, writers are already excluded."""
    processed = 0
    for t, s, news in plan:
        if t.dist_column is None:
            raise CatalogError(f"table {t.name} has no distribution column")
        for node in s.placements:
            src = cat.shard_dir(t.name, s.shard_id, node)
            if not os.path.isdir(src):
                continue
            seen = state.setdefault(src, {})
            live = load_deletes(src)
            if any(live.get(f) != h for f, h in seen.items()):
                if not locked:
                    return "dirty"
                _clear_targets(cat, plan, target_nodes)
                state.clear()
                return _redistribute_pass(cat, plan, bounds, target_nodes,
                                          state, locked=True)
            stripes = [st["file"] for st in ShardReader(src, t.schema)
                       .meta["stripes"]]
            new_files = [f for f in stripes if f not in seen]
            if new_files:
                processed += _route_pass(cat, t, src, new_files, live,
                                         bounds, news, target_nodes)
                for f in new_files:
                    seen[f] = live.get(f)
            break  # one placement is the source of truth; replicas re-copy later
    return processed


def split_shard(cat: Catalog, shard_id: int, split_points: list[int],
                target_nodes: list[int] | None = None,
                lock_manager=None, settings=None) -> list[int]:
    """Split a hash shard at ``split_points`` (inclusive upper bounds of
    the leading sub-ranges).  Returns the new shard ids of the first
    table in the colocation group.  Non-blocking (module doc): writers
    are excluded only for the final micro catch-up + catalog flip."""
    from citus_tpu.observability.trace import clock
    from citus_tpu.testing.faults import FAULTS
    from citus_tpu.transaction.branches import commit_metadata_flip
    from citus_tpu.transaction.snapshot import flip_generation
    from citus_tpu.transaction.write_locks import EXCLUSIVE, group_write_lock
    if settings is None:
        from citus_tpu.config import current_settings
        settings = current_settings()

    table, shard = _find_shard(cat, shard_id)
    if not table.is_distributed:
        raise CatalogError("can only split shards of hash-distributed tables")
    lo, hi = shard.hash_min, shard.hash_max
    points = sorted(set(int(p) for p in split_points))
    for p in points:
        if not (lo <= p < hi):
            raise CatalogError(
                f"split point {p} outside shard range [{lo}, {hi})")
    if not points:
        raise CatalogError("no split points given")
    bounds = []
    cur = lo
    for p in points:
        bounds.append((cur, p))
        cur = p + 1
    bounds.append((cur, hi))
    n_new = len(bounds)
    if target_nodes is None:
        target_nodes = [shard.placements[0]] * n_new
    if len(target_nodes) != n_new:
        raise CatalogError(f"expected {n_new} target nodes")
    for nid in target_nodes:
        if nid not in cat.nodes:
            raise CatalogError(f"node {nid} does not exist")

    group = _colocated_shards(cat, table, shard)
    new_ids_first: list[int] = []
    # allocate new shard ids per table, identical sub-range layout
    plan = []  # (t, old_shard, [new ShardMeta])
    from citus_tpu.catalog.catalog import ShardMeta
    for t, s in group:
        news = []
        for bi, (blo, bhi) in enumerate(bounds):
            news.append(ShardMeta(cat._alloc_shard_id(), 0, blo, bhi,
                                  [target_nodes[bi]]))
        plan.append((t, s, news))
        if t.name == table.name:
            new_ids_first = [n.shard_id for n in news]

    import uuid
    op_id = uuid.uuid4().int & ((1 << 62) - 1)
    register_operation(cat, op_id, kind="split_shard")
    for t, _s, news in plan:
        for bi, ns in enumerate(news):
            d = cat.shard_dir(t.name, ns.shard_id, target_nodes[bi])
            if not os.path.isdir(d):
                record_cleanup(cat, d, ON_FAILURE, operation_id=op_id)

    bytes_total = 0
    for t, s, _news in plan:
        for node in s.placements:
            src = cat.shard_dir(t.name, s.shard_id, node)
            if os.path.isdir(src):
                bytes_total += sum(
                    os.path.getsize(os.path.join(src, n))
                    for n in os.listdir(src) if n.endswith(".cts"))
                break  # mirror the single-source redistribute
    report_progress(phase="copy", bytes_done=0, bytes_total=bytes_total)
    t_start = clock()
    catchup_rounds = 0
    blocked_ms = 0.0
    state: dict = {}  # source dir -> {stripe_file: routed-against bitmap}
    try:
        # phase 1: snapshot redistribute with writers live
        FAULTS.hit("shard_move_copy", f"split:{table.name}:{shard_id}")
        _redistribute_pass(cat, plan, bounds, target_nodes, state,
                           locked=False)
        # phase 2: catch-up rounds — new stripes only; a dirty bitmap
        # restarts the snapshot (still unlocked, still bounded)
        report_progress(phase="catchup")
        mark_operation_phase(cat, op_id, "catchup")
        member_tables = sorted({t.name for t, _ in group})

        def _catchup_pass() -> int:
            r = _redistribute_pass(cat, plan, bounds, target_nodes, state,
                                   locked=False)
            if r == "dirty":
                _clear_targets(cat, plan, target_nodes)
                state.clear()
                r = _redistribute_pass(cat, plan, bounds, target_nodes,
                                       state, locked=False)
            return r if isinstance(r, int) else 1  # dirty again: not converged

        catchup_rounds = run_catchup_loop(
            cat, _catchup_pass, member_tables, settings=settings,
            fault_context=f"split:{table.name}:{shard_id}")
        # phase 3: exclude writers for the final micro catch-up + flip
        report_progress(phase="flip")
        with group_write_lock(cat, table, EXCLUSIVE,
                              lock_manager=lock_manager):
            t_block = clock()
            FAULTS.hit("shard_move_flip", f"split:{table.name}:{shard_id}")
            _redistribute_pass(cat, plan, bounds, target_nodes, state,
                               locked=True)
            # pre-flip ON_SUCCESS records for the old placements: the
            # decision record (the committed flip) then owns their fate
            for t, s, _news in plan:
                for node in s.placements:
                    d = cat.shard_dir(t.name, s.shard_id, node)
                    if os.path.isdir(d):
                        record_cleanup(cat, d, ON_SUCCESS,
                                       operation_id=op_id)

            def _flip():
                for t, s, news in plan:
                    idx = t.shards.index(s)
                    t.shards = t.shards[:idx] + news + t.shards[idx + 1:]
                    for i, sh in enumerate(t.shards):
                        sh.index = i
                    t.version += 1
                cat.ddl_epoch += 1

            # Bracketed in the snapshot flip generation: a reader whose
            # scan overlaps the shard-map swap would otherwise resolve
            # its planned shard indexes against the NEW shard list
            # (torn: half-shards read as whole, the tail shard missed)
            # — the generation bump makes it retry with a re-planned
            # shard set (executor/executor.py).
            with flip_generation(cat.data_dir, table):
                commit_metadata_flip(cat, op_id, _flip)
            blocked_ms = (clock() - t_block) * 1000.0
    except BaseException:
        complete_operation(cat, op_id, success=False)  # cleaner drops targets
        raise
    complete_operation(cat, op_id, success=True)
    _counters().bump("shard_move_blocked_write_ms", max(1, int(blocked_ms)))
    MOVE_STATS.record(
        op="split", shard_id=shard_id, source=shard.placements[0],
        target=-1 if len(set(target_nodes)) > 1 else target_nodes[0],
        bytes_copied=bytes_total, catchup_rounds=catchup_rounds,
        blocked_write_ms=round(blocked_ms, 3),
        total_ms=round((clock() - t_start) * 1000.0, 3))
    # phase 4: deferred drop of the old placements (ON_SUCCESS → ALWAYS)
    report_progress(phase="cleanup")
    if not settings.sharding.defer_drop_after_shard_move:
        try_drop_orphaned_resources(cat)
    return new_ids_first
