"""Table redistribution: undistribute_table / alter_distributed_table.

Reference: src/backend/distributed/commands/alter_table.c —
alter_distributed_table (change shard count / distribution column /
colocation) and undistribute_table both work by creating a new table,
moving the data, and swapping names under locks.  Here the swap is a
catalog update: read every live row, rewrite the shard layout, re-ingest
(hash routing handles the new layout), then defer-clean the old files.
"""

from __future__ import annotations

import os
from typing import Optional

import numpy as np

from citus_tpu.catalog import Catalog, DistributionMethod
from citus_tpu.errors import CatalogError
from citus_tpu.operations.cleaner import DEFERRED_ON_SUCCESS, record_cleanup
from citus_tpu.storage import ShardReader


def _collect_all_rows(cat: Catalog, table) -> tuple[dict, dict, int]:
    """Read every live row of a table into column arrays (PHYSICAL
    column space: uuid columns carry their lane stream alongside)."""
    names = table.schema.physical_names()
    vals = {c: [] for c in names}
    valid = {c: [] for c in names}
    total = 0
    for shard in table.shards:
        d = cat.shard_dir(table.name, shard.shard_id, shard.placements[0])
        if not os.path.isdir(d):
            continue
        reader = ShardReader(d, table.schema)
        for batch in reader.scan(names):
            for c in names:
                vals[c].append(batch.values[c])
                m = batch.validity[c]
                valid[c].append(np.ones(batch.row_count, bool) if m is None else m)
            total += batch.row_count
    out_v = {c: (np.concatenate(v) if v else
                 np.zeros(0, table.schema.scan_dtype(c)))
             for c, v in vals.items()}
    out_m = {c: (np.concatenate(m) if m else np.zeros(0, bool))
             for c, m in valid.items()}
    return out_v, out_m, total


def _record_old_placements(cat: Catalog, table) -> None:
    for shard in table.shards:
        for node in shard.placements:
            d = cat.shard_dir(table.name, shard.shard_id, node)
            if os.path.isdir(d):
                record_cleanup(cat, d, DEFERRED_ON_SUCCESS)


def _reingest(cat: Catalog, table, values, validity, txlog) -> None:
    from citus_tpu.ingest import TableIngestor
    if len(next(iter(values.values()), [])) == 0:
        return
    ing = TableIngestor(cat, table, txlog=txlog)
    ing.append(values, validity)
    ing.finish()


def undistribute_table(cat: Catalog, name: str, txlog=None) -> None:
    t = cat.table(name)
    if t.method == DistributionMethod.LOCAL:
        raise CatalogError(f'table "{name}" is not distributed')
    values, validity, _ = _collect_all_rows(cat, t)
    import contextlib as _ctxlib

    from citus_tpu.transaction.snapshot import flip_generation
    from citus_tpu.transaction.write_locks import group_resource
    # the whole shard-map swap + re-ingest is one flip to readers: a
    # scan overlapping it retries (and re-plans on the shard-count
    # change) instead of seeing empty new shards (executor/executor.py).
    # The swap changes the colocation group, so hold BOTH identities.
    with _ctxlib.ExitStack() as _flips:
        _flips.enter_context(flip_generation(cat.data_dir, t))
        old_res = group_resource(t)
        # post-swap identity is knowable upfront (local => colocation 0):
        # register its flip BEFORE the mutation is reader-visible, or a
        # reader binding mid-swap validates a quiet new group and scans
        # the still-empty local shard as a consistent image
        from types import SimpleNamespace
        new_ident = SimpleNamespace(name=name, colocation_id=0)
        if group_resource(new_ident) != old_res:
            _flips.enter_context(flip_generation(cat.data_dir, new_ident))
        _record_old_placements(cat, t)
        from citus_tpu.catalog.catalog import ShardMeta
        t.method = DistributionMethod.LOCAL
        t.dist_column = None
        t.colocation_id = 0
        t.shards = [ShardMeta(cat._alloc_shard_id(), 0, placements=[0])]
        t.version += 1
        cat.ddl_epoch += 1
        cat.commit()
        _reingest(cat, t, values, validity, txlog)


def alter_distributed_table(cat: Catalog, name: str, *,
                            shard_count: Optional[int] = None,
                            distribution_column: Optional[str] = None,
                            colocate_with: Optional[str] = None,
                            txlog=None) -> None:
    t = cat.table(name)
    if not t.is_distributed:
        raise CatalogError(f'table "{name}" is not distributed')
    new_count = shard_count or t.shard_count
    new_col = distribution_column or t.dist_column
    values, validity, _ = _collect_all_rows(cat, t)
    import contextlib as _ctxlib

    from citus_tpu.transaction.snapshot import flip_generation
    from citus_tpu.transaction.write_locks import group_resource
    # the swap CHANGES the table's colocation group, so readers may
    # validate against either identity: hold the flip bracket on BOTH
    # (old group entered first, new group entered as soon as it exists)
    # for the whole swap + re-ingest window
    with _ctxlib.ExitStack() as _flips:
        _flips.enter_context(flip_generation(cat.data_dir, t))
        old_res = group_resource(t)
        # register the flip on the POST-swap identity BEFORE mutating:
        # the shared TableMeta is reader-visible the instant
        # distribute_table assigns the new shard list, and a reader
        # binding in that window validates against the NEW colocation
        # group — it must already see a writer mid-flip there, or it
        # reads the not-yet-reingested (empty) shards as a clean scan
        from types import SimpleNamespace
        new_id = cat.resolve_colocation_id(name, new_col, new_count,
                                           colocate_with)
        new_ident = SimpleNamespace(name=name, colocation_id=new_id)
        if group_resource(new_ident) != old_res:
            _flips.enter_context(flip_generation(cat.data_dir, new_ident))
        _record_old_placements(cat, t)
        cat.distribute_table(name, new_col, new_count,
                             cat.active_node_ids(),
                             colocate_with=colocate_with,
                             colocation_id=new_id)
        t.version += 1
        cat.commit()
        _reingest(cat, t, values, validity, txlog)
