"""Dry-run rebalance planning: cost strategies over observed state.

Reference: pg_dist_rebalance_strategy's pluggable cost functions
(SURVEY §2.10's strategy table — by-shard-count, by-disk-size,
by-observed-load) feeding the same greedy balance loop as
operations/rebalancer.py, but *never executing anything*: the output is
an ordered list of move/split/isolate steps with per-step
expected-benefit scores, surfaced as ``SELECT
citus_rebalance_plan(strategy)`` and consumed by the autopilot
(services/autopilot.py).

Strategies
----------
``by_shard_count``
    every colocation group slot weighs 1.0 — pure placement spreading.
``by_bytes``
    placement stripe bytes on disk (the reference's by_disk_size).
``by_observed_load``
    EWMA'd device-ms/s rates from the per-placement attribution ledger
    (observability/load_attribution.py) — the load actually observed
    landing on each placement, not a proxy for it.

Beyond moves, the planner recognizes two shapes a move cannot fix:

* a single group slot so heavy that no move narrows the gap — the
  hottest shard itself must **split** (actuator: split_shard);
* one tenant dominating the hottest placement under
  ``by_observed_load`` — that tenant should be **isolated** to its own
  placement (actuator: isolate_tenant_to_node) rather than dragging
  every colocated tenant through a move.

Determinism: for a fixed catalog + attribution snapshot the plan is a
pure function — every choice breaks ties on (cost, node id, slot key),
and reading attribution rates never advances them (``tick()`` is
sampler-driven).  Calling this module has no side effects whatsoever.
"""

from __future__ import annotations

from dataclasses import dataclass

from citus_tpu.catalog import Catalog
from citus_tpu.operations.rebalancer import _placement_cost

PLAN_STRATEGIES = ("by_shard_count", "by_bytes", "by_observed_load")

#: a lone tenant carrying at least this share of the hottest
#: placement's device ms is an isolation candidate, not a move
ISOLATE_TENANT_SHARE = 0.6


@dataclass(frozen=True)
class PlanStep:
    """One dry-run action.  ``score`` is the expected benefit: the
    fraction of the current hi-lo load gap this step closes (1.0 =
    perfectly balancing), so steps compare across strategies."""
    action: str            # "move" | "split" | "isolate"
    table: str
    shard_id: int
    source_node: int
    target_node: int
    cost: float            # strategy units moved / split / isolated
    score: float
    reason: str

    def to_row(self, seq: int):
        return (seq, self.action, self.table, self.shard_id,
                self.source_node, self.target_node,
                round(float(self.cost), 3), round(float(self.score), 4),
                self.reason)


PLAN_COLUMNS = ("step", "action", "table_name", "shard_id", "source_node",
                "target_node", "cost", "score", "reason")


def _slot_costs(cat: Catalog, strategy: str, load_scores):
    """-> (cost per colocation slot, node loads, representative
    (table, shard_id, node) per slot) — the rebalancer's _group_costs
    generalized over the strategy's cost source."""
    groups: dict[tuple, float] = {}
    rep: dict[tuple, tuple] = {}
    loads: dict[int, float] = {n: 0.0 for n in cat.active_node_ids()}
    for tname in sorted(cat.tables):
        t = cat.tables[tname]
        if not t.is_distributed:
            continue
        for s in t.shards:
            node = s.placements[0]
            key = (t.colocation_id, s.index)
            if strategy == "by_observed_load":
                c = float(load_scores.get((t.name, s.shard_id, node), 0.0))
            elif strategy == "by_shard_count":
                c = 1.0
            else:  # by_bytes
                c = _placement_cost(cat, t, s, node, "by_disk_size")
            groups[key] = groups.get(key, 0.0) + c
            if key not in rep:
                rep[key] = (t.name, s.shard_id, node)
            loads[node] = loads.get(node, 0.0) + c
    return groups, loads, rep


def _dominant_tenant(attribution_rows, table: str, shard_id: int,
                     node: int):
    """-> (tenant, share of the placement's device ms) from the
    attribution ledger's rows_view, or (None, 0.0)."""
    total = 0.0
    per: dict[str, float] = {}
    for r in attribution_rows:
        if (r[0], r[1], r[2]) == (table, shard_id, node):
            total += float(r[5])
            per[str(r[3])] = per.get(str(r[3]), 0.0) + float(r[5])
    if total <= 0.0:
        return None, 0.0
    tenant = max(sorted(per), key=lambda k: per[k])
    return tenant, per[tenant] / total


def build_rebalance_plan(cat: Catalog, strategy: str = "by_observed_load",
                         threshold: float = 0.1, max_steps: int = 16,
                         load_scores=None, attribution_rows=None
                         ) -> list[PlanStep]:
    """Pure planning: simulate greedy hi→lo group moves until balanced,
    recognizing split/isolate shapes.  ``load_scores`` /
    ``attribution_rows`` default to the global attribution ledger's
    current snapshot; pass explicit snapshots for deterministic tests."""
    if strategy not in PLAN_STRATEGIES:
        from citus_tpu.errors import CatalogError
        raise CatalogError(
            f"unknown rebalance strategy {strategy!r} "
            f"(expected one of {', '.join(PLAN_STRATEGIES)})")
    if strategy == "by_observed_load":
        from citus_tpu.observability.load_attribution import (
            GLOBAL_ATTRIBUTION,
        )
        if load_scores is None:
            load_scores = GLOBAL_ATTRIBUTION.load_scores()
        if attribution_rows is None:
            attribution_rows = GLOBAL_ATTRIBUTION.rows_view()
    groups, loads, rep = _slot_costs(cat, strategy, load_scores or {})
    if len(loads) < 2:
        return []
    steps: list[PlanStep] = []
    location = {key: rep[key][2] for key in groups}
    mean = sum(loads.values()) / len(loads)
    floor = max(threshold * max(mean, 1.0), 1e-9)
    while len(steps) < max_steps:
        # deterministic hi/lo: load desc/asc, node id as tie-break
        hi = min(loads, key=lambda n: (-loads[n], n))
        lo = min(loads, key=lambda n: (loads[n], n))
        gap = loads[hi] - loads[lo]
        if gap <= floor:
            break
        movable = [(key, c) for key, c in groups.items()
                   if location[key] == hi and 0.0 < c < gap]
        if not movable:
            # nothing movable narrows the gap: the heaviest slot on hi
            # IS the imbalance — a split (and possibly an isolation)
            # is the only fix.  Terminal either way: a dry run cannot
            # simulate past a split's unknown post-split costs.
            stuck = [(key, c) for key, c in groups.items()
                     if location[key] == hi and c > 0.0]
            if not stuck:
                break
            key, cost = min(stuck, key=lambda kc: (-kc[1], kc[0]))
            if cost < loads[hi] * 0.99:
                # hi carries several slots, none individually movable:
                # that's placement parity (e.g. 4 shards on 3 nodes),
                # not a hot slot — splitting would just mint shards
                break
            table, shard_id, _ = rep[key]
            if strategy == "by_observed_load" and attribution_rows:
                tenant, share = _dominant_tenant(
                    attribution_rows, table, shard_id, hi)
                if tenant and tenant != "*" and share >= ISOLATE_TENANT_SHARE:
                    steps.append(PlanStep(
                        "isolate", table, shard_id, hi, lo,
                        cost * share, share,
                        f"tenant {tenant!r} carries "
                        f"{share:.0%} of the hottest placement"))
                    break
            steps.append(PlanStep(
                "split", table, shard_id, hi, lo, cost,
                min(1.0, cost / max(gap, 1e-9)),
                "heaviest group exceeds the node gap; no move helps"))
            break
        key, cost = min(movable, key=lambda kc: (-kc[1], kc[0]))
        table, shard_id, _ = rep[key]
        # moving cost c from hi to lo closes the gap by 2c (capped at
        # the gap itself): score 1.0 = this single move balances hi/lo
        steps.append(PlanStep(
            "move", table, shard_id, hi, lo, cost,
            min(1.0, 2.0 * cost / gap),
            f"{strategy}: narrows hi-lo gap {gap:.1f} by {2 * cost:.1f}"))
        loads[hi] -= cost
        loads[lo] += cost
        location[key] = lo
    return steps


def plan_rows(steps: list[PlanStep]) -> list[tuple]:
    return [s.to_row(i + 1) for i, s in enumerate(steps)]
