"""Shard transfer: move/copy a shard placement between nodes.

Reference: citus_move_shard_placement / TransferShards
(src/backend/distributed/operations/shard_transfer.c:351,472).  The
reference's 13-step non-blocking move (logical replication, catch-up,
metadata flip, deferred drop) collapses here because shard data files
are immutable-append and the catalog is the single source of truth:

  1. copy the placement's stripe files to the target placement dir
     (bulk phase — writers keep writing)
  2. under the colocation group's EXCLUSIVE write lock: final catch-up
     copy, then flip the placement in the catalog (atomic commit) —
     the lock blocks writers for only the diff copy + flip, like the
     reference's global-metadata-lock window (README:2560-2565)
  3. record the source directory for deferred cleanup

Colocated shards move together, like the reference.  Half-copied target
directories of a failed move are registered ON_FAILURE so the cleaner
removes them.
"""

from __future__ import annotations

import os
import shutil

from citus_tpu.catalog import Catalog
from citus_tpu.errors import CatalogError
from citus_tpu.operations.cleaner import (
    DEFERRED_ON_SUCCESS, ON_FAILURE, complete_operation, record_cleanup,
)
from citus_tpu.services.background_jobs import report_progress
from citus_tpu.storage.writer import SHARD_META, _load_meta


def _copy_placement_files(src: str, dst: str) -> None:
    from citus_tpu.testing.faults import FAULTS
    FAULTS.hit("shard_move_copy", src)
    os.makedirs(dst, exist_ok=True)
    # stripes are immutable: copy data files first, the meta file last so
    # a crash mid-copy leaves a readable (possibly shorter) placement
    names = sorted(n for n in os.listdir(src) if n.endswith(".cts"))
    for n in names:
        if not os.path.exists(os.path.join(dst, n)):
            shutil.copy2(os.path.join(src, n), os.path.join(dst, n))
            # stripes actually shipped count toward the move's byte
            # progress; skipped (already-present) files were booked by
            # the pass that copied them
            report_progress(add_bytes=os.path.getsize(os.path.join(dst, n)))
    # deletion bitmaps travel with the placement (they are re-copied on
    # every pass: unlike stripes they mutate in place)
    from citus_tpu.storage.deletes import DELETES_FILE
    if os.path.exists(os.path.join(src, DELETES_FILE)):
        shutil.copy2(os.path.join(src, DELETES_FILE),
                     os.path.join(dst, DELETES_FILE))
    shutil.copy2(os.path.join(src, SHARD_META), os.path.join(dst, SHARD_META))


def _find_shard(cat: Catalog, shard_id: int):
    for t in cat.tables.values():
        for s in t.shards:
            if s.shard_id == shard_id:
                return t, s
    raise CatalogError(f"shard {shard_id} does not exist")


def _colocated_shards(cat: Catalog, table, shard):
    """Shards that must move together: same colocation group, same index."""
    out = []
    for t in cat.tables.values():
        if t.colocation_id != table.colocation_id or t.colocation_id == 0:
            continue
        if t.is_distributed or t.method == "tenant":
            out.append((t, t.shards[shard.index]))
    return out


def copy_shard_placement(cat: Catalog, shard_id: int, source_node: int,
                         target_node: int) -> None:
    """Add a replica of a shard placement on target_node (reference:
    citus_copy_shard_placement)."""
    table, shard = _find_shard(cat, shard_id)
    if source_node not in shard.placements:
        raise CatalogError(f"shard {shard_id} has no placement on node {source_node}")
    if target_node in shard.placements:
        raise CatalogError(f"shard {shard_id} already placed on node {target_node}")
    if target_node not in cat.nodes:
        raise CatalogError(f"node {target_node} does not exist")
    for t, s in _colocated_shards(cat, table, shard):
        src = cat.shard_dir(t.name, s.shard_id, source_node)
        dst = cat.shard_dir(t.name, s.shard_id, target_node)
        if os.path.isdir(src):
            _copy_placement_files(src, dst)
        s.placements.append(target_node)
        t.version += 1
    cat.commit()


def _stripe_bytes_total(cat: Catalog, group, source_node: int) -> int:
    """Total stripe (.cts) bytes the move will ship, summed across the
    colocation group — the denominator of the move's progress record.
    Remote-hosted sources are sized over the data plane; an unreachable
    source just leaves the total at whatever was countable."""
    total = 0
    for t, s in group:
        src = cat.shard_dir(t.name, s.shard_id, source_node)
        if os.path.isdir(src):
            for n in os.listdir(src):
                if n.endswith(".cts"):
                    total += os.path.getsize(os.path.join(src, n))
        elif cat.is_remote_node(source_node) and cat.remote_data is not None:
            try:
                r = cat.remote_data.call(
                    cat.node_endpoint(source_node), "list_placement",
                    {"table": t.name, "shard_id": s.shard_id,
                     "node": source_node})
                total += sum(int(f["size"]) for f in r.get("files", [])
                             if f["name"].endswith(".cts"))
            # lint: disable=SWL01 -- sizing is advisory; the copy itself surfaces a dead source
            except Exception:
                pass
    return total


def _pull_one(cat: Catalog, t, s, source_node: int, dst: str) -> None:
    """One placement's bulk/catch-up copy: shared filesystem when the
    source directory is local, the RPC data plane when the source node
    is hosted by another coordinator (reference: the COPY-protocol file
    pull of executor/transmit.c + worker_shard_copy.c)."""
    src = cat.shard_dir(t.name, s.shard_id, source_node)
    if os.path.isdir(src):
        _copy_placement_files(src, dst)
    elif cat.is_remote_node(source_node) and cat.remote_data is not None:
        cat.remote_data.pull_placement(t.name, s.shard_id, source_node,
                                       cat.node_endpoint(source_node), dst)


def move_shard_placement(cat: Catalog, shard_id: int, source_node: int,
                         target_node: int, lock_manager=None) -> None:
    """Move a shard placement (and its colocated peers) between nodes.

    The final catch-up copy and the catalog flip run under the
    colocation group's EXCLUSIVE write lock — the same lock every DML
    writer holds while committing — so a stripe can never land on the
    source placement after the catch-up but before the flip (that write
    would be silently lost when the source is dropped).

    Cross-host: a source placement hosted by another coordinator is
    pulled over the data plane; a remote target is pushed the same way,
    and the source drop becomes a drop_placement RPC.  The catalog flip
    still travels through the metadata authority, so every coordinator
    observes the new placement map."""
    from citus_tpu.transaction.write_locks import EXCLUSIVE, group_write_lock

    table, shard = _find_shard(cat, shard_id)
    if source_node not in shard.placements:
        raise CatalogError(f"shard {shard_id} has no placement on node {source_node}")
    if target_node in shard.placements:
        raise CatalogError(f"shard {shard_id} already placed on node {target_node}")
    if target_node not in cat.nodes:
        raise CatalogError(f"node {target_node} does not exist")
    group = _colocated_shards(cat, table, shard)
    target_remote = cat.is_remote_node(target_node)
    import uuid
    op_id = uuid.uuid4().int & ((1 << 62) - 1)  # collision-free across movers
    for t, s in group:
        dst = cat.shard_dir(t.name, s.shard_id, target_node)
        if not os.path.isdir(dst):
            record_cleanup(cat, dst, ON_FAILURE, operation_id=op_id)
    report_progress(phase="copy", bytes_done=0,
                    bytes_total=_stripe_bytes_total(cat, group, source_node))
    try:
        # phase 1: bulk copy with writers still running
        for t, s in group:
            _pull_one(cat, t, s, source_node,
                      cat.shard_dir(t.name, s.shard_id, target_node))
        # phase 2: block writers for the diff copy + metadata flip only
        report_progress(phase="flip")
        with group_write_lock(cat, table, EXCLUSIVE, lock_manager=lock_manager):
            for t, s in group:
                dst = cat.shard_dir(t.name, s.shard_id, target_node)
                _pull_one(cat, t, s, source_node, dst)  # final catch-up
                if target_remote and os.path.isdir(dst):
                    # staged locally, now push to the hosting coordinator
                    cat.remote_data.push_placement(
                        dst, t.name, s.shard_id, target_node,
                        cat.node_endpoint(target_node))
            for t, s in group:
                s.placements = [target_node if n == source_node else n
                                for n in s.placements]
                t.version += 1
            cat.commit()
    except BaseException:
        complete_operation(cat, op_id, success=False)  # cleaner drops targets
        raise
    complete_operation(cat, op_id, success=True)
    # phase 3: deferred source drop (RPC for a remote-hosted source)
    report_progress(phase="cleanup")
    for t, s in group:
        src = cat.shard_dir(t.name, s.shard_id, source_node)
        if os.path.isdir(src):
            record_cleanup(cat, src, DEFERRED_ON_SUCCESS)
        elif cat.is_remote_node(source_node) and cat.remote_data is not None:
            try:
                cat.remote_data.drop_placement(
                    cat.node_endpoint(source_node), t.name, s.shard_id,
                    source_node)
            # lint: disable=SWL01 -- deferred cleanup is best-effort; the cleaner duty re-runs it
            except Exception:
                pass  # deferred cleanup is best-effort; cleaner re-runs
        if target_remote:
            # the staging copy in OUR data dir is not a placement —
            # the hosting coordinator owns the real one now
            dst = cat.shard_dir(t.name, s.shard_id, target_node)
            if os.path.isdir(dst):
                record_cleanup(cat, dst, DEFERRED_ON_SUCCESS)
        if cat.remote_data is not None:
            cat.remote_data.invalidate_cache(t.name)
