"""Shard transfer: move/copy a shard placement between nodes.

Reference: citus_move_shard_placement / TransferShards
(src/backend/distributed/operations/shard_transfer.c:351,472) — the
13-step non-blocking move.  Mapped onto immutable-append stripes and a
single-source-of-truth catalog it becomes:

  1. register the operation (pid + op id) and park every target dir
     ON_FAILURE in the cleaner — a kill at ANY later step leaves
     records the next cleaner pass adopts and resolves against the
     committed catalog (operations/cleaner.py)
  2. bulk snapshot copy of the placement's files — writers keep writing
  3. CDC catch-up loop: re-run the (incremental) copy until the
     replication lag — change records committed after the last pass
     started (cdc.py pending_count) — falls under
     citus.shard_move_catchup_threshold, bounded by
     citus.shard_move_max_catchup_rounds, parked between rounds in the
     shard_move_catchup wait event.  Each pass only ships stripes the
     target doesn't already have (size-verified: a truncated file from
     a killed earlier pass is re-shipped, never trusted), so a round
     costs O(delta) not O(placement)
  4. under the colocation group's EXCLUSIVE write lock: final micro
     catch-up (O(last-delta)), pre-flip ON_SUCCESS records for the
     source dirs, then the 2PC metadata flip
     (transaction/branches.py commit_metadata_flip +
     Catalog.flip_placement) — blocked-write time is the micro
     catch-up + one atomic commit, measured per move into
     shard_move_blocked_write_ms and citus_shard_move_stats()
  5. deferred source drop via the cleaner
     (citus.defer_drop_after_shard_move=false drops inline)

Colocated shards move together, like the reference.  Deletion bitmaps
are snapshotted under the placement's delete lock (they mutate in
place; an uncoordinated copy can tear against a concurrent DELETE) and
published at the target by rename.
"""

from __future__ import annotations

import os
import shutil
import threading
import time

from citus_tpu.catalog import Catalog
from citus_tpu.errors import CatalogError
from citus_tpu.operations.cleaner import (
    ON_FAILURE, ON_SUCCESS, complete_operation,
    mark_operation_phase, record_cleanup, register_operation,
    try_drop_orphaned_resources,
)
from citus_tpu.services.background_jobs import report_progress
from citus_tpu.stats import begin_wait, end_wait
from citus_tpu.storage.deletes import DELETES_FILE
from citus_tpu.storage.writer import SHARD_META

#: ceiling of the between-rounds backoff (doubles from 10 ms)
_BACKOFF_MAX_S = 0.16


class ShardMoveStats:
    """Per-move operational stats ring, the EXPLAIN-able side of the
    non-blocking move (SELECT citus_shard_move_stats()): how many
    catch-up rounds each move ran and — the availability headline — how
    long its writers were actually blocked."""

    def __init__(self, cap: int = 256):
        self._mu = threading.Lock()
        self._cap = cap
        self._rows: list[dict] = []

    def record(self, **row) -> None:
        with self._mu:
            self._rows.append(row)
            if len(self._rows) > self._cap:
                self._rows = self._rows[-self._cap:]

    def rows(self) -> list[dict]:
        with self._mu:
            return list(self._rows)

    def reset(self) -> None:
        with self._mu:
            self._rows = []


MOVE_STATS = ShardMoveStats()


def _counters():
    from citus_tpu.executor.executor import GLOBAL_COUNTERS
    return GLOBAL_COUNTERS


def _snapshot_deletes_file(src: str, dst: str) -> None:
    """Copy the placement's deletion bitmaps without tearing.  The file
    mutates in place (merge-under-flock + rename publish,
    storage/deletes.py), so the snapshot takes the same lock a
    committing DELETE holds, reads the published bytes, and republishes
    them at the target by rename — a reader at the target can never see
    a half-written bitmap."""
    import fcntl
    sp = os.path.join(src, DELETES_FILE)
    dp = os.path.join(dst, DELETES_FILE)
    lock_fd = os.open(os.path.join(src, ".deletes.lock"),
                      os.O_CREAT | os.O_RDWR)
    try:
        fcntl.flock(lock_fd, fcntl.LOCK_SH)
        try:
            with open(sp, "rb") as fh:
                data = fh.read()
        except FileNotFoundError:
            data = None
    finally:
        fcntl.flock(lock_fd, fcntl.LOCK_UN)
        os.close(lock_fd)
    if data is None:
        # deletes cleared at the source (VACUUM) after an earlier pass
        # copied them: the stale target copy must not survive the move
        try:
            os.remove(dp)
        except FileNotFoundError:
            pass
        return
    tmp = dp + ".part"
    with open(tmp, "wb") as fh:
        fh.write(data)
    os.replace(tmp, dp)


def _copy_atomic(src_path: str, dst_path: str) -> None:
    tmp = dst_path + ".part"
    shutil.copy2(src_path, tmp)
    os.replace(tmp, dst_path)


def _copy_placement_files(src: str, dst: str) -> int:
    """One (incremental) copy pass of a local placement; returns stripe
    bytes actually shipped — zero means the pass found nothing new, the
    converged signal of the catch-up loop when no CDC stream exists."""
    from citus_tpu.testing.faults import FAULTS
    FAULTS.hit("shard_move_copy", src)
    os.makedirs(dst, exist_ok=True)
    copied = 0
    # stripes are immutable: copy data files first, the meta file last so
    # a crash mid-copy leaves a readable (possibly shorter) placement
    names = sorted(n for n in os.listdir(src) if n.endswith(".cts"))
    for n in names:
        sp, dp = os.path.join(src, n), os.path.join(dst, n)
        try:
            src_size = os.path.getsize(sp)
        except OSError:
            continue  # vanished under VACUUM; the meta copy decides
        if os.path.exists(dp) and os.path.getsize(dp) == src_size:
            # complete stripe from an earlier pass (size-verified: mere
            # existence could be a truncation left by a killed pass,
            # which silently kept would corrupt the target)
            continue
        _copy_atomic(sp, dp)
        copied += src_size
        # stripes actually shipped count toward the move's byte
        # progress; skipped (already-present) files were booked by
        # the pass that copied them
        report_progress(add_bytes=src_size)
    # deletion bitmaps travel with the placement on every pass (unlike
    # stripes they mutate in place) — snapshotted, not raw-copied
    _snapshot_deletes_file(src, dst)
    _copy_atomic(os.path.join(src, SHARD_META), os.path.join(dst, SHARD_META))
    return copied


def _find_shard(cat: Catalog, shard_id: int):
    for t in cat.tables.values():
        for s in t.shards:
            if s.shard_id == shard_id:
                return t, s
    raise CatalogError(f"shard {shard_id} does not exist")


def _colocated_shards(cat: Catalog, table, shard):
    """Shards that must move together: same colocation group, same index."""
    out = []
    for t in cat.tables.values():
        if t.colocation_id != table.colocation_id or t.colocation_id == 0:
            continue
        if t.is_distributed or t.method == "tenant":
            out.append((t, t.shards[shard.index]))
    return out


def copy_shard_placement(cat: Catalog, shard_id: int, source_node: int,
                         target_node: int) -> None:
    """Add a replica of a shard placement on target_node (reference:
    citus_copy_shard_placement)."""
    table, shard = _find_shard(cat, shard_id)
    if source_node not in shard.placements:
        raise CatalogError(f"shard {shard_id} has no placement on node {source_node}")
    if target_node in shard.placements:
        raise CatalogError(f"shard {shard_id} already placed on node {target_node}")
    if target_node not in cat.nodes:
        raise CatalogError(f"node {target_node} does not exist")
    for t, s in _colocated_shards(cat, table, shard):
        src = cat.shard_dir(t.name, s.shard_id, source_node)
        dst = cat.shard_dir(t.name, s.shard_id, target_node)
        if os.path.isdir(src):
            _copy_placement_files(src, dst)
        s.placements.append(target_node)
        t.version += 1
    cat.commit()


def _stripe_bytes_total(cat: Catalog, group, source_node: int) -> int:
    """Total stripe (.cts) bytes the move will ship, summed across the
    colocation group — the denominator of the move's progress record.
    Remote-hosted sources are sized over the data plane; an unreachable
    source just leaves the total at whatever was countable."""
    total = 0
    for t, s in group:
        src = cat.shard_dir(t.name, s.shard_id, source_node)
        if os.path.isdir(src):
            for n in os.listdir(src):
                if n.endswith(".cts"):
                    total += os.path.getsize(os.path.join(src, n))
        elif cat.is_remote_node(source_node) and cat.remote_data is not None:
            try:
                r = cat.remote_data.call(
                    cat.node_endpoint(source_node), "list_placement",
                    {"table": t.name, "shard_id": s.shard_id,
                     "node": source_node})
                total += sum(int(f["size"]) for f in r.get("files", [])
                             if f["name"].endswith(".cts"))
            # lint: disable=SWL01 -- sizing is advisory; the copy itself surfaces a dead source
            except Exception:
                pass
    return total


def _pull_one(cat: Catalog, t, s, source_node: int, dst: str) -> int:
    """One placement's bulk/catch-up copy pass: shared filesystem when
    the source directory is local, the RPC data plane when the source
    node is hosted by another coordinator (reference: the COPY-protocol
    file pull of executor/transmit.c + worker_shard_copy.c).  Returns
    stripe bytes shipped this pass."""
    src = cat.shard_dir(t.name, s.shard_id, source_node)
    if os.path.isdir(src):
        return _copy_placement_files(src, dst)
    if cat.is_remote_node(source_node) and cat.remote_data is not None:
        return cat.remote_data.pull_placement(
            t.name, s.shard_id, source_node,
            cat.node_endpoint(source_node), dst)
    return 0


def _cdc(cat: Catalog):
    from citus_tpu.cdc import ChangeDataCapture
    return ChangeDataCapture(cat.data_dir, enabled=False)


def _cdc_frontier(cat: Catalog, tables) -> dict[str, int]:
    """Per-table newest change lsn at the start of a copy pass: every
    record at or below it is covered by the stripes that pass ships."""
    cdc = _cdc(cat)
    return {name: cdc.last_lsn(name) for name in tables}


def _cdc_lag(cat: Catalog, frontier: dict[str, int]) -> int | None:
    """Replication lag: change records committed after the frontier.
    None when no member table has a change stream (CDC off and no
    publications) — the caller falls back to the bytes-copied proxy."""
    cdc = _cdc(cat)
    total, have_stream = 0, False
    for name, lsn0 in frontier.items():
        if cdc.has_stream(name):
            have_stream = True
            total += cdc.pending_count(name, lsn0)
    return total if have_stream else None


def run_catchup_loop(cat: Catalog, copy_pass, tables, *,
                     settings, fault_context: str = "") -> int:
    """The bounded catch-up loop shared by shard moves and splits.

    ``copy_pass()`` ships one incremental delta to the target(s) and
    returns bytes shipped.  Rounds repeat while the replication lag
    (CDC records committed after the round's copy started; bytes
    shipped when no stream exists) exceeds
    citus.shard_move_catchup_threshold, up to
    citus.shard_move_max_catchup_rounds — then the caller takes the
    write lock and the final micro catch-up is O(whatever is left).
    The mover parks (not the writers) between rounds under the
    shard_move_catchup wait event, backing off 10 ms → 160 ms.
    Returns the number of rounds run (>= 1: the first round doubles as
    the convergence probe after the bulk copy)."""
    from citus_tpu.testing.faults import FAULTS
    threshold = settings.sharding.shard_move_catchup_threshold
    max_rounds = settings.sharding.shard_move_max_catchup_rounds
    rounds = 0
    backoff = 0.01
    while rounds < max_rounds:
        FAULTS.hit("shard_move_catchup", fault_context)
        frontier = _cdc_frontier(cat, tables)
        copied = copy_pass()
        rounds += 1
        _counters().bump("shard_move_catchup_rounds")
        lag = _cdc_lag(cat, frontier)
        if lag is None:
            # no change stream to measure against: converged when a
            # whole pass found nothing new to ship
            if copied == 0:
                break
        elif lag <= threshold:
            break
        if rounds >= max_rounds:
            break  # bounded: stop chasing, let the locked pass finish
        tok = begin_wait("shard_move_catchup")
        try:
            time.sleep(backoff)
        finally:
            end_wait(tok)
        backoff = min(backoff * 2, _BACKOFF_MAX_S)
    return rounds


def move_shard_placement(cat: Catalog, shard_id: int, source_node: int,
                         target_node: int, lock_manager=None,
                         settings=None) -> None:
    """Move a shard placement (and its colocated peers) between nodes
    without blocking writers for the data copy (module doc: the
    non-blocking sequence).

    Only the final micro catch-up and the catalog flip run under the
    colocation group's EXCLUSIVE write lock — the same lock every DML
    writer holds while committing — so a stripe can never land on the
    source placement after the final catch-up but before the flip
    (that write would be silently lost when the source is dropped),
    and the blocked-write window is O(last-delta), not O(diff).

    Cross-host: a source placement hosted by another coordinator is
    pulled over the data plane; a remote target is pushed the same way,
    and the source drop becomes a drop_placement RPC.  The catalog flip
    still travels through the metadata authority, so every coordinator
    observes the new placement map."""
    from citus_tpu.observability.trace import clock
    from citus_tpu.testing.faults import FAULTS
    from citus_tpu.transaction.branches import commit_metadata_flip
    from citus_tpu.transaction.write_locks import EXCLUSIVE, group_write_lock
    if settings is None:
        from citus_tpu.config import current_settings
        settings = current_settings()

    table, shard = _find_shard(cat, shard_id)
    if source_node not in shard.placements:
        raise CatalogError(f"shard {shard_id} has no placement on node {source_node}")
    if target_node in shard.placements:
        raise CatalogError(f"shard {shard_id} already placed on node {target_node}")
    if target_node not in cat.nodes:
        raise CatalogError(f"node {target_node} does not exist")
    group = _colocated_shards(cat, table, shard)
    target_remote = cat.is_remote_node(target_node)
    import uuid
    op_id = uuid.uuid4().int & ((1 << 62) - 1)  # collision-free across movers
    # registry row first, THEN the op-gated records: no cleaner pass can
    # see a record without a pid to arbitrate liveness against
    register_operation(cat, op_id, kind="move_shard")
    for t, s in group:
        dst = cat.shard_dir(t.name, s.shard_id, target_node)
        if not os.path.isdir(dst):
            record_cleanup(cat, dst, ON_FAILURE, operation_id=op_id)
    report_progress(phase="copy", bytes_done=0,
                    bytes_total=_stripe_bytes_total(cat, group, source_node))
    t_start = clock()
    bytes_copied = 0
    catchup_rounds = 0
    blocked_ms = 0.0
    try:
        # phase 1: bulk snapshot copy with writers still running
        for t, s in group:
            bytes_copied += _pull_one(
                cat, t, s, source_node,
                cat.shard_dir(t.name, s.shard_id, target_node))
        # phase 2: CDC catch-up — drain the replication lag in O(delta)
        # passes while writers still run
        report_progress(phase="catchup")
        mark_operation_phase(cat, op_id, "catchup")
        member_tables = sorted({t.name for t, _ in group})

        def _catchup_pass() -> int:
            shipped = 0
            for t, s in group:
                shipped += _pull_one(
                    cat, t, s, source_node,
                    cat.shard_dir(t.name, s.shard_id, target_node))
            return shipped

        catchup_rounds = run_catchup_loop(
            cat, _catchup_pass, member_tables, settings=settings,
            fault_context=f"{table.name}:{shard_id}")
        # phase 3: block writers for the final micro catch-up + flip only
        report_progress(phase="flip")
        with group_write_lock(cat, table, EXCLUSIVE, lock_manager=lock_manager):
            t_block = clock()
            FAULTS.hit("shard_move_flip", f"{table.name}:{shard_id}")
            for t, s in group:
                dst = cat.shard_dir(t.name, s.shard_id, target_node)
                _pull_one(cat, t, s, source_node, dst)  # final catch-up
                if target_remote and os.path.isdir(dst):
                    # staged locally, now push to the hosting coordinator
                    cat.remote_data.push_placement(
                        dst, t.name, s.shard_id, target_node,
                        cat.node_endpoint(target_node))
            # pre-flip ON_SUCCESS records for the source dirs: written
            # BEFORE the decision so a kill right after the commit still
            # leaves the cleaner everything it needs to finish the drop
            for t, s in group:
                src = cat.shard_dir(t.name, s.shard_id, source_node)
                if os.path.isdir(src):
                    record_cleanup(cat, src, ON_SUCCESS, operation_id=op_id)
                if target_remote:
                    # the staging copy in OUR data dir is not a placement —
                    # the hosting coordinator owns the real one now
                    dst = cat.shard_dir(t.name, s.shard_id, target_node)
                    if os.path.isdir(dst):
                        record_cleanup(cat, dst, ON_SUCCESS,
                                       operation_id=op_id)

            def _flip():
                # re-resolve under the lock: the catalog may have been
                # reloaded (MX invalidation) since the move started, and
                # the flip must land on the live objects
                ft, fs = _find_shard(cat, shard_id)
                for gt, gs in _colocated_shards(cat, ft, fs):
                    cat.flip_placement(gt, gs, source_node, target_node)

            commit_metadata_flip(cat, op_id, _flip)
            blocked_ms = (clock() - t_block) * 1000.0
    except BaseException:
        complete_operation(cat, op_id, success=False)  # cleaner drops targets
        raise
    complete_operation(cat, op_id, success=True)
    _counters().bump("shard_move_blocked_write_ms", max(1, int(blocked_ms)))
    MOVE_STATS.record(
        op="move", shard_id=shard_id, source=source_node,
        target=target_node, bytes_copied=bytes_copied,
        catchup_rounds=catchup_rounds,
        blocked_write_ms=round(blocked_ms, 3),
        total_ms=round((clock() - t_start) * 1000.0, 3))
    # phase 4: deferred source drop (RPC for a remote-hosted source);
    # the local dirs were parked ON_SUCCESS pre-flip and are now ALWAYS
    report_progress(phase="cleanup")
    for t, s in group:
        if cat.is_remote_node(source_node) and cat.remote_data is not None \
                and not os.path.isdir(
                    cat.shard_dir(t.name, s.shard_id, source_node)):
            try:
                cat.remote_data.drop_placement(
                    cat.node_endpoint(source_node), t.name, s.shard_id,
                    source_node)
            # lint: disable=SWL01 -- deferred cleanup is best-effort; the cleaner duty re-runs it
            except Exception:
                pass  # deferred cleanup is best-effort; cleaner re-runs
        if cat.remote_data is not None:
            cat.remote_data.invalidate_cache(t.name)
    if not settings.sharding.defer_drop_after_shard_move:
        # inline drop requested: run the cleaner pass now instead of
        # leaving the source for the maintenance daemon
        try_drop_orphaned_resources(cat)
