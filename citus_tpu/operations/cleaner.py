"""Deferred resource cleanup.

Reference: pg_dist_cleanup + shard_cleaner.c (TryDropOrphanedResources,
operations/shard_cleaner.c:199).  Operations that replace or move data
never delete the old files inline — they record a cleanup entry that the
maintenance daemon (or an explicit call) processes later, so concurrent
readers holding the old placement finish safely and failed operations
can't leak half-moved state.

The record file is shared by the maintenance daemon thread, foreground
calls, and (in MX setups) other coordinator processes, so every
load-mutate-store runs under a cross-process file lock.  Policies follow
the reference's CLEANUP_* semantics: ALWAYS entries are dropped on every
pass; ON_FAILURE / ON_SUCCESS entries stay parked while their operation
runs and are resolved by complete_operation — or, if the operation died
without resolving them (kill -9 mid-move), adopted by the next pass.

Crash adoption (reference: operation_id + the owning backend's lease in
pg_dist_cleanup): every move/split registers itself in OPERATIONS_FILE
with its pid *before* recording any op-gated entry.  A pass that finds
an op-gated record whose registered pid is dead resolves it by
arbitration against the COMMITTED catalog document — the metadata flip's
atomic commit is the operation's 2PC decision record
(transaction/branches.py doctrine, presumed abort): a path that is now a
live placement was promoted by a committed flip and must be kept; any
other path is orphaned half-moved state and is dropped.  The pass runs
under the cross-process cleanup lock, so two concurrent cleaners adopt
and drop each orphan exactly once.
"""

from __future__ import annotations

import json
import os
import shutil
import time
from citus_tpu.utils.clock import now as wall_now

from citus_tpu.catalog import Catalog

CLEANUP_FILE = "cleanup.json"
#: registry of in-flight operations that own op-gated cleanup records:
#: {str(operation_id): {"pid": ..., "kind": ..., "phase": ..., "started_at": ...}}
OPERATIONS_FILE = "operations.json"

# policies (mirroring the reference's CLEANUP_* semantics)
ALWAYS = "always"                 # drop whether the op succeeded or failed
ON_FAILURE = "on_failure"         # drop only if the op failed
ON_SUCCESS = "on_success"         # drop only if the op succeeded
DEFERRED_ON_SUCCESS = "deferred_on_success"  # drop after the op succeeded


def _cleanup_flock(cat: Catalog):
    from citus_tpu.utils.filelock import FileLock
    return FileLock(os.path.join(cat.data_dir, ".cleanup.lock"))


def _path(cat: Catalog) -> str:
    return os.path.join(cat.data_dir, CLEANUP_FILE)


def _ops_path(cat: Catalog) -> str:
    return os.path.join(cat.data_dir, OPERATIONS_FILE)


def _load(cat: Catalog) -> list[dict]:
    p = _path(cat)
    if not os.path.exists(p):
        return []
    with open(p) as fh:
        return json.load(fh)


def _store(cat: Catalog, records: list[dict]) -> None:
    tmp = _path(cat) + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(records, fh)
    os.replace(tmp, _path(cat))


def _load_ops(cat: Catalog) -> dict[str, dict]:
    p = _ops_path(cat)
    if not os.path.exists(p):
        return {}
    with open(p) as fh:
        return json.load(fh)


def _store_ops(cat: Catalog, ops: dict[str, dict]) -> None:
    tmp = _ops_path(cat) + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(ops, fh)
    os.replace(tmp, _ops_path(cat))


def _pid_alive(pid: int) -> bool:
    if pid == os.getpid():
        return True
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except OSError:
        return True  # EPERM: exists but owned elsewhere
    return True


def register_operation(cat: Catalog, operation_id: int, kind: str = "",
                       pid: int | None = None) -> None:
    """Register an in-flight operation BEFORE its first op-gated
    record_cleanup, so no pass can ever see an op-gated record without
    a registry row to arbitrate liveness against.  ``pid`` is
    overridable for tests that forge a dead owner."""
    with _cleanup_flock(cat):
        ops = _load_ops(cat)
        ops[str(operation_id)] = {
            "pid": os.getpid() if pid is None else int(pid),
            "kind": kind, "phase": "running", "started_at": wall_now(),
        }
        _store_ops(cat, ops)


def mark_operation_phase(cat: Catalog, operation_id: int, phase: str) -> None:
    """Advance the registry row's phase marker (copy / catchup / decide /
    decided) — observability plus the 2PC decision-window record."""
    with _cleanup_flock(cat):
        ops = _load_ops(cat)
        row = ops.get(str(operation_id))
        if row is not None:
            row["phase"] = phase
            _store_ops(cat, ops)


def operations_view(cat: Catalog) -> dict[str, dict]:
    with _cleanup_flock(cat):
        return _load_ops(cat)


def record_cleanup(cat: Catalog, resource_path: str, policy: str = DEFERRED_ON_SUCCESS,
                   operation_id: int = 0) -> None:
    with _cleanup_flock(cat):
        records = _load(cat)
        records.append({
            "path": resource_path, "policy": policy,
            "operation_id": operation_id, "recorded_at": wall_now(),
        })
        _store(cat, records)


def complete_operation(cat: Catalog, operation_id: int, success: bool) -> None:
    """Resolve an operation's op-gated records and retire its registry
    row.  ON_FAILURE entries (half-copied targets): success discards
    them (the resources are now live data), failure makes them
    unconditionally droppable.  ON_SUCCESS entries (the pre-flip source
    placements): success makes them droppable on the next pass
    (deferred drop), failure discards them (the source is still the
    live placement)."""
    with _cleanup_flock(cat):
        records = _load(cat)
        out = []
        for r in records:
            if r["operation_id"] == operation_id:
                if r["policy"] == ON_FAILURE:
                    if success:
                        continue  # resource promoted to live data
                    r = dict(r, policy=ALWAYS)
                elif r["policy"] == ON_SUCCESS:
                    if not success:
                        continue  # source placement stays live
                    r = dict(r, policy=ALWAYS)
            out.append(r)
        _store(cat, out)
        ops = _load_ops(cat)
        if ops.pop(str(operation_id), None) is not None:
            _store_ops(cat, ops)


def pending_cleanup(cat: Catalog) -> list[dict]:
    with _cleanup_flock(cat):
        return _load(cat)


def _live_placement_dirs(cat: Catalog) -> set[str]:
    """Every placement directory the COMMITTED catalog document names —
    re-read from disk, not from this process's in-memory view, because
    the crashed operation may have committed its flip from another
    process an instant before dying."""
    dirs: set[str] = set()
    try:
        with open(cat._path()) as fh:
            doc = json.load(fh)
        tables = doc.get("tables", [])
    except (OSError, ValueError):
        tables = None
    if tables is None:
        # no on-disk document yet: fall back to the live object
        for t in cat.tables.values():
            for s in t.shards:
                for n in s.placements:
                    dirs.add(os.path.normpath(
                        cat.shard_dir(t.name, s.shard_id, n)))
        return dirs
    for td in tables:
        name = td.get("name")
        for sd in td.get("shards", []):
            for n in sd.get("placements", []):
                dirs.add(os.path.normpath(
                    cat.shard_dir(name, sd["shard_id"], n)))
    return dirs


def try_drop_orphaned_resources(cat: Catalog) -> int:
    """Drop every droppable recorded resource; returns how many were
    removed.  Safe to call repeatedly and concurrently (the maintenance
    daemon does).  Op-gated records whose owner died are adopted here:
    the committed catalog decides survivor vs orphan (module doc)."""
    with _cleanup_flock(cat):
        records = _load(cat)
        ops = _load_ops(cat)
        live_dirs: set[str] | None = None
        remaining, dropped = [], 0
        referenced: set[str] = set()
        for r in records:
            if r["policy"] in (ON_FAILURE, ON_SUCCESS):
                row = ops.get(str(r["operation_id"]))
                if row is None or _pid_alive(int(row["pid"])):
                    # owner still running — or unregistered (an API
                    # caller that never registered: only
                    # complete_operation may resolve its records; every
                    # move/split registers before recording, so a crash
                    # always leaves a row with a dead pid)
                    remaining.append(r)
                    referenced.add(str(r["operation_id"]))
                    continue
                # owner is gone without resolving: adopt.  The committed
                # catalog is the decision record — a live placement path
                # was promoted by the flip; anything else is orphaned.
                if live_dirs is None:
                    live_dirs = _live_placement_dirs(cat)
                if os.path.normpath(r["path"]) in live_dirs:
                    continue  # promoted to live data; record retired
            p = r["path"]
            try:
                if os.path.isdir(p):
                    shutil.rmtree(p)
                elif os.path.exists(p):
                    os.remove(p)
                dropped += 1
            except FileNotFoundError:
                dropped += 1  # someone else removed it: success
            except OSError:
                remaining.append(r)  # retry next cycle
                if r["policy"] in (ON_FAILURE, ON_SUCCESS):
                    referenced.add(str(r["operation_id"]))
        _store(cat, remaining)
        # retire registry rows of dead owners with no records left
        stale = [oid for oid, row in ops.items()
                 if oid not in referenced and not _pid_alive(int(row["pid"]))]
        if stale:
            for oid in stale:
                ops.pop(oid, None)
            _store_ops(cat, ops)
        return dropped
