"""Deferred resource cleanup.

Reference: pg_dist_cleanup + shard_cleaner.c (TryDropOrphanedResources,
operations/shard_cleaner.c:199).  Operations that replace or move data
never delete the old files inline — they record a cleanup entry that the
maintenance daemon (or an explicit call) processes later, so concurrent
readers holding the old placement finish safely and failed operations
can't leak half-moved state.

The record file is shared by the maintenance daemon thread, foreground
calls, and (in MX setups) other coordinator processes, so every
load-mutate-store runs under a cross-process file lock.  Policies follow
the reference's CLEANUP_* semantics: ALWAYS entries are dropped on every
pass; ON_FAILURE entries are dropped only once their operation is marked
failed (a crashed operation's entries are adopted by the next pass via
the operation registry); DEFERRED_ON_SUCCESS entries are recorded after
the operation succeeded and dropped on the next pass.
"""

from __future__ import annotations

import json
import os
import shutil
import time
from citus_tpu.utils.clock import now as wall_now

from citus_tpu.catalog import Catalog

CLEANUP_FILE = "cleanup.json"

# policies (mirroring the reference's CLEANUP_* semantics)
ALWAYS = "always"                 # drop whether the op succeeded or failed
ON_FAILURE = "on_failure"         # drop only if the op failed
DEFERRED_ON_SUCCESS = "deferred_on_success"  # drop after the op succeeded


def _cleanup_flock(cat: Catalog):
    from citus_tpu.utils.filelock import FileLock
    return FileLock(os.path.join(cat.data_dir, ".cleanup.lock"))


def _path(cat: Catalog) -> str:
    return os.path.join(cat.data_dir, CLEANUP_FILE)


def _load(cat: Catalog) -> list[dict]:
    p = _path(cat)
    if not os.path.exists(p):
        return []
    with open(p) as fh:
        return json.load(fh)


def _store(cat: Catalog, records: list[dict]) -> None:
    tmp = _path(cat) + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(records, fh)
    os.replace(tmp, _path(cat))


def record_cleanup(cat: Catalog, resource_path: str, policy: str = DEFERRED_ON_SUCCESS,
                   operation_id: int = 0) -> None:
    with _cleanup_flock(cat):
        records = _load(cat)
        records.append({
            "path": resource_path, "policy": policy,
            "operation_id": operation_id, "recorded_at": wall_now(),
        })
        _store(cat, records)


def complete_operation(cat: Catalog, operation_id: int, success: bool) -> None:
    """Resolve ON_FAILURE records: a successful operation's entries are
    discarded (the resources are now live data); a failed operation's
    entries become unconditionally droppable."""
    with _cleanup_flock(cat):
        records = _load(cat)
        out = []
        for r in records:
            if r["policy"] == ON_FAILURE and r["operation_id"] == operation_id:
                if success:
                    continue  # resource promoted to live data
                r = dict(r, policy=ALWAYS)
            out.append(r)
        _store(cat, out)


def pending_cleanup(cat: Catalog) -> list[dict]:
    with _cleanup_flock(cat):
        return _load(cat)


def try_drop_orphaned_resources(cat: Catalog) -> int:
    """Drop every droppable recorded resource; returns how many were
    removed.  Safe to call repeatedly and concurrently (the maintenance
    daemon does)."""
    with _cleanup_flock(cat):
        records = _load(cat)
        remaining, dropped = [], 0
        for r in records:
            if r["policy"] == ON_FAILURE:
                remaining.append(r)  # operation outcome not yet resolved
                continue
            p = r["path"]
            try:
                if os.path.isdir(p):
                    shutil.rmtree(p)
                elif os.path.exists(p):
                    os.remove(p)
                dropped += 1
            except FileNotFoundError:
                dropped += 1  # someone else removed it: success
            except OSError:
                remaining.append(r)  # retry next cycle
        _store(cat, remaining)
        return dropped
