"""Deferred resource cleanup.

Reference: pg_dist_cleanup + shard_cleaner.c (TryDropOrphanedResources,
operations/shard_cleaner.c:199).  Operations that replace or move data
never delete the old files inline — they record a cleanup entry that the
maintenance daemon (or an explicit call) processes later, so concurrent
readers holding the old placement finish safely and failed operations
can't leak half-moved state.
"""

from __future__ import annotations

import json
import os
import shutil
import time

from citus_tpu.catalog import Catalog

CLEANUP_FILE = "cleanup.json"

# policies (mirroring the reference's CLEANUP_* semantics)
ALWAYS = "always"                 # drop whether the op succeeded or failed
ON_FAILURE = "on_failure"         # drop only if the op failed
DEFERRED_ON_SUCCESS = "deferred_on_success"  # drop after the op succeeded


def _path(cat: Catalog) -> str:
    return os.path.join(cat.data_dir, CLEANUP_FILE)


def _load(cat: Catalog) -> list[dict]:
    p = _path(cat)
    if not os.path.exists(p):
        return []
    with open(p) as fh:
        return json.load(fh)


def _store(cat: Catalog, records: list[dict]) -> None:
    tmp = _path(cat) + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(records, fh)
    os.replace(tmp, _path(cat))


def record_cleanup(cat: Catalog, resource_path: str, policy: str = DEFERRED_ON_SUCCESS,
                   operation_id: int = 0) -> None:
    records = _load(cat)
    records.append({
        "path": resource_path, "policy": policy,
        "operation_id": operation_id, "recorded_at": time.time(),
    })
    _store(cat, records)


def pending_cleanup(cat: Catalog) -> list[dict]:
    return _load(cat)


def try_drop_orphaned_resources(cat: Catalog) -> int:
    """Drop every recorded resource; returns how many were removed.
    Safe to call repeatedly (the maintenance daemon does)."""
    records = _load(cat)
    remaining, dropped = [], 0
    for r in records:
        p = r["path"]
        try:
            if os.path.isdir(p):
                shutil.rmtree(p)
            elif os.path.exists(p):
                os.remove(p)
            dropped += 1
        except OSError:
            remaining.append(r)  # retry next cycle
    _store(cat, remaining)
    return dropped
