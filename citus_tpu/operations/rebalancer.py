"""Shard rebalancer.

Reference: the greedy rebalance algorithm in
src/backend/distributed/operations/shard_rebalancer.c
(GetRebalanceSteps :532, RebalancePlacementUpdates :635) with
per-strategy cost/capacity hooks from pg_dist_rebalance_strategy.

Algorithm (same shape as the reference's): compute each node's total
cost (here: placement disk bytes, min 1 per placement so empty shards
still spread), then repeatedly move the best-fitting shard group from
the most-utilized node to the least-utilized node while the improvement
exceeds ``threshold`` of the mean utilization.  Colocation groups move
as one unit, exactly like the reference.
"""

from __future__ import annotations

import os
from dataclasses import dataclass

from citus_tpu.catalog import Catalog
from citus_tpu.operations.shard_transfer import move_shard_placement


@dataclass
class RebalanceMove:
    shard_id: int
    source_node: int
    target_node: int
    cost: float

    def to_row(self):
        return (self.shard_id, self.source_node, self.target_node)


def _placement_cost(cat: Catalog, table, shard, node: int,
                    strategy: str = "by_disk_size") -> float:
    if strategy == "by_shard_count":
        return 1.0  # every shard group weighs the same
    d = cat.shard_dir(table.name, shard.shard_id, node)
    if not os.path.isdir(d):
        return 1.0
    return max(1.0, float(sum(
        os.path.getsize(os.path.join(d, f)) for f in os.listdir(d))))


def _group_costs(cat: Catalog, table_name: str | None = None,
                 strategy: str = "by_disk_size"):
    """-> (cost per colocation-group-slot keyed by (colocation_id, index),
    node loads, representative shard per group slot)."""
    groups: dict[tuple, float] = {}
    rep: dict[tuple, tuple] = {}
    loads: dict[int, float] = {n: 0.0 for n in cat.active_node_ids()}
    for t in cat.tables.values():
        if not t.is_distributed:
            continue
        if table_name is not None and t.colocation_id != cat.table(table_name).colocation_id:
            continue
        for s in t.shards:
            node = s.placements[0]
            key = (t.colocation_id, s.index)
            c = _placement_cost(cat, t, s, node, strategy)
            groups[key] = groups.get(key, 0.0) + c
            if key not in rep:
                rep[key] = (s.shard_id, node)
            loads[node] = loads.get(node, 0.0) + c
    return groups, loads, rep


REBALANCE_STRATEGIES = ("by_disk_size", "by_shard_count")


def get_rebalance_plan(cat: Catalog, table_name: str | None = None,
                       threshold: float = 0.1,
                       max_moves: int = 1000,
                       strategy: str = "by_disk_size") -> list[RebalanceMove]:
    """Greedy improvement plan; does not execute anything.  ``strategy``
    mirrors pg_dist_rebalance_strategy's built-ins: by_disk_size
    (placement bytes) or by_shard_count (uniform weights)."""
    if strategy not in REBALANCE_STRATEGIES:
        from citus_tpu.errors import CatalogError
        raise CatalogError(f"unknown rebalance strategy {strategy!r}")
    groups, loads, rep = _group_costs(cat, table_name, strategy)
    if not loads:
        return []
    # group slot -> current node (simulated as moves are planned)
    location = {key: rep[key][1] for key in groups}
    moves: list[RebalanceMove] = []
    mean = sum(loads.values()) / len(loads)
    for _ in range(max_moves):
        hi = max(loads, key=lambda n: loads[n])
        lo = min(loads, key=lambda n: loads[n])
        gap = loads[hi] - loads[lo]
        if gap <= max(threshold * max(mean, 1.0), 1e-9):
            break
        # best candidate on hi: largest group that still improves balance
        candidates = [(key, c) for key, c in groups.items()
                      if location[key] == hi and c < gap]
        if not candidates:
            break
        key, cost = max(candidates, key=lambda kc: kc[1])
        shard_id, _ = rep[key]
        moves.append(RebalanceMove(shard_id, hi, lo, cost))
        loads[hi] -= cost
        loads[lo] += cost
        location[key] = lo
    return moves


def rebalance_table_shards(cat: Catalog, table_name: str | None = None,
                           threshold: float = 0.1,
                           strategy: str = "by_disk_size",
                           lock_manager=None,
                           settings=None) -> list[RebalanceMove]:
    """Plan + execute (reference: rebalance_table_shards / the background
    variant citus_rebalance_start — each move runs the non-blocking
    catch-up sequence, so a foreground rebalance only blocks writers
    for the per-move flip windows)."""
    moves = get_rebalance_plan(cat, table_name, threshold, strategy=strategy)
    for m in moves:
        move_shard_placement(cat, m.shard_id, m.source_node, m.target_node,
                             lock_manager=lock_manager, settings=settings)
    return moves
