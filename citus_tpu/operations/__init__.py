"""Cluster operations: node management, shard transfer, rebalancing,
deferred cleanup (reference: src/backend/distributed/operations/)."""

from citus_tpu.operations.shard_transfer import (
    MOVE_STATS, move_shard_placement, copy_shard_placement,
)
from citus_tpu.operations.rebalancer import (
    RebalanceMove, get_rebalance_plan, rebalance_table_shards,
)
from citus_tpu.operations.cleaner import (
    record_cleanup, try_drop_orphaned_resources, pending_cleanup,
    register_operation, complete_operation, operations_view,
)

__all__ = [
    "MOVE_STATS", "move_shard_placement", "copy_shard_placement",
    "RebalanceMove", "get_rebalance_plan", "rebalance_table_shards",
    "record_cleanup", "try_drop_orphaned_resources", "pending_cleanup",
    "register_operation", "complete_operation", "operations_view",
]
