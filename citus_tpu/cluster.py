"""Cluster: the public entry point.

One Cluster = one coordinator over a data directory + a logical node set
that maps onto the JAX device mesh at execution time.  SQL goes through
``execute``; the control-plane operations the reference exposes as UDFs
(create_distributed_table, create_reference_table, ...) are available
both as Python methods and through their SQL spellings
(``SELECT create_distributed_table('t','col')``).
"""

from __future__ import annotations

import json
from typing import Any, Iterable, Optional, Sequence

import numpy as np

import jax

from citus_tpu.catalog import Catalog, DistributionMethod
from citus_tpu.config import Settings, current_settings
from citus_tpu.errors import (
    AnalysisError, CatalogError, ExecutionError, TransactionError,
    UnsupportedFeatureError,
)
from citus_tpu.executor import Result, execute_select
from citus_tpu.ingest import TableIngestor, encode_columns, rows_to_columns
from citus_tpu.planner import ast as A
from citus_tpu.planner import parse_sql
from citus_tpu.planner.bind import bind_select
from citus_tpu.schema import Column, Schema
from citus_tpu.types import type_from_sql


def _option_bool(v) -> bool:
    return str(v).lower() in ("true", "1", "on")


def _has_derived(item) -> bool:
    if isinstance(item, (A.SubqueryRef, A.FunctionRef)):
        return True
    if isinstance(item, A.Join):
        return _has_derived(item.left) or _has_derived(item.right)
    return False


def _srf_result(name: str, args, alias) -> "Result":
    """Evaluate a set-returning FROM function to rows (reference:
    PostgreSQL SRFs; only constant arguments are supported since the
    call is unlateral)."""
    vals = [_eval_const(a) for a in args]
    if name == "generate_series":
        if len(vals) not in (2, 3):
            raise AnalysisError(
                "generate_series(start, stop [, step]) expects 2 or 3 "
                "arguments")
        if any(v is None for v in vals):
            # PostgreSQL: a NULL bound yields zero rows
            return Result(columns=[alias or "generate_series"], rows=[])
        import decimal as _dec
        import math as _math
        numeric = False
        for v in vals:
            if isinstance(v, bool) \
                    or not isinstance(v, (int, float, _dec.Decimal)):
                raise AnalysisError(
                    "generate_series requires numeric bounds "
                    f"(got {v!r}); timestamp series are not supported")
            if (isinstance(v, float) and not _math.isfinite(v)) \
                    or (isinstance(v, _dec.Decimal) and not v.is_finite()):
                raise AnalysisError(
                    "generate_series bound cannot be infinity or NaN")
            if not isinstance(v, int):
                # PostgreSQL: any numeric argument makes the whole
                # series numeric (2.0..4.0 -> 2.0, 3.0, 4.0)
                numeric = True
        if numeric:
            # PostgreSQL numeric generate_series(1.1, 4.0, 1.3) ->
            # 1.1, 2.4, 3.7 — exact decimal stepping
            start = _dec.Decimal(str(vals[0]))
            stop = _dec.Decimal(str(vals[1]))
            step = _dec.Decimal(str(vals[2])) if len(vals) > 2 \
                else _dec.Decimal(1)
            if step == 0:
                raise ExecutionError("step size cannot equal zero")
            rows = []
            v = start
            while (v <= stop) if step > 0 else (v >= stop):
                rows.append((v,))
                v += step
            return Result(columns=[alias or "generate_series"], rows=rows)
        start, stop = int(vals[0]), int(vals[1])
        step = int(vals[2]) if len(vals) > 2 else 1
        if step == 0:
            raise ExecutionError("step size cannot equal zero")
        end = stop + (1 if step > 0 else -1)
        rows = [(v,) for v in range(start, end, step)]
        return Result(columns=[alias or "generate_series"], rows=rows)
    raise UnsupportedFeatureError(
        f"set-returning function {name}() is not supported in FROM")


def _max_param_index(stmt) -> int:
    """Highest $N referenced anywhere in a SELECT (0 when none)."""
    mx = 0

    def visit(e):
        nonlocal mx
        if isinstance(e, A.Param):
            mx = max(mx, e.index)
        elif isinstance(e, A.BinOp):
            visit(e.left), visit(e.right)
        elif isinstance(e, A.UnOp):
            visit(e.operand)
        elif isinstance(e, A.Between):
            visit(e.expr), visit(e.lo), visit(e.hi)
        elif isinstance(e, A.InList):
            visit(e.expr)
            for it in e.items:
                visit(it)
        elif isinstance(e, (A.IsNull, A.Cast)):
            visit(e.expr)
        elif isinstance(e, A.CaseExpr):
            for c, v in e.whens:
                visit(c), visit(v)
            if e.else_ is not None:
                visit(e.else_)
        elif isinstance(e, A.FuncCall):
            for a in e.args:
                visit(a)

    for item in stmt.items:
        visit(item.expr)
    visit(stmt.where)
    visit(stmt.having)
    for g in stmt.group_by:
        visit(g)
    for o in stmt.order_by:
        visit(o.expr)
    return mx


def _eval_const(e):
    """Evaluate a literal-only expression tree to a Python value (SELECT
    without FROM); NULL-propagating arithmetic/comparisons."""
    import decimal as _dec
    if isinstance(e, A.Literal):
        return e.value
    if isinstance(e, A.UnOp):
        v = _eval_const(e.operand)
        if e.op == "-":
            return None if v is None else -v
        return None if v is None else (not v)
    if isinstance(e, A.BinOp):
        if isinstance(e.left, A.IntervalLiteral) \
                or isinstance(e.right, A.IntervalLiteral):
            import datetime as _dt

            from citus_tpu.planner.bound import py_add_interval
            if e.op not in ("+", "-"):
                raise UnsupportedFeatureError(
                    f"operator {e.op} is not defined for intervals")
            ivl = e.right if isinstance(e.right, A.IntervalLiteral) \
                else e.left
            other = e.left if ivl is e.right else e.right
            if ivl is e.left and e.op != "+":
                raise UnsupportedFeatureError(
                    "interval arithmetic supports date/timestamp ± interval")
            v = _eval_const(other)
            if v is None:
                return None
            if not isinstance(v, (_dt.date, _dt.datetime)):
                raise AnalysisError(
                    "cannot add an interval to a non-date value "
                    "(use a typed literal: date '...')")
            sign = 1 if e.op == "+" else -1
            return py_add_interval(v, sign * ivl.months, sign * ivl.days,
                                   sign * ivl.micros)
        l, r = _eval_const(e.left), _eval_const(e.right)
        if e.op == "and":
            if l is False or r is False:
                return False
            return None if (l is None or r is None) else True
        if e.op == "or":
            if l is True or r is True:
                return True
            return None if (l is None or r is None) else False
        if l is None or r is None:
            return None
        if isinstance(l, (int, float)) and isinstance(r, _dec.Decimal):
            l = _dec.Decimal(str(l))
        if isinstance(r, (int, float)) and isinstance(l, _dec.Decimal):
            r = _dec.Decimal(str(r))
        ops = {"+": lambda: l + r, "-": lambda: l - r, "*": lambda: l * r,
               "/": lambda: l / r if r else None,
               "%": lambda: l % r if r else None,
               "=": lambda: l == r, "<>": lambda: l != r,
               "<": lambda: l < r, "<=": lambda: l <= r,
               ">": lambda: l > r, ">=": lambda: l >= r}
        if e.op not in ops:
            raise UnsupportedFeatureError(f"operator {e.op} without FROM")
        return ops[e.op]()
    if isinstance(e, A.IsNull):
        v = _eval_const(e.expr)
        return (v is not None) if e.negated else (v is None)
    if isinstance(e, A.Cast):
        v = _eval_const(e.expr)
        if v is None:
            return None
        t = type_from_sql(e.type_name, list(e.type_args) or None)
        try:
            return t.from_physical(t.to_physical(v))
        except (ValueError, TypeError):
            raise AnalysisError(
                f"invalid input syntax for type {e.type_name}: {v!r}")
    if isinstance(e, A.CaseExpr):
        for c, v in e.whens:
            if _eval_const(c) is True:
                return _eval_const(v)
        return _eval_const(e.else_) if e.else_ is not None else None
    if isinstance(e, A.FuncCall) and e.name == "coalesce":
        for a in e.args:
            v = _eval_const(a)
            if v is not None:
                return v
        return None
    if isinstance(e, A.FuncCall):
        v = _eval_const_func(e)
        if v is not NotImplemented:
            return v
    raise UnsupportedFeatureError(
        f"cannot evaluate {type(e).__name__} without a FROM clause")


def _eval_const_func(e):
    """Constant evaluation of the scalar math/string surface (SELECT
    without FROM); NotImplemented when the function is unknown."""
    import decimal as _dec
    import math as _math
    args = [_eval_const(a) for a in e.args]
    name = e.name
    if name == "pi":
        return _math.pi
    if name in ("current_date", "current_timestamp", "now"):
        import datetime as _dt
        return _dt.date.today() if name == "current_date" \
            else _dt.datetime.now()
    if name == "nullif":
        # NULLIF is not strict: it returns the first argument unless the
        # comparison is true, so nullif(5, NULL) = 5 (PostgreSQL).
        return None if args[0] == args[1] else args[0]
    if any(a is None for a in args):
        # all these functions are strict (NULL in -> NULL out)
        known = {"abs", "floor", "ceil", "ceiling", "round", "trunc",
                 "sign", "sqrt", "exp", "ln", "log", "log10", "log2",
                 "power", "pow", "mod", "degrees", "radians", "greatest",
                 "least", "upper", "lower", "length", "char_length",
                 "strpos", "reverse", "initcap", "trim",
                 "btrim", "ltrim", "rtrim", "replace", "left", "right"}
        if name in ("greatest", "least"):
            vals = [a for a in args if a is not None]
            if not vals:
                return None
            return max(vals) if name == "greatest" else min(vals)
        return None if name in known else NotImplemented
    try:
        if name == "abs":
            return abs(args[0])
        if name in ("floor", "ceil", "ceiling"):
            f = _math.floor if name == "floor" else _math.ceil
            v = f(args[0])
            return _dec.Decimal(v) if isinstance(args[0], _dec.Decimal) \
                else (float(v) if isinstance(args[0], float) else v)
        if name == "round":
            nd = int(args[1]) if len(args) > 1 else 0
            if isinstance(args[0], float):
                # round(double precision) ties to even in PostgreSQL
                return float(round(args[0], nd))
            d = args[0] if isinstance(args[0], _dec.Decimal) \
                else _dec.Decimal(str(args[0]))
            return d.quantize(_dec.Decimal(1).scaleb(-nd),
                              rounding=_dec.ROUND_HALF_UP)
        if name == "trunc":
            nd = int(args[1]) if len(args) > 1 else 0
            d = args[0] if isinstance(args[0], _dec.Decimal) \
                else _dec.Decimal(str(args[0]))
            q = d.quantize(_dec.Decimal(1).scaleb(-nd),
                           rounding=_dec.ROUND_DOWN)
            return float(q) if isinstance(args[0], float) else q
        if name == "sign":
            v = args[0]
            return (v > 0) - (v < 0)
        if name == "sqrt":
            return _math.sqrt(args[0]) if args[0] >= 0 else None
        if name == "exp":
            return _math.exp(args[0])
        if name in ("ln", "log", "log10", "log2"):
            if name == "log" and len(args) == 2:
                return (_math.log(args[1]) / _math.log(args[0])
                        if args[1] > 0 and args[0] > 0 else None)
            if args[0] <= 0:
                return None
            return _math.log(args[0]) if name == "ln" else (
                _math.log2(args[0]) if name == "log2"
                else _math.log10(args[0]))
        if name in ("power", "pow"):
            return float(args[0]) ** float(args[1])
        if name == "mod":
            a, b = args
            if not b:
                return None
            # SQL mod truncates toward zero; exact integer arithmetic
            # (float division would lose precision past 2^53)
            q = abs(a) // abs(b)
            if (a < 0) != (b < 0):
                q = -q
            return a - q * b
        if name == "degrees":
            return _math.degrees(args[0])
        if name == "radians":
            return _math.radians(args[0])
        if name in ("greatest", "least"):
            return max(args) if name == "greatest" else min(args)
        if args and isinstance(args[0], str):
            s = args[0]
            if name == "upper":
                return s.upper()
            if name == "lower":
                return s.lower()
            if name in ("length", "char_length"):
                return len(s)
            if name == "strpos":
                return s.find(str(args[1])) + 1
            if name == "reverse":
                return s[::-1]
            if name == "initcap":
                return s.title()
            if name in ("trim", "btrim"):
                return s.strip(str(args[1]) if len(args) > 1 else None)
            if name == "ltrim":
                return s.lstrip(str(args[1]) if len(args) > 1 else None)
            if name == "rtrim":
                return s.rstrip(str(args[1]) if len(args) > 1 else None)
            if name == "replace":
                return s.replace(str(args[1]), str(args[2]))
            if name == "left":
                return s[:int(args[1])]
            if name == "right":
                n = int(args[1])
                return s[max(0, len(s) - n):] if n >= 0 else s[-n:]
    except (ValueError, OverflowError, ArithmeticError):
        return None
    return NotImplemented


def _expand_returning_items(t, items, subst=None):
    """Expand a RETURNING list to [(expr, output name)]: * becomes the
    table's columns; substitutions (UPDATE assignments, INSERT row
    values) apply after expansion."""
    expanded = []
    for it in items:
        if isinstance(it.expr, A.Star):
            for n in t.schema.names:
                e = A.ColumnRef(n)
                if subst:
                    e = _replace_exprs(e, subst)
                expanded.append((e, n))
        else:
            e = _replace_exprs(it.expr, subst) if subst else it.expr
            expanded.append((e, it.alias or str(it.expr)))
    return expanded


def _replace_exprs(e, mapping: dict):
    """Structural replacement of whole sub-expressions (used to NULL out
    rolled-up grouping columns inside HAVING)."""
    if e in mapping:
        return mapping[e]
    if isinstance(e, A.BinOp):
        return A.BinOp(e.op, _replace_exprs(e.left, mapping),
                       _replace_exprs(e.right, mapping))
    if isinstance(e, A.UnOp):
        return A.UnOp(e.op, _replace_exprs(e.operand, mapping))
    if isinstance(e, A.Between):
        return A.Between(_replace_exprs(e.expr, mapping),
                         _replace_exprs(e.lo, mapping),
                         _replace_exprs(e.hi, mapping), e.negated)
    if isinstance(e, A.InList):
        return A.InList(_replace_exprs(e.expr, mapping),
                        tuple(_replace_exprs(i, mapping) for i in e.items),
                        e.negated)
    if isinstance(e, A.IsNull):
        return A.IsNull(_replace_exprs(e.expr, mapping), e.negated)
    if isinstance(e, A.Cast):
        return A.Cast(_replace_exprs(e.expr, mapping), e.type_name, e.type_args)
    if isinstance(e, A.CaseExpr):
        return A.CaseExpr(tuple((_replace_exprs(c, mapping),
                                 _replace_exprs(v, mapping))
                                for c, v in e.whens),
                          _replace_exprs(e.else_, mapping)
                          if e.else_ is not None else None)
    if isinstance(e, A.FuncCall):
        import dataclasses
        return dataclasses.replace(
            e, args=tuple(_replace_exprs(a, mapping) for a in e.args),
            agg_order=tuple((_replace_exprs(oe, mapping), asc)
                            for oe, asc in e.agg_order),
            filter=_replace_exprs(e.filter, mapping)
            if e.filter is not None else None)
    return e


def _subst_args(e, sub: dict):
    """Replace bare ColumnRefs naming function parameters with the call
    arguments (used by SQL function inlining)."""
    if isinstance(e, A.ColumnRef) and e.table is None and e.name in sub:
        return sub[e.name]
    if isinstance(e, A.BinOp):
        return A.BinOp(e.op, _subst_args(e.left, sub), _subst_args(e.right, sub))
    if isinstance(e, A.UnOp):
        return A.UnOp(e.op, _subst_args(e.operand, sub))
    if isinstance(e, A.Between):
        return A.Between(_subst_args(e.expr, sub), _subst_args(e.lo, sub),
                         _subst_args(e.hi, sub), e.negated)
    if isinstance(e, A.InList):
        return A.InList(_subst_args(e.expr, sub),
                        tuple(_subst_args(i, sub) for i in e.items), e.negated)
    if isinstance(e, A.IsNull):
        return A.IsNull(_subst_args(e.expr, sub), e.negated)
    if isinstance(e, A.Cast):
        return A.Cast(_subst_args(e.expr, sub), e.type_name, e.type_args)
    if isinstance(e, A.CaseExpr):
        return A.CaseExpr(tuple((_subst_args(c, sub), _subst_args(v, sub))
                                for c, v in e.whens),
                          _subst_args(e.else_, sub) if e.else_ is not None else None)
    if isinstance(e, A.FuncCall):
        import dataclasses
        return dataclasses.replace(
            e, args=tuple(_subst_args(a, sub) for a in e.args),
            agg_order=tuple((_subst_args(oe, sub), asc)
                            for oe, asc in e.agg_order),
            filter=_subst_args(e.filter, sub)
            if e.filter is not None else None)
    return e


def _pylit(v) -> A.Literal:
    """Python value -> literal AST node (for synthesized statements)."""
    import decimal as _dec
    if v is None:
        return A.Literal(None, "null")
    if isinstance(v, bool):
        return A.Literal(v, "bool")
    if isinstance(v, int):
        return A.Literal(v, "int")
    if isinstance(v, float):
        return A.Literal(v, "float")
    if isinstance(v, _dec.Decimal):
        return A.Literal(v, "decimal")
    return A.Literal(str(v), "string")


def _subst_excluded(e, excl: dict):
    """Replace ``excluded.col`` references with the proposed row's
    literal values (ON CONFLICT DO UPDATE, PostgreSQL semantics)."""
    if isinstance(e, A.ColumnRef) and e.table == "excluded":
        return excl.get(e.name, A.Literal(None, "null"))
    if isinstance(e, A.BinOp):
        return A.BinOp(e.op, _subst_excluded(e.left, excl),
                       _subst_excluded(e.right, excl))
    if isinstance(e, A.UnOp):
        return A.UnOp(e.op, _subst_excluded(e.operand, excl))
    if isinstance(e, A.Between):
        return A.Between(_subst_excluded(e.expr, excl),
                         _subst_excluded(e.lo, excl),
                         _subst_excluded(e.hi, excl), e.negated)
    if isinstance(e, A.InList):
        return A.InList(_subst_excluded(e.expr, excl),
                        tuple(_subst_excluded(i, excl) for i in e.items),
                        e.negated)
    if isinstance(e, A.IsNull):
        return A.IsNull(_subst_excluded(e.expr, excl), e.negated)
    if isinstance(e, A.Cast):
        return A.Cast(_subst_excluded(e.expr, excl), e.type_name, e.type_args)
    if isinstance(e, A.CaseExpr):
        return A.CaseExpr(
            tuple((_subst_excluded(c, excl), _subst_excluded(v, excl))
                  for c, v in e.whens),
            _subst_excluded(e.else_, excl) if e.else_ is not None else None)
    if isinstance(e, A.FuncCall):
        import dataclasses
        return dataclasses.replace(
            e, args=tuple(_subst_excluded(a, excl) for a in e.args),
            agg_order=tuple((_subst_excluded(oe, excl), asc)
                            for oe, asc in e.agg_order),
            filter=_subst_excluded(e.filter, excl)
            if e.filter is not None else None)
    return e


def _sort_rows(rows, names, order_by):
    """ORDER BY over materialized rows: items resolve by output position
    or output column name (PostgreSQL's rule for set operations)."""
    for oi in reversed(order_by):
        idx = None
        if isinstance(oi.expr, A.Literal) and isinstance(oi.expr.value, int):
            idx = oi.expr.value - 1
        elif isinstance(oi.expr, A.ColumnRef) and oi.expr.table is None \
                and oi.expr.name in names:
            idx = names.index(oi.expr.name)
        if idx is None or not (0 <= idx < len(names)):
            raise AnalysisError(
                "ORDER BY on a set operation must reference an output "
                "column name or position")
        nf = oi.nulls_first if oi.nulls_first is not None else (not oi.ascending)
        nulls = [x for x in rows if x[idx] is None]
        vals = [x for x in rows if x[idx] is not None]
        vals.sort(key=lambda x, j=idx: x[j], reverse=not oi.ascending)
        rows = (nulls + vals) if nf else (vals + nulls)
    return rows


def _limit0(stmt):
    """A zero-row variant of a SELECT-shaped statement (column/type
    probing without scanning)."""
    import dataclasses as _dc
    if isinstance(stmt, (A.Select, A.SetOp)):
        return _dc.replace(stmt, limit=0)
    if isinstance(stmt, A.WithSelect):
        return _dc.replace(stmt, body=_dc.replace(stmt.body, limit=0))
    return stmt


def _from_relations_scope(node) -> set:
    """Relations referenced inside one WITH scope (CTE bodies + body)."""
    inner: set = set()
    for _n, sub in node.ctes:
        inner |= _from_relations(sub)
    inner |= _from_relations(node.body)
    return inner


def _from_relations(s) -> set:
    """Relation names referenced in FROM clauses (incl. joins, derived
    tables, set-op arms) — the self-reference guard for CREATE OR
    REPLACE VIEW."""
    out: set = set()

    def from_item(item):
        if isinstance(item, A.TableRef):
            out.add(item.name)
        elif isinstance(item, A.Join):
            from_item(item.left)
            from_item(item.right)
        elif isinstance(item, A.SubqueryRef):
            walk(item.select)

    def walk(node):
        if isinstance(node, A.SetOp):
            walk(node.left)
            walk(node.right)
        elif isinstance(node, A.WithSelect):
            cte_names = {n for n, _ in node.ctes}
            inner = _from_relations_scope(node)
            out.update(inner - cte_names)
        elif isinstance(node, A.Select) and node.from_ is not None:
            from_item(node.from_)

    walk(s)
    return out


def _infer_column_type(vals):
    """Fallback type inference for intermediate results whose planner
    types are unknown (e.g. window outputs): first non-NULL value wins;
    decimals take the column's max scale."""
    import datetime as _dt
    import decimal as _dec
    from citus_tpu import types as T
    kind = None
    max_scale = 0
    for v in vals:
        if v is None:
            continue
        if isinstance(v, bool):
            return T.BOOL_T
        if isinstance(v, _dec.Decimal):
            kind = "decimal"
            max_scale = max(max_scale, -v.as_tuple().exponent)
        elif isinstance(v, float):
            return T.FLOAT64_T
        elif isinstance(v, int):
            kind = kind or "int"
        elif isinstance(v, str):
            return T.TEXT_T
        elif isinstance(v, _dt.datetime):
            return T.TIMESTAMP_T
        elif isinstance(v, _dt.date):
            return T.DATE_T
        else:
            raise AnalysisError(f"cannot infer a column type from {v!r}")
    if kind == "decimal":
        return T.decimal_t(max(18, max_scale), max(max_scale, 0))
    return T.INT64_T


class Cluster:
    def __init__(self, data_dir: str, *, n_nodes: Optional[int] = None,
                 settings: Optional[Settings] = None,
                 serve_port: Optional[int] = None,
                 coordinator: Optional[tuple] = None):
        self.settings = settings or current_settings()
        self.catalog = Catalog(data_dir)
        if n_nodes is None:
            n_nodes = max(len(jax.devices()), 1)
        self.catalog.ensure_nodes(n_nodes)
        self.catalog.commit()
        # transaction log + recovery on open (reference: 2PC recovery at
        # maintenance-daemon startup, transaction_recovery.c)
        from citus_tpu.transaction import TransactionLog
        from citus_tpu.transaction.recovery import recover_transactions
        self.txlog = TransactionLog(data_dir)
        recover_transactions(self.catalog, self.txlog)
        from citus_tpu.cdc import ChangeDataCapture
        from citus_tpu.utils.clock import CausalClock
        self.clock = CausalClock(data_dir)
        self.cdc = ChangeDataCapture(data_dir, self.settings.enable_change_data_capture)
        # plan cache keyed by SQL text (reference analog: prepared-statement
        # plan caching + local_plan_cache.c); invalidated by table version
        self._plan_cache: dict[str, tuple] = {}
        self._background_jobs = None
        self._maintenance = None
        # per-thread implicit sessions: {thread ident: (Thread, Session)}
        self._default_sessions: dict = {}
        # observability (citus_stat_* / citus_locks analogs)
        from citus_tpu.executor.executor import GLOBAL_COUNTERS
        from citus_tpu.stats import ActivityTracker, QueryStats, TenantStats
        from citus_tpu.transaction import LockManager
        self.counters = GLOBAL_COUNTERS
        self.query_stats = QueryStats()
        self.tenant_stats = TenantStats()
        self.activity = ActivityTracker()
        self.locks = LockManager()
        # thread id -> role active in that thread's execute() call
        self._exec_roles: dict[int, Optional[str]] = {}
        # control plane (reference: metadata sync + 2PC votes over libpq;
        # here an RPC skeleton — net/control_plane.py).  serve_port=N
        # makes this coordinator the metadata authority; coordinator=
        # (host, port) joins one.  Without either, multi-coordinator
        # invalidation falls back to catalog-file mtime polling.
        self._catalog_dirty = False
        self._control = None
        if serve_port is not None or coordinator is not None:
            from citus_tpu.net.control_plane import ControlPlane
            self._control = ControlPlane(self, serve_port=serve_port,
                                         coordinator=coordinator)
            # catalog commits serialize through the authority's DDL
            # lease and ship the document over RPC (push_catalog)
            self.catalog.commit_transport = self._control
        self.catalog.on_commit = self._on_catalog_commit
        # mtime-poll baseline: our own open-time commit; anything newer
        # is a foreign change (avoids missing commits that land between
        # construction and the first execute)
        self._catalog_mtime = getattr(self.catalog, "self_mtime", None)
        # the maintenance daemon starts with the cluster (reference: the
        # per-database daemon starts with the database, maintenanced.c:138)
        # — opt out via settings.start_maintenance_daemon for embedded
        # uses that drive run_once() themselves
        if self.settings.start_maintenance_daemon:
            self.maintenance  # noqa: B018 — property constructs + starts

    def _peer_inflight(self) -> set:
        if self._control is not None:
            return self._control.peer_inflight_xids()
        return set()

    def _on_catalog_commit(self) -> None:
        if self._control is not None:
            self._control.publish_catalog_change()

    def _on_foreign_catalog_applied(self) -> None:
        """A pushed catalog document was just stored into our live
        catalog (authority side): drop cached plans keyed on the old
        metadata."""
        self._plan_cache.clear()

    @property
    def control_port(self) -> Optional[int]:
        if self._control is not None and self._control.server is not None:
            return self._control.server.port
        return None

    @property
    def background_jobs(self):
        """Lazy background task runner (reference: background_jobs.c)."""
        if self._background_jobs is None:
            from citus_tpu.operations import move_shard_placement
            from citus_tpu.services import BackgroundJobRunner
            r = BackgroundJobRunner(self.catalog)
            r.register("move_shard", lambda shard_id, source, target:
                       move_shard_placement(self.catalog, shard_id, source, target,
                                            lock_manager=self.locks))
            r.start()
            self._background_jobs = r
        return self._background_jobs

    @property
    def maintenance(self):
        """Lazy maintenance daemon (reference: maintenanced.c)."""
        if self._maintenance is None:
            from citus_tpu.services import MaintenanceDaemon
            from citus_tpu.transaction.recovery import recover_transactions
            d = MaintenanceDaemon(self.catalog)
            # 2PC recovery duty (reference: Recover2PCInterval, default 60 s)
            d.register("transaction_recovery",
                       lambda: recover_transactions(
                           self.catalog, self.txlog,
                           peer_inflight=self._peer_inflight()),
                       interval_s=60.0)
            # global deadlock detection (reference:
            # CheckForDistributedDeadlocks every 2 s,
            # distributed_deadlock_detection.c:105)
            from citus_tpu.transaction.global_deadlock import run_detection
            d.register("deadlock_detection",
                       lambda: run_detection(self),
                       interval_s=lambda:
                       self.settings.deadlock_detection_interval_s)
            if self._control is not None:
                # authority health / lease-based promotion (reference:
                # node_promotion.c; HA via external failover managers in
                # the reference, built-in here)
                d.register("authority_watch",
                           lambda: self._control.ensure_authority(),
                           interval_s=lambda:
                           self.settings.authority_watch_interval_s)
            d.start()
            self._maintenance = d
        return self._maintenance

    def close(self) -> None:
        # open transactions on the per-thread default sessions roll back
        # (connection-close semantics)
        for _owner, ds in list(getattr(self, "_default_sessions", {}).values()):
            if ds.txn is not None:
                self._rollback_txn(ds)
        if self._background_jobs is not None:
            self._background_jobs.stop()
        if self._maintenance is not None:
            self._maintenance.stop()
        if self._control is not None:
            self._control.close()
        # release the transaction-log owner marker: our undecided
        # transactions become recoverable by other coordinators
        self.txlog.close()

    def _write_lock(self, table_meta, mode: str):
        """Serialize writers on a table's colocation group (the analog of
        LockShardResource / SerializeNonCommutativeWrites,
        utils/resource_lock.c): EXCLUSIVE for UPDATE/DELETE/MERGE/
        TRUNCATE/VACUUM (their scan→bitmap→re-insert sequences are not
        commutative), SHARED for append-only ingest.  Shard moves/splits
        take EXCLUSIVE on the same resource across their final catch-up
        and metadata flip, so a writer can never commit into a placement
        being retired.  Two-layer (thread LockManager + process flock);
        after acquisition the catalog is refreshed so a writer that
        waited out a foreign mover sees the flipped placements."""
        import contextlib

        @contextlib.contextmanager
        def _ctx():
            from citus_tpu.storage.overlay import current_overlay
            txn = current_overlay()
            if txn is not None:
                # inside BEGIN..COMMIT: two-phase locking — acquire into
                # the transaction and retain until COMMIT/ROLLBACK
                # (reference holds shard locks to transaction end)
                txn.hold_group_lock(self, table_meta, mode)
                yield
                return
            from citus_tpu.transaction.write_locks import group_write_lock
            with group_write_lock(self.catalog, table_meta, mode,
                                  lock_manager=self.locks,
                                  timeout=self.settings.executor.lock_timeout_s):
                # force_sync: an RPC invalidation push may not have
                # arrived yet; a writer that just waited out a mover must
                # check staleness synchronously before touching placements
                self._maybe_reload_catalog(force_sync=True)
                yield
        return _ctx()

    def _maybe_reload_catalog(self, force_sync: bool = False) -> None:
        """Pick up metadata written by other coordinators sharing this
        data dir (the query-from-any-node / MX analog: any process can
        plan and execute once metadata is synced; reference:
        metadata/metadata_sync.c).  With a control plane attached,
        invalidation arrives as an RPC push (syscache-invalidation
        analog); otherwise fall back to catalog-file mtime polling.
        Writes made by THIS process must not trigger a reload:
        concurrent sessions hold references into the live catalog, and
        reloading underneath them (clear + load) is a read-tear race."""
        import os
        if self._control is not None and self._control.connected:
            if self._catalog_dirty:
                self._catalog_dirty = False
                self._reload_catalog()
                try:
                    self._catalog_mtime = os.path.getmtime(self.catalog._path())
                except OSError:
                    pass
                return
            if not force_sync:
                return
            # fall through to the synchronous mtime check: write paths
            # cannot rely on the asynchronous push having arrived
        p = self.catalog._path()
        try:
            mtime = os.path.getmtime(p)
        except OSError:
            return
        if mtime == getattr(self.catalog, "self_mtime", None):
            self._catalog_mtime = mtime
            return
        if getattr(self, "_catalog_mtime", None) is None:
            self._catalog_mtime = mtime
            return
        if mtime != self._catalog_mtime:
            self._catalog_mtime = mtime
            self._reload_catalog()

    def _reload_catalog(self) -> None:
        # with an authority attached, the catalog document itself comes
        # over RPC (fetch_catalog) — the file is only the fallback
        doc = None
        if self._control is not None and self._control.connected:
            try:
                doc = self._control.fetch_catalog_doc()
            except Exception:
                doc = None
        with self.catalog._lock:
            # swap, never clear-then-refill: load_document reassigns each
            # section dict atomically, so concurrent readers see either
            # the old or the new state — no read-tear window
            self.catalog._dicts = {}
            self.catalog._dict_index = {}
            self.catalog._dict_sig = {}
            import os as _os
            if doc is not None:
                self.catalog.load_document(doc)
            elif _os.path.exists(self.catalog._path()):
                self.catalog._load()
            else:
                self.catalog.tables = {}
                self.catalog.nodes = {}
            self.catalog.ddl_epoch += 1  # invalidate cached plans
        self._plan_cache.clear()

    # ------------------------------------------------------------- DDL
    def create_table(self, name: str, schema: Schema, *, if_not_exists: bool = False,
                     **columnar_opts) -> None:
        if if_not_exists and self.catalog.has_table(name):
            return
        col = self.settings.columnar
        opts = {
            "chunk_row_limit": int(columnar_opts.get("chunk_group_row_limit", col.chunk_group_row_limit)),
            "stripe_row_limit": int(columnar_opts.get("stripe_row_limit", col.stripe_row_limit)),
            "compression": columnar_opts.get("compression", col.compression),
            "compression_level": int(columnar_opts.get("compression_level", col.compression_level)),
        }
        self.catalog.create_table(name, schema, **opts)
        self.catalog.commit()

    def drop_table(self, name: str, *, if_exists: bool = False) -> None:
        if if_exists and not self.catalog.has_table(name):
            return
        from citus_tpu.integrity import forbid_drop_referenced
        forbid_drop_referenced(self.catalog, name)
        t = self.catalog.table(name)
        if t.is_partitioned:
            # PostgreSQL: dropping the parent drops its partitions
            for p in list(self.catalog.partitions_of(name)):
                self.drop_table(p.name)
        self.catalog.drop_table(name)
        for key in [k for k in self.catalog.enum_columns
                    if k.startswith(name + ".")]:
            del self.catalog.enum_columns[key]
        if self.catalog.policies.pop(name, None) is not None:
            self.catalog.tombstone("policies", name)
        if self.catalog.rls.pop(name, None) is not None:
            self.catalog.tombstone("rls", name)
        for tn in [n for n, t in self.catalog.triggers.items()
                   if t.get("table") == name]:
            del self.catalog.triggers[tn]
            self.catalog.tombstone("triggers", tn)
        for key in [k for k in self.catalog.domain_columns
                    if k.startswith(name + ".")]:
            del self.catalog.domain_columns[key]
            self.catalog.tombstone("domain_columns", key)
        for pub in self.catalog.publications.values():
            tl = pub.get("tables")
            if isinstance(tl, list) and name in tl:
                tl.remove(name)  # PostgreSQL drops the table from pubs
        self.catalog.commit()

    # ------------------------------------------------------- partitioning
    def _internal_txn(self):
        """All-or-nothing wrapper for engine-generated multi-statement
        work (multi-partition writes): inside a user transaction it is
        transparent (that transaction provides atomicity); otherwise it
        opens, stages, and 2PC-commits an internal one, rolling back on
        any failure."""
        import contextlib

        @contextlib.contextmanager
        def _ctx():
            from citus_tpu.storage.overlay import (
                current_overlay, transaction_overlay,
            )
            if current_overlay() is not None:
                yield
                return
            from citus_tpu.transaction.session import OpenTransaction
            s = self.session()
            xid = self.txlog.begin()
            s.txn = OpenTransaction(xid, s.lock_sid)
            s.txn.tombstones_snapshot = {
                k: set(v) for k, v in self.catalog._tombstones.items()}
            try:
                with transaction_overlay(s.txn):
                    yield
            except BaseException:
                self._rollback_txn(s)
                raise
            self._commit_txn(s)
        return _ctx()

    def _create_partition(self, name: str, parent: str, lo_raw, hi_raw,
                          *, if_not_exists: bool = False) -> None:
        """CREATE TABLE name PARTITION OF parent FOR VALUES FROM..TO:
        clone the parent's schema, record physical bounds, inherit the
        parent's distribution (siblings colocate).  Reference:
        PostgreSQL partition DDL distributed per-partition
        (multi_partitioning_utils.c)."""
        from citus_tpu.partitioning import bound_to_physical, check_new_partition
        if if_not_exists and self.catalog.has_table(name):
            return
        pt = self.catalog.table(parent)
        if not pt.is_partitioned:
            raise CatalogError(f'"{parent}" is not partitioned')
        col = pt.schema.column(pt.partition_by["column"])
        lo = bound_to_physical(col.type, lo_raw)
        hi = bound_to_physical(col.type, hi_raw)
        check_new_partition(self.catalog, pt, lo, hi)
        self.catalog.create_table(
            name, pt.schema,
            chunk_row_limit=pt.chunk_row_limit,
            stripe_row_limit=pt.stripe_row_limit,
            compression=pt.compression,
            compression_level=pt.compression_level)
        t = self.catalog.table(name)
        t.partition_of = {"parent": parent, "lo": lo, "hi": hi}
        # constraints declared on the parent apply to every partition
        # (PostgreSQL propagates both; unique keys were validated at
        # parent creation to include the partition column)
        import json as _json
        t.foreign_keys = _json.loads(_json.dumps(pt.foreign_keys))
        if pt.method == DistributionMethod.HASH:
            siblings = [p for p in self.catalog.partitions_of(parent)
                        if p.name != name and p.is_distributed]
            self.catalog.distribute_table(
                name, pt.dist_column,
                pt.partition_by.get("shard_count")
                or self.settings.sharding.shard_count,
                self.catalog.active_node_ids(),
                colocate_with=siblings[0].name if siblings else None,
                replication_factor=self.settings.sharding.shard_replication_factor)
        self.catalog.commit()
        for ix in pt.indexes:
            self.create_index(f"{name}_{ix['column']}_key", name,
                              ix["column"], unique=ix.get("unique", False))
        self._plan_cache.clear()

    def _truncate_one(self, name: str) -> None:
        """Truncate one (possibly partitioned) relation; FK validation
        happens at the statement level, list-aware."""
        from citus_tpu.executor.dml import execute_truncate
        from citus_tpu.transaction.locks import EXCLUSIVE
        t = self.catalog.table(name)
        if t.is_partitioned:
            for p in self.catalog.partitions_of(name):
                self._truncate_one(p.name)
            return
        with self._write_lock(t, EXCLUSIVE):
            execute_truncate(self.catalog, self.catalog.table(name))
        self._plan_cache.clear()
        if self._cdc_captures(t.name):
            self.cdc.emit(t.name, "truncate",
                          self.clock.transaction_clock(), force=True)

    def _fanout_partitions(self, stmt, *, aggregate_explain: bool = False
                           ) -> Result:
        """Run a single-table utility statement (TRUNCATE, VACUUM) on
        every partition of the named parent, optionally summing the
        integer explain stats."""
        import dataclasses as _dc
        agg: dict = {}
        for p in self.catalog.partitions_of(stmt.table):
            sub = self._execute_stmt(_dc.replace(stmt, table=p.name))
            if aggregate_explain:
                for k, v in sub.explain.items():
                    agg[k] = agg.get(k, 0) + v
        return Result(columns=[], rows=[], explain=agg)

    def _partition_dml(self, stmt, t) -> Result:
        """UPDATE/DELETE against a partitioned parent: run per surviving
        partition (pruned on the WHERE) and sum the counts."""
        import dataclasses
        from citus_tpu.partitioning import prune_partitions
        if getattr(stmt, "returning", None):
            raise UnsupportedFeatureError(
                "RETURNING on a partitioned parent is not supported")
        if isinstance(stmt, A.Update):
            pcol = t.partition_by["column"]
            if any(c == pcol for c, _ in stmt.assignments):
                raise UnsupportedFeatureError(
                    "updating the partition column (row movement) is "
                    "not supported; DELETE the rows and re-INSERT them "
                    "through the parent so they route to the right "
                    "partition")
        total_key = "updated" if isinstance(stmt, A.Update) else "deleted"
        total = 0
        # atomic across partitions: a later partition's failure must not
        # leave earlier partitions' writes committed
        with self._internal_txn():
            for p in prune_partitions(self.catalog, t, stmt.where):
                sub = dataclasses.replace(stmt, table=p.name)
                r = self._execute_stmt(sub)
                total += r.explain.get(total_key, 0)
        return Result(columns=[], rows=[], explain={total_key: total})

    def _copy_into_partitions(self, t, columns) -> int:
        """Route an ingest batch against a partitioned parent to its
        partitions by range (the multi-level ShardIdForTuple)."""
        from citus_tpu.partitioning import partition_for_rows
        pcol = t.partition_by["column"]
        if pcol not in columns:
            raise AnalysisError(f"missing column {pcol!r} in ingest batch")
        col = t.schema.column(pcol)
        raw = columns[pcol]
        if isinstance(raw, np.ndarray) and raw.dtype != object \
                and raw.dtype.kind in "iuf":
            # mirror encode_columns' numeric fast path exactly (decimal
            # floats scale by 10^scale with ROUND_HALF_UP; integer input
            # is already physical), so routing and storage agree
            if col.type.kind == "decimal" \
                    and np.issubdtype(raw.dtype, np.floating):
                x = raw * float(10 ** col.type.scale)
                phys = np.where(x >= 0, np.floor(x + 0.5),
                                np.ceil(x - 0.5)).astype(np.int64)
            else:
                phys = raw.astype(col.type.storage_dtype)
        else:
            vals = list(raw)
            if any(v is None for v in vals):
                raise AnalysisError(
                    f'no partition of relation "{t.name}" found for row '
                    f"({pcol} is null)")
            phys = np.asarray([col.type.to_physical(v) for v in vals])
        n = 0
        cols_np = {c: (v if isinstance(v, np.ndarray)
                       else np.asarray(v, dtype=object))
                   for c, v in columns.items()}
        routed = partition_for_rows(self.catalog, t, phys)
        # atomic across partitions (a unique violation in the second
        # partition must not leave the first partition's rows behind)
        with self._internal_txn():
            for pname, mask in routed:
                sub = {c: v[mask] for c, v in cols_np.items()}
                n += self.copy_from(pname, columns=sub)
        return n

    def _drop_catalog_object(self, section: str, stmt) -> Result:
        """DROP for the simple metadata-object sections (extension,
        domain, collation, publication, statistics)."""
        store = getattr(self.catalog, section)
        if stmt.name not in store:
            if stmt.if_exists:
                return Result(columns=[], rows=[])
            raise CatalogError(
                f'{section[:-1]} "{stmt.name}" does not exist')
        del store[stmt.name]
        self.catalog.tombstone(section, stmt.name)
        self.catalog.ddl_epoch += 1
        self.catalog.commit()
        return Result(columns=[], rows=[])

    # ----------------------------------------------------------- indexes
    def _find_index(self, name: str):
        """-> (table_meta, index dict) or (None, None)."""
        for t in self.catalog.tables.values():
            for ix in t.indexes:
                if ix["name"] == name:
                    return t, ix
        return None, None

    def _drop_index_segments(self, t, column: str) -> None:
        from citus_tpu.storage.index import drop_segments
        import os as _os
        for shard in t.shards:
            for node in shard.placements:
                d = self.catalog.shard_dir(t.name, shard.shard_id, node)
                if _os.path.isdir(d):
                    drop_segments(d, column)

    def _drop_index_segments_if_unindexed(self, table_name: str,
                                          column: str) -> None:
        """Deferred (COMMIT-time) segment removal: a same-name index
        recreated later in the transaction must keep its fresh segments;
        a dropped table's removal owns its whole directory."""
        if not self.catalog.has_table(table_name):
            return
        t2 = self.catalog.table(table_name)
        if t2.index_on(column) is None:
            self._drop_index_segments(t2, column)

    def create_index(self, name: str, table: str, column: str, *,
                     unique: bool = False,
                     if_not_exists: bool = False) -> None:
        """CREATE [UNIQUE] INDEX: register the index, validate existing
        data for UNIQUE, and backfill per-stripe segments on every
        placement (reference: commands/index.c DDL propagation +
        columnar_index_build_range_scan, columnar_tableam.c:1444)."""
        from citus_tpu.storage.index import backfill_index
        from citus_tpu.transaction.locks import EXCLUSIVE
        existing_t, existing = self._find_index(name)
        if existing is not None:
            if if_not_exists:
                return
            raise CatalogError(f'index "{name}" already exists')
        t = self.catalog.table(table)
        if t.is_partitioned:
            raise UnsupportedFeatureError(
                "CREATE INDEX on a partitioned parent is not supported; "
                "create the index on each partition")
        t.schema.column(column)  # must exist
        if t.schema.column(column).type.is_float and unique:
            raise UnsupportedFeatureError(
                "UNIQUE indexes over floating-point columns are not "
                "supported (no exact equality)")
        if t.index_on(column) is not None:
            raise CatalogError(
                f'column "{column}" of "{table}" is already indexed')
        ix = {"name": name, "column": column, "unique": bool(unique)}
        # EXCLUSIVE write lock: no ingest may slip between the uniqueness
        # validation / backfill and the catalog flip
        from citus_tpu.storage.overlay import current_overlay
        with self._write_lock(t, EXCLUSIVE):
            if unique:
                from citus_tpu.integrity import validate_unique_backfill
                validate_unique_backfill(self.catalog, t, ix)
            # segments first, catalog second: a backfill failure must
            # leave no in-memory claim of an index that was never built
            backfill_index(self.catalog, t, [column])
            txn = current_overlay()
            if txn is not None:
                # ROLLBACK must remove the backfilled segments (additive
                # files: invisible to peers until the catalog commits)
                txn.on_rollback.append(
                    lambda: self._drop_index_segments(t, column))
            t.indexes.append(ix)
            t.version += 1
            self.catalog.ddl_epoch += 1
            self.catalog.commit()
        self._plan_cache.clear()

    def _execute_create_index(self, stmt: A.CreateIndex) -> Result:
        self.create_index(stmt.name, stmt.table, stmt.column,
                          unique=stmt.unique,
                          if_not_exists=stmt.if_not_exists)
        return Result(columns=[], rows=[])

    def _execute_drop_index(self, stmt: A.DropIndex) -> Result:
        t, ix = self._find_index(stmt.name)
        if ix is None:
            if stmt.if_exists:
                return Result(columns=[], rows=[])
            raise CatalogError(f'index "{stmt.name}" does not exist')
        from citus_tpu.storage.overlay import current_overlay
        from citus_tpu.transaction.locks import EXCLUSIVE
        with self._write_lock(t, EXCLUSIVE):
            t.indexes.remove(ix)
            # another index may not share the column (enforced at CREATE)
            txn = current_overlay()
            if txn is not None:
                # segment removal is irreversible: defer to COMMIT
                col = ix["column"]
                tname = t.name
                txn.on_commit.append(
                    lambda: self._drop_index_segments_if_unindexed(tname, col))
            else:
                self._drop_index_segments(t, ix["column"])
            t.version += 1
            self.catalog.ddl_epoch += 1
            self.catalog.commit()
        self._plan_cache.clear()
        return Result(columns=[], rows=[])

    def create_distributed_table(self, name: str, dist_column: str,
                                 shard_count: Optional[int] = None,
                                 colocate_with: Optional[str] = None) -> None:
        """reference: create_distributed_table UDF
        (src/backend/distributed/commands/create_distributed_table.c)."""
        t = self.catalog.table(name)
        if t.is_partitioned:
            # distribute every partition (colocated siblings) and record
            # the distribution on the metadata-only parent
            shard_count = shard_count or self.settings.sharding.shard_count
            t.schema.column(dist_column)
            first = None
            for p in self.catalog.partitions_of(name):
                self.create_distributed_table(
                    p.name, dist_column, shard_count,
                    colocate_with=first or colocate_with)
                first = first or p.name
            t.method = DistributionMethod.HASH
            t.dist_column = dist_column
            t.partition_by["shard_count"] = shard_count
            if first is not None:
                t.colocation_id = self.catalog.table(first).colocation_id
            t.version += 1
            self.catalog.commit()
            return
        from citus_tpu.catalog.stats import table_row_count
        if table_row_count(self.catalog, t) > 0:
            raise UnsupportedFeatureError(
                "distributing a non-empty table is not supported yet; "
                "create, distribute, then load")
        shard_count = shard_count or self.settings.sharding.shard_count
        self.catalog.distribute_table(
            name, dist_column, shard_count, self.catalog.active_node_ids(),
            colocate_with=colocate_with,
            replication_factor=self.settings.sharding.shard_replication_factor)
        try:
            from citus_tpu.integrity import validate_fk_distribution
            validate_fk_distribution(self.catalog, name)
        except Exception:
            self.catalog._load()  # roll back the uncommitted distribution
            raise
        self.catalog.commit()

    def create_reference_table(self, name: str) -> None:
        t = self.catalog.table(name)
        from citus_tpu.catalog.stats import table_row_count
        if table_row_count(self.catalog, t) > 0:
            raise UnsupportedFeatureError(
                "converting a non-empty table is not supported yet")
        self.catalog.make_reference_table(name, self.catalog.active_node_ids())
        try:
            from citus_tpu.integrity import validate_fk_distribution
            validate_fk_distribution(self.catalog, name)
        except Exception:
            self.catalog._load()
            raise
        self.catalog.commit()

    # ----------------------------------------------------------- ingest
    def copy_from(self, table_name: str,
                  columns: Optional[dict[str, Sequence[Any]]] = None,
                  rows: Optional[Iterable[Sequence[Any]]] = None,
                  column_names: Optional[list[str]] = None,
                  session=None) -> int:
        """Bulk load (the COPY analog).  Either ``columns`` (dict of
        arrays/lists, fastest) or ``rows`` (iterable of tuples).  Inside
        an open transaction (``session`` with BEGIN, or called from a
        statement of one) the write stages under the transaction's xid
        and commits with it."""
        from citus_tpu.storage.overlay import current_overlay, transaction_overlay
        if session is None:
            # match execute(): a BEGIN issued through cl.execute() opens
            # a transaction on the shared default session, and a COPY
            # issued the same way must join it, not autocommit past it
            session = self._default_session()
        if session.txn is not None and current_overlay() is None:
            if session.txn.failed:
                from citus_tpu.transaction.session import InFailedTransaction
                raise InFailedTransaction(
                    "current transaction is aborted, commands ignored "
                    "until end of transaction block")
            with transaction_overlay(session.txn):
                try:
                    return self.copy_from(table_name, columns=columns,
                                          rows=rows,
                                          column_names=column_names)
                except Exception:
                    session.txn.failed = True
                    raise
        t = self.catalog.table(table_name)
        if (columns is None) == (rows is None):
            raise AnalysisError("provide exactly one of columns= or rows=")
        if rows is not None:
            columns = rows_to_columns(t.schema.names, rows, column_names)
        if t.is_partitioned:
            # two-level routing: range partition first, then hash shard
            # within it (each recursive call re-enters with the same
            # session/transaction context)
            return self._copy_into_partitions(t, columns)
        self._check_domains(t, columns)
        values, validity = encode_columns(self.catalog, t, columns)
        if t.partition_of is not None:
            from citus_tpu.partitioning import check_partition_bounds
            check_partition_bounds(self.catalog, t, values, validity)
        import contextlib as _ctxlib

        from citus_tpu.transaction.locks import EXCLUSIVE, SHARED
        txn = current_overlay()
        # unique enforcement needs probe+write atomicity: two SHARED
        # ingests could both miss the probe and insert the same key.
        # The mode is re-derived from the fresh TableMeta inside the
        # lock — a CREATE UNIQUE INDEX committed after our stale fetch
        # must escalate us before the probe runs.
        lock_mode = EXCLUSIVE if t.unique_indexes else SHARED
        while True:
            with self._write_lock(t, lock_mode):
                t = self.catalog.table(table_name)  # re-fetch: fresh placements
                if t.unique_indexes and lock_mode == SHARED:
                    lock_mode = EXCLUSIVE
                    continue  # retry under the stronger lock
                self._copy_from_locked(t, txn, columns, values, validity)
                break
        n = len(next(iter(values.values()))) if values else 0
        self.counters.bump("rows_ingested", n)
        if self._cdc_captures(t.name) and n:
            self._emit_cdc(t.name, "insert",
                           rows=self._decode_rows(t, values, validity),
                           columns=t.schema.names)
        return n

    def _copy_from_locked(self, t, txn, columns, values, validity) -> None:
        """copy_from's body under the table write lock: FK + unique
        probes, then the staged or 2PC ingest."""
        import contextlib as _ctxlib

        from citus_tpu.transaction.locks import SHARED
        with _ctxlib.ExitStack() as stack:
            if t.foreign_keys:
                # hold the parents' group locks (SHARED) across
                # probe + write, so a concurrent parent DELETE
                # (EXCLUSIVE on the parent group) cannot interleave
                # between the FK check and the ingest commit
                from citus_tpu.integrity import check_ingest
                from citus_tpu.transaction.write_locks import (
                    group_resource, group_write_lock,
                )
                parents = {}
                for fk in t.foreign_keys:
                    p = self.catalog.table(fk["ref_table"])
                    parents[group_resource(p)] = p
                for res in sorted(parents):
                    if txn is not None:
                        txn.hold_group_lock(self, parents[res], SHARED)
                    else:
                        stack.enter_context(group_write_lock(
                            self.catalog, parents[res], SHARED,
                            lock_manager=self.locks,
                            timeout=self.settings.executor.lock_timeout_s))
                check_ingest(self, t, columns)
            if t.unique_indexes:
                from citus_tpu.integrity import check_unique_ingest
                check_unique_ingest(self, t, values, validity)
            if txn is not None:
                # stage under the open transaction; COMMIT flips it.
                # On failure, REGISTER (don't abort) what was staged:
                # aborting the xid would destroy earlier statements'
                # staged rows; registration lets ROLLBACK [TO
                # SAVEPOINT] clean exactly this statement's stripes.
                ing = TableIngestor(self.catalog, t, txlog=None)
                ing.xid = txn.xid
                try:
                    ing.append(values, validity)
                    for w in ing._writers.values():
                        w.flush()
                finally:
                    txn.record_ingest(
                        t.name,
                        [w.directory for w in ing._writers.values()])
            else:
                ing = TableIngestor(self.catalog, t, txlog=self.txlog)
                try:
                    ing.append(values, validity)
                except BaseException:
                    ing.abort()
                    raise
                ing.finish()

    def _domain_columns_of(self, t) -> list[tuple[str, str, dict]]:
        """[(column, domain name, domain def)] for ``t``."""
        out = []
        for cname in t.schema.names:
            dn = self.catalog.domain_columns.get(f"{t.name}.{cname}")
            if dn is None:
                continue
            dom = self.catalog.domains.get(dn)
            if dom is not None:
                out.append((cname, dn, dom))
        return out

    def _check_domain_values(self, dn: str, dom: dict, values) -> None:
        """Evaluate one domain's CHECK over an iterable of logical
        values.  Distinct-value memoization keeps categorical bulk
        ingest cheap; NULL passes CHECK (NOT NULL is the column's)."""
        import numpy as _np
        from citus_tpu.planner.parser import Parser as _P
        if not dom.get("check"):
            return
        expr = _P(dom["check"]).parse_expr()
        verdicts: dict = {}
        for v in values:
            if v is None:
                continue
            if isinstance(v, _np.generic):
                v = v.item()
            ok = verdicts.get(v)
            if ok is None:
                sub = {A.ColumnRef("value"): _pylit(v)}
                try:
                    ok = _eval_const(_replace_exprs(expr, sub)) is True
                except Exception:
                    raise UnsupportedFeatureError(
                        f'cannot evaluate CHECK of domain "{dn}" '
                        f"({dom['check']!r})")
                verdicts[v] = ok
            if not ok:
                raise ExecutionError(
                    f'value {v!r} for domain "{dn}" violates check '
                    f"constraint ({dom['check']})")

    def _check_domains(self, t, columns) -> None:
        """Domain CHECK enforcement at ingest (reference: domain
        constraints fire on every insert; VALUE names the checked
        value)."""
        for cname, dn, dom in self._domain_columns_of(t):
            if cname in columns:
                self._check_domain_values(dn, dom, columns[cname])

    def _check_domains_physical(self, t, values, validity) -> None:
        """Same enforcement over PHYSICAL column arrays (the UPDATE
        re-insert path): decode back to logical values first."""
        for cname, dn, dom in self._domain_columns_of(t):
            if cname not in values or not dom.get("check"):
                continue
            col = t.schema.column(cname)
            vals = []
            for phys, ok in zip(values[cname], validity[cname]):
                if not ok:
                    continue
                if col.type.is_text:
                    vals.append(self.catalog.decode_strings(
                        t.name, cname, [int(phys)])[0])
                else:
                    vals.append(col.type.from_physical(
                        np.asarray(phys).item()))
            self._check_domain_values(dn, dom, vals)

    def _cdc_captures(self, table: str) -> bool:
        """The table's changes are captured when CDC is globally on OR
        any publication covers it (reference: commands/publication.c —
        publications gate logical decoding per table)."""
        if self.cdc.enabled:
            return True
        if not self.catalog.publications:
            return False
        # a publication on a partitioned parent covers its partitions
        # (writes route to leaves before this gate runs)
        names = {table}
        t = self.catalog.tables.get(table)
        if t is not None and t.partition_of is not None:
            names.add(t.partition_of["parent"])
        for pub in self.catalog.publications.values():
            tl = pub.get("tables")
            if tl == "all" or (isinstance(tl, list) and names & set(tl)):
                return True
        return False

    def _emit_cdc(self, table: str, op: str, **kw) -> None:
        """Emit a change event — or, inside an open transaction, defer
        it to COMMIT (PostgreSQL logical decoding emits on commit)."""
        from citus_tpu.storage.overlay import current_overlay
        txn = current_overlay()
        if txn is not None:
            txn.cdc_events.append((table, op, kw))
        else:
            self.cdc.emit(table, op, self.clock.transaction_clock(),
                          force=True, **kw)

    def _decode_rows(self, t, values, validity) -> list:
        out = []
        names = t.schema.names
        n = len(next(iter(values.values())))
        text_cache = {}
        for c in names:
            col = t.schema.column(c)
            if col.type.is_text:
                text_cache[c] = self.catalog.decode_strings(
                    t.name, c, values[c].tolist())
        for i in range(n):
            row = []
            for c in names:
                col = t.schema.column(c)
                if not validity[c][i]:
                    row.append(None)
                elif col.type.is_text:
                    row.append(text_cache[c][i])
                else:
                    row.append(col.type.from_physical(values[c][i].item()))
            out.append(row)
        return out

    def copy_from_csv(self, table_name: str, path: str, *,
                      delimiter: str = ",", header: bool = False,
                      null_string: str = "", batch_rows: int = 200_000) -> int:
        """Bulk load from a CSV file, streamed in batches (the reference's
        COPY FROM with per-shard stream switchover,
        commands/multi_copy.c)."""
        import csv
        t = self.catalog.table(table_name)
        names = t.schema.names
        total = 0
        with open(path, newline="") as fh:
            reader = csv.reader(fh, delimiter=delimiter)
            if header:
                next(reader, None)
            batch: list = []
            for row in reader:
                batch.append([None if v == null_string else v for v in row])
                if len(batch) >= batch_rows:
                    total += self.copy_from(table_name, rows=batch)
                    batch = []
            if batch:
                total += self.copy_from(table_name, rows=batch)
        return total

    @staticmethod
    def _open_csv_writer(fh, columns, *, delimiter: str, header: bool):
        """One CSV emission convention for both COPY TO forms."""
        import csv
        w = csv.writer(fh, delimiter=delimiter)
        if header:
            w.writerow(columns)
        return w

    def copy_to_csv(self, table_name: str, path: str, *,
                    delimiter: str = ",", header: bool = False,
                    null_string: str = "") -> int:
        """Streaming CSV export: shards are read batch by batch, decoded,
        and written incrementally (symmetric with copy_from_csv)."""
        import os as _os
        from citus_tpu.storage import ShardReader
        from citus_tpu.transaction.write_locks import flip_latch
        t = self.catalog.table(table_name)
        names = t.schema.names
        total = 0
        with open(path, "w", newline="") as fh, \
                flip_latch(self.catalog.data_dir, t, shared=True,
                           timeout=self.settings.executor.lock_timeout_s):
            # SHARED flip latch: the multi-shard export must not
            # interleave with TRUNCATE's per-shard flips
            w = self._open_csv_writer(fh, names, delimiter=delimiter,
                                      header=header)
            for shard in t.shards:
                d = self.catalog.shard_dir(table_name, shard.shard_id,
                                           shard.placements[0])
                if not _os.path.isdir(d):
                    continue
                reader = ShardReader(d, t.schema)
                for batch in reader.scan(names):
                    decoded = {}
                    for c in names:
                        col = t.schema.column(c)
                        vals = batch.values[c]
                        if col.type.is_text:
                            decoded[c] = self.catalog.decode_strings(
                                table_name, c, vals.tolist())
                        else:
                            decoded[c] = [col.type.from_physical(v.item())
                                          for v in vals]
                    for i in range(batch.row_count):
                        row = []
                        for c in names:
                            m = batch.validity[c]
                            if m is not None and not m[i]:
                                row.append(null_string)
                            else:
                                row.append(decoded[c][i])
                        w.writerow(row)
                        total += 1
        return total

    # -------------------------------------------------------------- SQL
    def session(self):
        """Open an interactive session (the psql-connection analog):
        supports BEGIN/COMMIT/ROLLBACK and savepoints.  Statements run
        through ``Cluster.execute`` directly use a shared default
        session, so ``cl.execute("BEGIN")`` works too."""
        from citus_tpu.transaction.session import Session
        return Session(self)

    def _default_session(self):
        """One implicit session PER THREAD (each thread of the
        session-less API is its own psql connection): a BEGIN issued on
        one thread must not pull other threads' autocommit statements
        into its transaction block, and concurrent statements keep
        distinct lock identities.  CPython reuses thread idents, so each
        entry remembers its owning Thread — a recycled ident rolls back
        the dead owner's abandoned transaction instead of inheriting it."""
        import threading as _th
        sessions = self._default_sessions
        me = _th.current_thread()
        tid = me.ident
        entry = sessions.get(tid)
        if entry is not None:
            owner, s = entry
            if owner is me:
                return s
            # ident recycled from a dead thread: its abandoned open
            # transaction rolls back (connection-close semantics)
            if s.txn is not None:
                self._rollback_txn(s)
        s = self.session()
        sessions[tid] = (me, s)
        return s

    def execute(self, sql: str, params: Optional[Sequence[Any]] = None,
                role: Optional[str] = None, session=None) -> Result:
        import time as _time
        if session is None:
            session = self._default_session()
        if session.txn is None:
            # inside a transaction the catalog object must stay stable
            # (statements hold references into it; PostgreSQL blocks
            # conflicting DDL with locks instead)
            self._maybe_reload_catalog()
        stmts = parse_sql(sql)
        if role is not None:
            for stmt in stmts:
                self._check_privileges(role, stmt)
        result = Result(columns=[], rows=[])
        gpid = self.activity.enter(sql)
        t0 = _time.perf_counter()
        # active role for statements synthesized mid-execution (the
        # upsert's internal UPDATE must see the same RLS policies);
        # per-thread: concurrent execute() calls must not see each
        # other's roles
        import threading as _threading
        # restore (not pop) on exit: a nested execute() — EXECUTE of a
        # prepared statement — must not clear the outer call's role,
        # or later synthesized statements would skip RLS
        _tid = _threading.get_ident()
        _prev_role = self._exec_roles.get(_tid)
        self._exec_roles[_tid] = role
        try:
            for stmt in stmts:
                if isinstance(stmt, A.TransactionStmt):
                    result = self._execute_transaction_stmt(session, stmt)
                    continue
                txn = session.txn
                if txn is not None and txn.failed:
                    from citus_tpu.transaction.session import (
                        InFailedTransaction,
                    )
                    raise InFailedTransaction(
                        "current transaction is aborted, commands "
                        "ignored until end of transaction block")
                if isinstance(stmt, (A.Prepare, A.ExecutePrepared,
                                     A.Deallocate)):
                    try:
                        result = self._execute_prepared_stmt(session, stmt,
                                                             role)
                    except Exception:
                        # PostgreSQL: any error aborts the block
                        if txn is not None:
                            txn.failed = True
                        raise
                    continue
                if txn is not None:
                    from citus_tpu.storage.overlay import transaction_overlay
                    try:
                        self._guard_in_txn(stmt)
                        with transaction_overlay(txn):
                            result = self._execute_in_session(
                                stmt, sql, stmts, params, role)
                            self._fire_triggers(stmt)
                    except Exception:
                        # PostgreSQL: any error aborts the transaction
                        # block until ROLLBACK [TO SAVEPOINT]
                        txn.failed = True
                        raise
                else:
                    result = self._execute_in_session(stmt, sql, stmts,
                                                      params, role)
                    self._fire_triggers(stmt)
        finally:
            if _prev_role is None:
                self._exec_roles.pop(_tid, None)
            else:
                self._exec_roles[_tid] = _prev_role
            self.activity.exit(gpid)
        # the nested execute() of an EXECUTE already recorded the
        # underlying statement — don't double-count the wrapper
        if not (len(stmts) == 1 and isinstance(stmts[0], A.ExecutePrepared)):
            executor = result.explain.get("strategy", "utility") if result.explain else "utility"
            elapsed = _time.perf_counter() - t0
            rkey = result.explain.get("router_key") if result.explain else None
            self.query_stats.record(sql, elapsed, result.rowcount, str(executor),
                                    partition_key="" if rkey is None else str(rkey))
            if rkey is not None:
                self.tenant_stats.record(str(rkey), elapsed)
        return result

    def _execute_in_session(self, stmt, sql, stmts, params, role) -> Result:
        """One statement through parameter substitution, RLS rewrite,
        and plan-cache keying (the pre-session body of execute())."""
        if params is not None:
            # parameterized plans: cached generic plan + deferred
            # pruning when the query shape supports it (reference:
            # Job->deferredPruning, fast_path_router_planner.c)
            # — superuser only: the cache keys on SQL text and an
            # RLS rewrite must never leak across roles
            if len(stmts) == 1 and isinstance(stmt, A.Select) \
                    and role is None:
                r = self._execute_param_select(sql, stmt, list(params))
                if r is not None:
                    return r
            from citus_tpu.planner.recursive import rewrite_params
            stmt = rewrite_params(stmt, list(params))
        rls_rewritten = False
        if role is not None:
            # after parameter substitution so WITH CHECK sees the
            # actual inserted values
            stmt, rls_rewritten = self._apply_rls(role, stmt)
        key = sql if (len(stmts) == 1 and params is None
                      and not rls_rewritten) else None
        return self._execute_stmt(stmt, sql_text=key)

    #: statement types allowed inside BEGIN..COMMIT.  DDL and cluster
    #: operations commit catalog changes immediately, so allowing them
    #: would break transaction atomicity — refuse instead (PostgreSQL
    #: allows transactional DDL; a documented divergence for now).
    _TXN_ALLOWED = None  # initialized lazily below

    def _guard_in_txn(self, stmt) -> None:
        if Cluster._TXN_ALLOWED is None:
            Cluster._TXN_ALLOWED = (
                A.Select, A.WithSelect, A.SetOp, A.Explain, A.Insert,
                A.Update, A.Delete,
                # transactional DDL: catalog mutations stage in memory
                # (Catalog.commit defers), physical file actions defer to
                # COMMIT / register rollback cleanups (reference: DDL in
                # transaction blocks via citus_ProcessUtility,
                # utility_hook.c:148)
                A.CreateTable, A.DropTable, A.CreateIndex, A.DropIndex,
                A.CreateSchema, A.CreateView, A.DropView, A.CreateSequence,
                A.DropSequence, A.CreateFunction, A.DropFunction,
                A.CreateType, A.DropType, A.CreateRole, A.DropRole,
                A.Grant, A.CreatePolicy, A.DropPolicy, A.CreateTrigger,
                A.DropTrigger, A.AlterTableRls, A.AlterTable,
                A.CreateExtension, A.DropExtension, A.CreateDomain,
                A.DropDomain, A.CreateCollation, A.DropCollation,
                A.CreatePublication, A.DropPublication,
                A.CreateStatistics, A.DropStatistics, A.Analyze,
                A.CreateTableAs, A.SetConfig, A.ShowConfig,
                A.UtilityCall)
        if not isinstance(stmt, Cluster._TXN_ALLOWED):
            raise UnsupportedFeatureError(
                f"{type(stmt).__name__} cannot run inside a transaction "
                "block")
        if isinstance(stmt, A.AlterTable) and stmt.action in (
                "rename_table", "rename_column"):
            # renames shard-data directories / dictionary and segment
            # files in place — not stageable
            raise UnsupportedFeatureError(
                "ALTER TABLE RENAME cannot run inside a transaction block")
        if isinstance(stmt, A.UtilityCall) and stmt.name not in (
                "create_distributed_table", "create_reference_table"):
            raise UnsupportedFeatureError(
                f"{stmt.name}() cannot run inside a transaction block")

    def _execute_prepared_stmt(self, session, stmt, role) -> Result:
        """PREPARE / EXECUTE / DEALLOCATE — the stored unit is SQL text,
        so EXECUTE rides the text-keyed generic-plan cache (one compile
        serves every invocation; reference: prepared statements with
        deferred pruning, fast_path_router_planner.c)."""
        if isinstance(stmt, A.Prepare):
            if stmt.name in session.prepared:
                raise CatalogError(
                    f'prepared statement "{stmt.name}" already exists')
            session.prepared[stmt.name] = stmt.sql
            return Result(columns=[], rows=[])
        if isinstance(stmt, A.Deallocate):
            if stmt.name is None:
                session.prepared.clear()
                return Result(columns=[], rows=[])
            if session.prepared.pop(stmt.name, None) is None:
                raise CatalogError(
                    f'prepared statement "{stmt.name}" does not exist')
            return Result(columns=[], rows=[])
        sql = session.prepared.get(stmt.name)
        if sql is None:
            raise CatalogError(
                f'prepared statement "{stmt.name}" does not exist')
        args = [_eval_const(a) for a in stmt.args]
        return self.execute(sql, params=args or None, role=role,
                            session=session)

    def _execute_transaction_stmt(self, session, stmt) -> Result:
        """BEGIN/COMMIT/ROLLBACK/SAVEPOINT state machine (reference:
        CoordinatedTransactionCallback, transaction_management.c:319;
        subtransaction callback :176)."""
        from citus_tpu.transaction.session import OpenTransaction
        kind = stmt.kind
        txn = session.txn
        if kind == "begin":
            if txn is not None:
                return Result(columns=[], rows=[],
                              explain={"warning": "there is already a "
                                       "transaction in progress"})
            xid = self.txlog.begin()
            session.txn = OpenTransaction(xid, session.lock_sid)
            # DDL rollback restores drop-tombstones along with the
            # in-memory document
            session.txn.tombstones_snapshot = {
                k: set(v) for k, v in self.catalog._tombstones.items()}
            return Result(columns=[], rows=[], explain={"transaction": "begin"})
        if kind == "commit":
            if txn is None:
                return Result(columns=[], rows=[],
                              explain={"warning": "there is no transaction "
                                       "in progress"})
            if txn.failed:
                # COMMIT of an aborted transaction rolls back
                self._rollback_txn(session)
                return Result(columns=[], rows=[],
                              explain={"transaction": "rollback"})
            self._commit_txn(session)
            return Result(columns=[], rows=[], explain={"transaction": "commit"})
        if kind == "rollback":
            if txn is None:
                return Result(columns=[], rows=[],
                              explain={"warning": "there is no transaction "
                                       "in progress"})
            self._rollback_txn(session)
            return Result(columns=[], rows=[], explain={"transaction": "rollback"})
        # savepoint family requires an open transaction (PostgreSQL
        # errors outside one)
        if txn is None:
            raise TransactionError(
                f"{kind.upper()} can only be used in transaction blocks")
        if kind == "savepoint":
            if txn.failed:
                from citus_tpu.transaction.session import InFailedTransaction
                raise InFailedTransaction(
                    "current transaction is aborted, commands ignored "
                    "until end of transaction block")
            txn.savepoints.append((stmt.name, txn.snapshot(self.catalog)))
            return Result(columns=[], rows=[])
        if kind == "rollback_to":
            for i in range(len(txn.savepoints) - 1, -1, -1):
                if txn.savepoints[i][0] == stmt.name:
                    txn.restore(txn.savepoints[i][1], self)
                    # the savepoint itself survives (PostgreSQL keeps it
                    # so you can roll back to it again); later ones die
                    del txn.savepoints[i + 1:]
                    self._plan_cache.clear()
                    return Result(columns=[], rows=[])
            txn.failed = True  # error in a txn block aborts it (25P02)
            raise TransactionError(f'savepoint "{stmt.name}" does not exist')
        if kind == "release":
            if txn.failed:
                from citus_tpu.transaction.session import InFailedTransaction
                raise InFailedTransaction(
                    "current transaction is aborted, commands ignored "
                    "until end of transaction block")
            for i in range(len(txn.savepoints) - 1, -1, -1):
                if txn.savepoints[i][0] == stmt.name:
                    del txn.savepoints[i:]
                    return Result(columns=[], rows=[])
            txn.failed = True  # error in a txn block aborts it (25P02)
            raise TransactionError(f'savepoint "{stmt.name}" does not exist')
        raise AnalysisError(f"unknown transaction statement {kind!r}")

    def _commit_txn(self, session) -> None:
        """PREPARED -> COMMITTED -> flip staged state -> DONE across
        every placement the transaction touched — the interactive-
        transaction generalization of the per-statement 2PC (reference:
        pre-commit PREPARE on all write connections,
        transaction_management.c:319)."""
        from citus_tpu.storage.deletes import commit_staged_deletes
        from citus_tpu.storage.writer import commit_staged
        from citus_tpu.transaction.manager import TxState

        txn = session.txn
        try:
            if not (txn.has_writes or txn.catalog_dirty or txn.on_commit):
                self.txlog.release(txn.xid)
                return
            try:
                # catalog (with version bumps + staged DDL) persisted
                # before the COMMITTED record: roll-forward must find
                # everything it references on disk (same ordering as
                # ingest.finish).  The overlay is inactive here, so this
                # commit persists and broadcasts for real — the single
                # DDL-lease application point of the transaction's DDL.
                for name in sorted(txn.tables):
                    if self.catalog.has_table(name):
                        self.catalog.table(name).version += 1
                # release the staging guard just before the persist: this
                # commit IS the transaction's DDL application point
                self.catalog._end_staging(txn)
                self.catalog.commit()
                if txn.has_writes:
                    payload = {"kind": "txn",
                               "placements": sorted(txn.delete_dirs),
                               "ingest_placements": sorted(txn.ingest_dirs),
                               "tables": sorted(txn.tables)}
                    self.txlog.log(txn.xid, TxState.PREPARED, payload)
                    self.txlog.log(txn.xid, TxState.COMMITTED, payload)
                    for d in sorted(txn.delete_dirs):
                        commit_staged_deletes(d, txn.xid)
                    for d in sorted(txn.ingest_dirs):
                        commit_staged(d, txn.xid)
                    self.txlog.log(txn.xid, TxState.DONE)
                else:
                    self.txlog.release(txn.xid)
                # deferred physical DDL effects (segment drops, table
                # file removal) — only after the catalog flip is durable
                for act in txn.on_commit:
                    act()
            except BaseException:
                # stop driving; recovery decides the outcome from the log
                self.txlog.release(txn.xid)
                raise
            self._plan_cache.clear()
            if txn.cdc_events:
                clock = self.clock.transaction_clock()
                for table, op, kw in txn.cdc_events:
                    # queued only for captured tables at statement time
                    self.cdc.emit(table, op, clock, force=True, **kw)
        finally:
            self.catalog._end_staging(txn)
            txn.release_locks(self)
            session.txn = None

    def _rollback_txn(self, session) -> None:
        from citus_tpu.storage.deletes import abort_staged_deletes
        from citus_tpu.storage.writer import abort_staged

        txn = session.txn
        try:
            for d in sorted(txn.ingest_dirs):
                abort_staged(d, txn.xid)
            for d in sorted(txn.delete_dirs):
                abort_staged_deletes(d, txn.xid)
            # physical artifacts staged by DDL (e.g. backfilled index
            # segments) — remove in reverse order of creation
            for act in reversed(txn.on_rollback):
                try:
                    act()
                except Exception:
                    pass  # best-effort: orphan files never affect reads
            if txn.catalog_dirty:
                # discard staged DDL: the on-disk document was never
                # touched, so reloading it restores the pre-BEGIN state
                self._reload_catalog()
                self.catalog._tombstones = {
                    k: set(v) for k, v in txn.tombstones_snapshot.items()}
            self.txlog.release(txn.xid)
            self._plan_cache.clear()
        finally:
            # only now may other sessions persist the (restored) catalog
            self.catalog._end_staging(txn)
            txn.release_locks(self)
            session.txn = None

    def _execute_param_select(self, sql: str, stmt: A.Select,
                              params: list) -> Optional[Result]:
        """Execute a parameterized SELECT through the generic-plan cache:
        bind once with $N slots, prune shards at bind-value time, reuse
        jitted kernels across values.  Returns None when the query shape
        needs the literal-substitution fallback."""
        from citus_tpu.planner.recursive import has_subquery
        if not isinstance(stmt.from_, A.TableRef):
            return None
        if self.catalog.has_table(stmt.from_.name) \
                and self.catalog.table(stmt.from_.name).is_partitioned:
            # partitioned parents need the expand_from rewrite, which
            # runs in _execute_stmt — fall back to literal substitution
            return None
        if stmt.distinct_on:
            return None  # DISTINCT ON dedups through _execute_distinct_on
        if any(isinstance(i.expr, A.WindowCall) for i in stmt.items):
            return None
        exprs = ([i.expr for i in stmt.items] + [stmt.where, stmt.having]
                 + stmt.group_by + [o.expr for o in stmt.order_by])
        if any(e is not None and has_subquery(e) for e in exprs):
            return None
        n_params = _max_param_index(stmt)
        if n_params > len(params):
            raise AnalysisError(
                f"query references ${n_params} but only "
                f"{len(params)} parameters were supplied")
        key = ("$param", sql)
        backend = self.settings.executor.task_executor_backend
        cached = self._plan_cache.get(key)
        if cached is not None:
            bound, plan, version, epoch, cbackend = cached
            if (epoch == self.catalog.ddl_epoch
                    and bound.table.version == version
                    and cbackend == backend):
                self.counters.bump("plan_cache_hits")
                return execute_select(self.catalog, bound, self.settings,
                                      plan=plan, param_values=params)
        try:
            bound = bind_select(self.catalog, stmt, param_count=n_params)
        except UnsupportedFeatureError:
            return None  # fall back to literal substitution
        from citus_tpu.planner.physical import plan_select
        plan = plan_select(self.catalog, bound,
                           direct_limit=self.settings.planner.direct_gid_limit)
        self._plan_cache[key] = (bound, plan, bound.table.version,
                                 self.catalog.ddl_epoch, backend)
        self.counters.bump("plan_cache_misses")
        return execute_select(self.catalog, bound, self.settings, plan=plan,
                              param_values=params)

    #: statement-recursion ceiling: subquery materialization, view
    #: expansion, and partition fan-out all re-enter _execute_stmt; a
    #: circular view reference (direct, via subqueries, or through
    #: another view) would otherwise die with a raw RecursionError
    _MAX_STMT_DEPTH = 64
    _stmt_depth = __import__("threading").local()

    def _execute_stmt(self, stmt: A.Statement, sql_text: Optional[str] = None) -> Result:
        depth = getattr(self._stmt_depth, "v", 0)
        if depth >= self._MAX_STMT_DEPTH:
            raise AnalysisError(
                "query nesting too deep (possible circular view "
                "reference)")
        self._stmt_depth.v = depth + 1
        try:
            return self._execute_stmt_inner(stmt, sql_text)
        finally:
            self._stmt_depth.v = depth

    def _execute_stmt_inner(self, stmt: A.Statement, sql_text: Optional[str] = None) -> Result:
        if isinstance(stmt, A.WithSelect):
            return self._execute_with(stmt)
        if isinstance(stmt, (A.Select, A.SetOp)) and self.catalog.functions:
            stmt = self._expand_functions_stmt(stmt)
        if isinstance(stmt, A.SetOp):
            return self._execute_setop(stmt)
        if isinstance(stmt, A.Select) and stmt.distinct_on:
            return self._execute_distinct_on(stmt)
        if isinstance(stmt, A.Select) and stmt.from_ is None:
            return self._execute_constant_select(stmt)
        if isinstance(stmt, A.Select) and stmt.from_ is not None:
            from citus_tpu.planner.recursive import (
                decorrelate_scalars, decorrelate_where,
            )
            stmt = decorrelate_scalars(stmt)
            stmt = decorrelate_where(stmt)
        if isinstance(stmt, A.Select) and stmt.from_ is not None \
                and self.catalog.views:
            new_from = self._expand_views(stmt.from_)
            if new_from is not stmt.from_:
                stmt = A.Select(stmt.items, new_from, stmt.where,
                                stmt.group_by, stmt.having, stmt.order_by,
                                stmt.limit, stmt.offset, stmt.distinct,
                                stmt.windows)
        if isinstance(stmt, A.Select) and stmt.from_ is not None and any(
                t.is_partitioned for t in self.catalog.tables.values()):
            # partitioned parents rewrite to their surviving partitions
            # (partition pruning stacks on shard + chunk pruning)
            from citus_tpu.partitioning import expand_from
            new_from = expand_from(self, stmt.from_, stmt.where)
            if new_from is not stmt.from_:
                import dataclasses as _dc
                stmt = _dc.replace(stmt, from_=new_from)
        if isinstance(stmt, A.Select) and stmt.from_ is not None \
                and _has_derived(stmt.from_):
            return self._execute_derived(stmt)
        if isinstance(stmt, A.Select) and len(stmt.group_by) == 1 \
                and isinstance(stmt.group_by[0], A.GroupingSetsSpec):
            return self._execute_grouping_sets(stmt, stmt.group_by[0].sets)
        if isinstance(stmt, A.Select) and any(
                isinstance(i.expr, A.WindowCall) for i in stmt.items):
            return self._execute_window(stmt)
        if isinstance(stmt, A.Select):
            # recursive planning: materialize subqueries first
            from citus_tpu.planner.recursive import rewrite_subqueries
            new_stmt = rewrite_subqueries(
                stmt, lambda sub: self._execute_stmt(sub))
            if new_stmt is not stmt:
                return self._execute_stmt(new_stmt)  # plans are not cached
        if isinstance(stmt, A.Delete) and stmt.where is not None:
            from citus_tpu.planner.recursive import has_subquery, rewrite_subqueries
            if has_subquery(stmt.where):
                wrapped = A.Select([A.SelectItem(A.Literal(1, "int"))],
                                   from_=None, where=stmt.where)
                rew = rewrite_subqueries(wrapped, lambda sub: self._execute_stmt(sub))
                stmt = A.Delete(stmt.table, rew.where)
        if isinstance(stmt, A.Update):
            from citus_tpu.planner.recursive import has_subquery, rewrite_subqueries
            exprs = [e for _, e in stmt.assignments] +                 ([stmt.where] if stmt.where is not None else [])
            if any(has_subquery(e) for e in exprs):
                items = [A.SelectItem(e) for _, e in stmt.assignments]
                wrapped = A.Select(items or [A.SelectItem(A.Literal(1, "int"))],
                                   from_=None, where=stmt.where)
                rew = rewrite_subqueries(wrapped, lambda sub: self._execute_stmt(sub))
                new_assignments = [(c, it.expr) for (c, _), it in
                                   zip(stmt.assignments, rew.items)]                     if stmt.assignments else []
                stmt = A.Update(stmt.table, new_assignments, rew.where)
        if isinstance(stmt, A.Select) and isinstance(stmt.from_, A.Join):
            from citus_tpu.executor.join_executor import execute_join_select
            from citus_tpu.planner.join_planner import bind_join_select
            bj = bind_join_select(self.catalog, stmt)
            return execute_join_select(self.catalog, bj, self.settings)
        if isinstance(stmt, A.Select):
            cached = self._plan_cache.get(sql_text) if sql_text else None
            if cached is not None:
                bound, plan, version, epoch, backend = cached
                if (epoch == self.catalog.ddl_epoch
                        and bound.table.version == version
                        and backend == self.settings.executor.task_executor_backend):
                    return execute_select(self.catalog, bound, self.settings, plan=plan)
            bound = bind_select(self.catalog, stmt)
            from citus_tpu.planner.physical import plan_select
            plan = plan_select(self.catalog, bound,
                               direct_limit=self.settings.planner.direct_gid_limit)
            if sql_text:
                self._plan_cache[sql_text] = (
                    bound, plan, bound.table.version, self.catalog.ddl_epoch,
                    self.settings.executor.task_executor_backend)
            return execute_select(self.catalog, bound, self.settings, plan=plan)
        if isinstance(stmt, A.CreateSchema):
            if stmt.if_not_exists and stmt.name in self.catalog.schemas:
                return Result(columns=[], rows=[])
            self.catalog.create_schema(stmt.name)
            self.catalog.commit()
            return Result(columns=[], rows=[])
        if isinstance(stmt, A.DropSchema):
            members = self.catalog.drop_schema(stmt.name, cascade=stmt.cascade)
            for m in members:
                self.catalog.drop_table(m)
            self.catalog.commit()
            self._plan_cache.clear()
            return Result(columns=[], rows=[])
        if isinstance(stmt, A.CreateType):
            if stmt.name in self.catalog.types:
                raise CatalogError(f'type "{stmt.name}" already exists')
            if not stmt.labels or len(set(stmt.labels)) != len(stmt.labels):
                raise AnalysisError("enum labels must be unique and non-empty")
            self.catalog.types[stmt.name] = list(stmt.labels)
            self.catalog.ddl_epoch += 1
            self.catalog.commit()
            return Result(columns=[], rows=[])
        if isinstance(stmt, A.DropType):
            if stmt.if_exists and stmt.name not in self.catalog.types:
                return Result(columns=[], rows=[])
            if stmt.name not in self.catalog.types:
                raise CatalogError(f'type "{stmt.name}" does not exist')
            users = [k for k, v in self.catalog.enum_columns.items()
                     if v == stmt.name]
            if users:
                raise CatalogError(
                    f'cannot drop type "{stmt.name}": used by {users[0]}')
            del self.catalog.types[stmt.name]
            self.catalog.tombstone("types", stmt.name)
            self.catalog.ddl_epoch += 1
            self.catalog.commit()
            return Result(columns=[], rows=[])
        if isinstance(stmt, A.CreateFunction):
            from citus_tpu.planner.aggregates import AGG_REGISTRY
            from citus_tpu.planner.bind import AGG_FUNCS
            if stmt.name in AGG_FUNCS or stmt.name in AGG_REGISTRY:
                raise CatalogError(
                    f'cannot replace built-in function "{stmt.name}"')
            if stmt.name in self.catalog.functions and not stmt.or_replace:
                raise CatalogError(f'function "{stmt.name}" already exists')
            if stmt.returns != "trigger" and any(
                    t.get("function") == stmt.name
                    for t in self.catalog.triggers.values()):
                raise CatalogError(
                    f'cannot replace "{stmt.name}": trigger(s) depend on it '
                    "remaining a trigger function")
            # expression macros validate as expressions; trigger
            # functions (RETURNS trigger) hold a SQL statement body
            entry = {"args": list(stmt.arg_names),
                     "arg_types": list(stmt.arg_types),
                     "returns": stmt.returns, "body": stmt.body}
            if stmt.returns == "trigger":
                parse_sql(stmt.body)
                entry["kind"] = "statement"
            else:
                from citus_tpu.planner.parser import Parser as _P
                _P(stmt.body).parse_expr()
            self.catalog.functions[stmt.name] = entry
            self.catalog.ddl_epoch += 1
            self.catalog.commit()
            self._plan_cache.clear()
            return Result(columns=[], rows=[])
        if isinstance(stmt, A.DropFunction):
            if stmt.if_exists and stmt.name not in self.catalog.functions:
                return Result(columns=[], rows=[])
            if stmt.name not in self.catalog.functions:
                raise CatalogError(f'function "{stmt.name}" does not exist')
            users = [n for n, t in self.catalog.triggers.items()
                     if t.get("function") == stmt.name]
            if users:
                raise CatalogError(
                    f'cannot drop function "{stmt.name}": trigger(s) '
                    f'{", ".join(sorted(users))} depend on it')
            del self.catalog.functions[stmt.name]
            self.catalog.tombstone("functions", stmt.name)
            self.catalog.ddl_epoch += 1
            self.catalog.commit()
            self._plan_cache.clear()
            return Result(columns=[], rows=[])
        if isinstance(stmt, A.CreateRole):
            if stmt.if_not_exists and stmt.name in self.catalog.roles:
                return Result(columns=[], rows=[])
            self.catalog.create_role(stmt.name)
            self.catalog.commit()
            return Result(columns=[], rows=[])
        if isinstance(stmt, A.DropRole):
            if stmt.if_exists and stmt.name not in self.catalog.roles:
                return Result(columns=[], rows=[])
            self.catalog.drop_role(stmt.name)
            self.catalog.commit()
            return Result(columns=[], rows=[])
        if isinstance(stmt, A.Grant):
            if stmt.revoke:
                self.catalog.revoke(stmt.table, stmt.role, stmt.privileges)
            else:
                self.catalog.grant(stmt.table, stmt.role, stmt.privileges)
            self.catalog.commit()
            return Result(columns=[], rows=[])
        if isinstance(stmt, A.CreatePolicy):
            self.catalog.table(stmt.table)  # must exist
            pols = self.catalog.policies.setdefault(stmt.table, [])
            if any(p["name"] == stmt.name for p in pols):
                raise CatalogError(
                    f'policy "{stmt.name}" for table "{stmt.table}" '
                    "already exists")
            from citus_tpu.planner.parser import Parser as _P
            for text in (stmt.using_sql, stmt.check_sql):
                if text is not None:
                    _P(text).parse_expr()  # validate
            pols.append({"name": stmt.name, "cmd": stmt.cmd,
                         "roles": list(stmt.roles),
                         "using": stmt.using_sql, "check": stmt.check_sql})
            self.catalog.commit()
            return Result(columns=[], rows=[])
        if isinstance(stmt, A.DropPolicy):
            pols = self.catalog.policies.get(stmt.table, [])
            kept = [p for p in pols if p["name"] != stmt.name]
            if len(kept) == len(pols):
                if stmt.if_exists:
                    return Result(columns=[], rows=[])
                raise CatalogError(
                    f'policy "{stmt.name}" for table "{stmt.table}" '
                    "does not exist")
            if kept:
                self.catalog.policies[stmt.table] = kept
            else:
                del self.catalog.policies[stmt.table]
            # per-policy tombstone: the commit-time merge is per policy
            self.catalog.tombstone("policies", f"{stmt.table}.{stmt.name}")
            self.catalog.commit()
            return Result(columns=[], rows=[])
        if isinstance(stmt, A.AlterTableRls):
            self.catalog.table(stmt.table)
            if stmt.enable:
                self.catalog.rls[stmt.table] = True
            elif self.catalog.rls.pop(stmt.table, None) is not None:
                self.catalog.tombstone("rls", stmt.table)
            self.catalog.commit()
            return Result(columns=[], rows=[])
        if isinstance(stmt, A.CreateTrigger):
            self.catalog.table(stmt.table)
            if stmt.name in self.catalog.triggers:
                raise CatalogError(f'trigger "{stmt.name}" already exists')
            fn = self.catalog.functions.get(stmt.function)
            if fn is None or fn.get("kind") != "statement":
                raise CatalogError(
                    f'"{stmt.function}" is not a trigger function '
                    "(CREATE FUNCTION ... RETURNS trigger)")
            self.catalog.triggers[stmt.name] = {
                "table": stmt.table, "event": stmt.event,
                "function": stmt.function}
            self.catalog.commit()
            return Result(columns=[], rows=[])
        if isinstance(stmt, A.DropTrigger):
            t = self.catalog.triggers.get(stmt.name)
            if t is None or t.get("table") != stmt.table:
                if stmt.if_exists:
                    return Result(columns=[], rows=[])
                raise CatalogError(
                    f'trigger "{stmt.name}" on "{stmt.table}" does not exist')
            del self.catalog.triggers[stmt.name]
            self.catalog.tombstone("triggers", stmt.name)
            self.catalog.commit()
            return Result(columns=[], rows=[])
        if isinstance(stmt, A.CreateTsConfig):
            if stmt.name in self.catalog.ts_configs:
                raise CatalogError(
                    f'text search configuration "{stmt.name}" already exists')
            src = stmt.options.get("copy")
            if src is not None and src not in self.catalog.ts_configs \
                    and src != "simple":
                raise CatalogError(
                    f'text search configuration "{src}" does not exist')
            base = (dict(self.catalog.ts_configs.get(src, {}))
                    if src is not None else {})
            base["parser"] = stmt.options.get("parser",
                                              base.get("parser", "default"))
            self.catalog.ts_configs[stmt.name] = base
            self.catalog.commit()
            return Result(columns=[], rows=[])
        if isinstance(stmt, A.DropTsConfig):
            if stmt.name not in self.catalog.ts_configs:
                if stmt.if_exists:
                    return Result(columns=[], rows=[])
                raise CatalogError(
                    f'text search configuration "{stmt.name}" does not exist')
            del self.catalog.ts_configs[stmt.name]
            self.catalog.tombstone("ts_configs", stmt.name)
            self.catalog.commit()
            return Result(columns=[], rows=[])
        if isinstance(stmt, A.CreateView):
            # validate the body against current metadata (LIMIT 0 run)
            import dataclasses
            probe = dataclasses.replace(stmt.select, limit=0) \
                if isinstance(stmt.select, A.Select) else stmt.select
            replacing = stmt.or_replace and stmt.name in self.catalog.views
            if replacing:
                if stmt.name in _from_relations(stmt.select):
                    raise AnalysisError(
                        f'view "{stmt.name}" cannot reference itself')
            new_r = self._execute_stmt(probe)
            if replacing:
                # PostgreSQL: a replace may only ADD columns at the end,
                # keeping existing names AND types
                from citus_tpu.planner.parser import parse_statement
                old_sel = parse_statement(self.catalog.views[stmt.name])
                old_r = self._execute_stmt(_limit0(old_sel))
                old_cols = old_r.columns
                if new_r.columns[:len(old_cols)] != old_cols:
                    raise AnalysisError(
                        "cannot drop, rename, or reorder columns of "
                        f'view "{stmt.name}" with CREATE OR REPLACE')
                if old_r.types and new_r.types:
                    for i, (ot, nt) in enumerate(zip(old_r.types,
                                                     new_r.types)):
                        if ot is not None and nt is not None \
                                and ot.kind != nt.kind:
                            raise AnalysisError(
                                "cannot change data type of view column "
                                f'"{old_cols[i]}"')
            self.catalog.create_view(stmt.name, stmt.sql,
                                     or_replace=stmt.or_replace)
            self.catalog.commit()
            self._plan_cache.clear()
            return Result(columns=[], rows=[])
        if isinstance(stmt, A.DropView):
            if stmt.if_exists and stmt.name not in self.catalog.views:
                return Result(columns=[], rows=[])
            self.catalog.drop_view(stmt.name)
            self.catalog.commit()
            self._plan_cache.clear()
            return Result(columns=[], rows=[])
        if isinstance(stmt, A.CreateSequence):
            if stmt.if_not_exists and stmt.name in self.catalog.sequences:
                return Result(columns=[], rows=[])
            self.catalog.create_sequence(stmt.name, stmt.start, stmt.increment)
            self.catalog.commit()
            return Result(columns=[], rows=[])
        if isinstance(stmt, A.DropSequence):
            if stmt.if_exists and stmt.name not in self.catalog.sequences:
                return Result(columns=[], rows=[])
            self.catalog.drop_sequence(stmt.name)
            self.catalog.commit()
            return Result(columns=[], rows=[])
        if isinstance(stmt, A.CreateTableAs):
            if self.catalog.has_table(stmt.name):
                if stmt.if_not_exists:
                    return Result(columns=[], rows=[])
                raise CatalogError(
                    f'relation "{stmt.name}" already exists')
            r = self._execute_stmt(stmt.select)
            names, types = self._schema_from_result(r, strict_empty=True)
            # atomic create+load: a load failure must not leave an empty
            # committed table behind (transparent inside a user txn)
            with self._internal_txn():
                self.create_table(stmt.name,
                                  Schema([Column(cn, ct_)
                                          for cn, ct_ in zip(names, types)]))
                if r.rows:
                    self.copy_from(stmt.name, rows=r.rows,
                                   column_names=names)
            return Result(columns=[], rows=[],
                          explain={"selected": len(r.rows)})
        if isinstance(stmt, A.CreateTable) and stmt.partition_of is not None:
            self._create_partition(
                stmt.name, stmt.partition_of["parent"],
                stmt.partition_of["lo"], stmt.partition_of["hi"],
                if_not_exists=stmt.if_not_exists)
            return Result(columns=[], rows=[])
        if isinstance(stmt, A.CreateTable):
            from citus_tpu import types as T
            cols, enum_binds = [], []
            domain_binds = []
            for c in stmt.columns:
                if c.type_name in self.catalog.types:
                    cols.append(Column(c.name, T.TEXT_T, c.not_null))
                    enum_binds.append((c.name, c.type_name))
                elif c.type_name in self.catalog.domains:
                    d = self.catalog.domains[c.type_name]
                    cols.append(Column(
                        c.name,
                        type_from_sql(d["base"], d["args"] or None),
                        c.not_null or d["not_null"]))
                    domain_binds.append((c.name, c.type_name))
                else:
                    cols.append(Column(
                        c.name, type_from_sql(c.type_name, c.type_args or None),
                        c.not_null))
            schema = Schema(cols)
            opts = {k: v for k, v in stmt.options.items() if k != "access_method"}
            fks = []
            pre_existing = self.catalog.has_table(stmt.name)
            # pre-validate implicit PK/UNIQUE indexes and the partition
            # clause BEFORE the table commits: PostgreSQL's CREATE TABLE
            # is all-or-nothing
            want_indexes = []
            if not pre_existing:
                seen_ix: set = set()
                for c in stmt.columns:
                    if not (c.primary_key or c.unique):
                        continue
                    iname = (f"{stmt.name}_pkey" if c.primary_key
                             else f"{stmt.name}_{c.name}_key")
                    if iname in seen_ix or self._find_index(iname)[1] is not None:
                        raise CatalogError(f'index "{iname}" already exists')
                    seen_ix.add(iname)
                    if schema.column(c.name).type.is_float:
                        raise UnsupportedFeatureError(
                            "UNIQUE indexes over floating-point columns "
                            "are not supported (no exact equality)")
                    want_indexes.append((iname, c.name))
                if stmt.partition_by is not None:
                    schema.column(stmt.partition_by)  # must exist
                    # PostgreSQL: a unique constraint on a partitioned
                    # table must include the partition column
                    for _, cname in want_indexes:
                        if cname != stmt.partition_by:
                            raise UnsupportedFeatureError(
                                "unique constraint on partitioned table "
                                "must include the partition column")
            if stmt.foreign_keys and not pre_existing:
                from citus_tpu.integrity import declare_fks
                fks = declare_fks(self.catalog, stmt.name,
                                  stmt.foreign_keys, schema=schema)
            self.create_table(stmt.name, schema, if_not_exists=stmt.if_not_exists, **opts)
            if fks and not pre_existing and self.catalog.has_table(stmt.name):
                # IF NOT EXISTS no-op must not clobber existing constraints
                self.catalog.table(stmt.name).foreign_keys = fks
                self.catalog.commit()
            if enum_binds and self.catalog.has_table(stmt.name):
                for cn, tn in enum_binds:
                    self.catalog.enum_columns[f"{stmt.name}.{cn}"] = tn
                self.catalog.commit()
            if domain_binds and not pre_existing \
                    and self.catalog.has_table(stmt.name):
                for cn, dn in domain_binds:
                    self.catalog.domain_columns[f"{stmt.name}.{cn}"] = dn
                self.catalog.commit()
            if want_indexes and self.catalog.has_table(stmt.name):
                # PRIMARY KEY / UNIQUE column constraints become unique
                # indexes (PostgreSQL's implicit btree; pg_index rows) —
                # pre-validated above, so these cannot fail halfway
                for iname, cname in want_indexes:
                    self.create_index(iname, stmt.name, cname, unique=True)
            if stmt.partition_by is not None \
                    and not pre_existing and self.catalog.has_table(stmt.name):
                # validated before create_table above
                t0 = self.catalog.table(stmt.name)
                t0.partition_by = {"column": stmt.partition_by,
                                   "kind": "range"}
                self.catalog.commit()
            return Result(columns=[], rows=[])
        if isinstance(stmt, A.DropTable):
            self.drop_table(stmt.name, if_exists=stmt.if_exists)
            return Result(columns=[], rows=[])
        if isinstance(stmt, A.CreateIndex):
            return self._execute_create_index(stmt)
        if isinstance(stmt, A.DropIndex):
            return self._execute_drop_index(stmt)
        if isinstance(stmt, A.CreateExtension):
            if stmt.name in self.catalog.extensions:
                if stmt.if_not_exists:
                    return Result(columns=[], rows=[])
                raise CatalogError(f'extension "{stmt.name}" already exists')
            self.catalog.extensions[stmt.name] = {
                "version": stmt.version or "1.0"}
            self.catalog.ddl_epoch += 1
            self.catalog.commit()
            return Result(columns=[], rows=[])
        if isinstance(stmt, A.DropExtension):
            return self._drop_catalog_object("extensions", stmt)
        if isinstance(stmt, A.CreateDomain):
            if stmt.name in self.catalog.domains:
                raise CatalogError(f'domain "{stmt.name}" already exists')
            type_from_sql(stmt.base, stmt.type_args or None)  # must resolve
            if stmt.check_sql is not None:
                from citus_tpu.planner.parser import Parser as _P
                _P(stmt.check_sql).parse_expr()  # validate
            self.catalog.domains[stmt.name] = {
                "base": stmt.base, "args": list(stmt.type_args or []),
                "not_null": stmt.not_null, "check": stmt.check_sql}
            self.catalog.ddl_epoch += 1
            self.catalog.commit()
            return Result(columns=[], rows=[])
        if isinstance(stmt, A.DropDomain):
            users = [k for k, v in self.catalog.domain_columns.items()
                     if v == stmt.name]
            if users and stmt.name in self.catalog.domains:
                raise CatalogError(
                    f'cannot drop domain "{stmt.name}": column {users[0]} '
                    "depends on it")
            return self._drop_catalog_object("domains", stmt)
        if isinstance(stmt, A.CreateCollation):
            if stmt.name in self.catalog.collations:
                raise CatalogError(f'collation "{stmt.name}" already exists')
            self.catalog.collations[stmt.name] = dict(stmt.options)
            self.catalog.ddl_epoch += 1
            self.catalog.commit()
            return Result(columns=[], rows=[])
        if isinstance(stmt, A.DropCollation):
            return self._drop_catalog_object("collations", stmt)
        if isinstance(stmt, A.CreatePublication):
            if stmt.name in self.catalog.publications:
                raise CatalogError(
                    f'publication "{stmt.name}" already exists')
            if isinstance(stmt.tables, list):
                for tn in stmt.tables:
                    self.catalog.table(tn)  # must exist
            self.catalog.publications[stmt.name] = {
                "tables": stmt.tables}
            self.catalog.ddl_epoch += 1
            self.catalog.commit()
            return Result(columns=[], rows=[])
        if isinstance(stmt, A.DropPublication):
            return self._drop_catalog_object("publications", stmt)
        if isinstance(stmt, A.CreateStatistics):
            if stmt.name in self.catalog.statistics:
                raise CatalogError(
                    f'statistics object "{stmt.name}" already exists')
            t = self.catalog.table(stmt.table)
            for c in stmt.columns:
                t.schema.column(c)
            # extended statistics: n-distinct over the column combination
            # (reference: CREATE STATISTICS ndistinct; computed eagerly —
            # our ANALYZE analog)
            nd = self._compute_ndistinct(stmt.table, list(stmt.columns))
            self.catalog.statistics[stmt.name] = {
                "table": stmt.table, "columns": list(stmt.columns),
                "ndistinct": nd}
            self.catalog.ddl_epoch += 1
            self.catalog.commit()
            return Result(columns=[], rows=[])
        if isinstance(stmt, A.DropStatistics):
            return self._drop_catalog_object("statistics", stmt)
        if isinstance(stmt, A.Insert):
            return self._execute_insert(stmt)
        if isinstance(stmt, A.CopyTo):
            n = self.copy_to_csv(
                stmt.table, stmt.path,
                delimiter=stmt.options.get("delimiter", ","),
                header=_option_bool(stmt.options.get("header", "false")),
                null_string=stmt.options.get("null", ""))
            return Result(columns=[], rows=[], explain={"copied": n})
        if isinstance(stmt, A.CopyQueryTo):
            r = self._execute_stmt(stmt.select)
            nulls = stmt.options.get("null", "")
            with open(stmt.path, "w", newline="") as fh:
                w = self._open_csv_writer(
                    fh, r.columns,
                    delimiter=stmt.options.get("delimiter", ","),
                    header=_option_bool(stmt.options.get("header", "false")))
                for row in r.rows:
                    w.writerow([nulls if v is None else v for v in row])
            return Result(columns=[], rows=[], explain={"copied": len(r.rows)})
        if isinstance(stmt, A.CopyFrom):
            n = self.copy_from_csv(
                stmt.table, stmt.path,
                delimiter=stmt.options.get("delimiter", ","),
                header=_option_bool(stmt.options.get("header", "false")),
                null_string=stmt.options.get("null", ""))
            return Result(columns=[], rows=[], explain={"copied": n})
        if isinstance(stmt, A.Delete):
            from citus_tpu.executor.dml import execute_delete
            from citus_tpu.planner.bind import Binder
            t = self.catalog.table(stmt.table)
            if t.is_partitioned:
                return self._partition_dml(stmt, t)
            where = Binder(self.catalog, t).bind_scalar(stmt.where) \
                if stmt.where is not None else None
            from citus_tpu.transaction.locks import EXCLUSIVE
            with self._write_lock(t, EXCLUSIVE):
                if self.catalog.referencing_fks(stmt.table):
                    # RESTRICT / CASCADE / SET NULL on referencing tables
                    # before the parent rows disappear
                    from citus_tpu.integrity import on_parent_delete
                    on_parent_delete(self, stmt.table, stmt.where)
                # RETURNING reads the pre-image under the same lock so
                # the rows returned are exactly the rows deleted
                ret = self._returning_result(stmt.table, stmt.where,
                                             stmt.returning) \
                    if stmt.returning else None
                t = self.catalog.table(stmt.table)  # re-fetch: fresh placements
                from citus_tpu.storage.overlay import current_overlay
                n = execute_delete(self.catalog, self.txlog, t, where,
                                   txn=current_overlay())
            self._plan_cache.clear()
            if self._cdc_captures(t.name) and n:
                self._emit_cdc(t.name, "delete", count=n)
            if ret is not None:
                ret.explain["deleted"] = n
                return ret
            return Result(columns=[], rows=[], explain={"deleted": n})
        if isinstance(stmt, A.Update):
            from citus_tpu.executor.dml import execute_update
            from citus_tpu.planner.bind import Binder
            t = self.catalog.table(stmt.table)
            if t.is_partitioned:
                return self._partition_dml(stmt, t)
            b = Binder(self.catalog, t)
            assignments = []
            for col, e in stmt.assignments:
                target = t.schema.column(col)
                bound = b.bind_scalar(e)
                from citus_tpu.planner.bound import BCast, BLiteral
                if target.type.is_text:
                    if isinstance(bound, BLiteral) and isinstance(bound.value, str):
                        did = self.catalog.encode_strings(t.name, col, [bound.value])[0]
                        bound = BLiteral(int(did), target.type)
                    elif not bound.type.is_text:
                        raise AnalysisError(
                            f"cannot assign {bound.type} to {col} ({target.type})")
                elif bound.type.is_text:
                    raise AnalysisError(
                        f"cannot assign text to {col} ({target.type})")
                elif bound.type != target.type:
                    bound = BCast(bound, target.type)
                assignments.append((col, bound))
            where = b.bind_scalar(stmt.where) if stmt.where is not None else None
            from citus_tpu.transaction.locks import EXCLUSIVE
            with self._write_lock(t, EXCLUSIVE):
                assigned_cols = {c for c, _e in stmt.assignments}
                if self.catalog.referencing_fks(stmt.table):
                    from citus_tpu.integrity import on_parent_update
                    on_parent_update(self, stmt.table, assigned_cols,
                                     stmt.where, stmt.assignments)
                if t.foreign_keys:
                    from citus_tpu.integrity import check_child_update
                    check_child_update(self, t, stmt.assignments)
                ret = None
                if stmt.returning:
                    # new values = assignments substituted into the items,
                    # evaluated over the pre-image under the same lock
                    subst = {}
                    for col, e in stmt.assignments:
                        subst[A.ColumnRef(col)] = e
                        subst[A.ColumnRef(col, stmt.table)] = e
                    ret = self._returning_result(stmt.table, stmt.where,
                                                 stmt.returning, subst)
                t = self.catalog.table(stmt.table)  # re-fetch: fresh placements
                from citus_tpu.storage.overlay import current_overlay
                assigned = {c for c, _e in stmt.assignments}
                checks = []
                if any(c in assigned
                       for c, _dn, _d in self._domain_columns_of(t)):
                    checks.append(
                        lambda v, m: self._check_domains_physical(t, v, m))
                if t.partition_of is not None:
                    from citus_tpu.partitioning import check_partition_bounds
                    checks.append(
                        lambda v, m: check_partition_bounds(
                            self.catalog, t, v, m))
                check = None
                if checks:
                    check = lambda v, m: [c(v, m) for c in checks]  # noqa: E731
                n = execute_update(self.catalog, self.txlog, t, assignments,
                                   where, txn=current_overlay(), check=check)
            self._plan_cache.clear()
            if self._cdc_captures(t.name) and n:
                self._emit_cdc(t.name, "update", count=n)
            if ret is not None:
                ret.explain["updated"] = n
                return ret
            return Result(columns=[], rows=[], explain={"updated": n})
        if isinstance(stmt, A.AlterTable):
            if self.catalog.has_table(stmt.table) \
                    and self.catalog.table(stmt.table).is_partitioned:
                if stmt.action in ("rename_table", "rename_column"):
                    raise UnsupportedFeatureError(
                        "renaming a partitioned parent (or its columns) "
                        "is not supported")
                if stmt.action == "drop_column" \
                        and stmt.old_name == self.catalog.table(
                            stmt.table).partition_by["column"]:
                    raise CatalogError("cannot drop the partition column")
                # PostgreSQL: schema changes on the parent cascade to
                # every partition
                import dataclasses as _dc
                for p in self.catalog.partitions_of(stmt.table):
                    self._execute_stmt(_dc.replace(stmt, table=p.name))
            if stmt.action == "add_column":
                from citus_tpu import types as T
                tn = stmt.column.type_name
                if tn in self.catalog.types:  # enum
                    col = Column(stmt.column.name, T.TEXT_T,
                                 stmt.column.not_null)
                    self.catalog.add_column(stmt.table, col)
                    self.catalog.enum_columns[
                        f"{stmt.table}.{stmt.column.name}"] = tn
                elif tn in self.catalog.domains:
                    d = self.catalog.domains[tn]
                    col = Column(stmt.column.name,
                                 type_from_sql(d["base"], d["args"] or None),
                                 stmt.column.not_null or d["not_null"])
                    self.catalog.add_column(stmt.table, col)
                    self.catalog.domain_columns[
                        f"{stmt.table}.{stmt.column.name}"] = tn
                else:
                    col = Column(stmt.column.name,
                                 type_from_sql(tn, stmt.column.type_args or None),
                                 stmt.column.not_null)
                    self.catalog.add_column(stmt.table, col)
            elif stmt.action == "drop_column":
                t0 = self.catalog.table(stmt.table)
                if t0.index_on(stmt.old_name) is not None:
                    from citus_tpu.storage.overlay import current_overlay
                    txn0 = current_overlay()
                    if txn0 is not None:
                        # irreversible file removal: defer to COMMIT
                        col0 = stmt.old_name
                        tname0 = t0.name
                        txn0.on_commit.append(
                            lambda: self._drop_index_segments_if_unindexed(
                                tname0, col0))
                    else:
                        self._drop_index_segments(t0, stmt.old_name)
                    t0.indexes[:] = [ix for ix in t0.indexes
                                     if ix["column"] != stmt.old_name]
                # PostgreSQL drops the table's own FK constraints that
                # include the column; a referenced parent column needs
                # CASCADE (unsupported here), so fail closed instead of
                # leaving a stale constraint behind.
                for child, fk in self.catalog.referencing_fks(stmt.table):
                    if child == stmt.table:
                        continue  # self-FK belongs to this table: dropped
                    if stmt.old_name in fk["ref_columns"]:
                        raise AnalysisError(
                            f'cannot drop column "{stmt.old_name}" of '
                            f'table "{stmt.table}" because foreign key '
                            f'constraint "{fk["name"]}" on table '
                            f'"{child}" depends on it')
                t = self.catalog.table(stmt.table)
                t.foreign_keys[:] = [
                    fk for fk in t.foreign_keys
                    if stmt.old_name not in fk["columns"]
                    and not (fk["ref_table"] == stmt.table
                             and stmt.old_name in fk["ref_columns"])]
                key = f"{stmt.table}.{stmt.old_name}"
                if self.catalog.domain_columns.pop(key, None) is not None:
                    self.catalog.tombstone("domain_columns", key)
                if self.catalog.enum_columns.pop(key, None) is not None:
                    self.catalog.tombstone("enum_columns", key)
                # PostgreSQL auto-drops extended statistics with a column
                for sname in [n for n, st in self.catalog.statistics.items()
                              if st["table"] == stmt.table
                              and stmt.old_name in st["columns"]]:
                    del self.catalog.statistics[sname]
                    self.catalog.tombstone("statistics", sname)
                self.catalog.drop_column(stmt.table, stmt.old_name)
            elif stmt.action == "rename_column":
                t0 = self.catalog.table(stmt.table)
                if t0.index_on(stmt.old_name) is not None:
                    # segments are keyed by logical column name on disk:
                    # rename them with the column
                    import os as _os
                    suffix = f".idx.{stmt.old_name}.npz"
                    for shard in t0.shards:
                        for node in shard.placements:
                            d = self.catalog.shard_dir(
                                t0.name, shard.shard_id, node)
                            if not _os.path.isdir(d):
                                continue
                            for f in _os.listdir(d):
                                if f.endswith(suffix):
                                    base = f[:-len(suffix)]
                                    _os.replace(
                                        _os.path.join(d, f),
                                        _os.path.join(
                                            d, base + f".idx.{stmt.new_name}.npz"))
                    for ix in t0.indexes:
                        if ix["column"] == stmt.old_name:
                            ix["column"] = stmt.new_name
                self.catalog.rename_column(stmt.table, stmt.old_name, stmt.new_name)
                # keep FK metadata consistent: this table's own key
                # columns and every child's referenced-column names
                for fk in self.catalog.table(stmt.table).foreign_keys:
                    fk["columns"] = [stmt.new_name if c == stmt.old_name
                                     else c for c in fk["columns"]]
                for _child, fk in self.catalog.referencing_fks(stmt.table):
                    fk["ref_columns"] = [stmt.new_name if c == stmt.old_name
                                         else c for c in fk["ref_columns"]]
            elif stmt.action == "rename_table":
                from citus_tpu.transaction.locks import EXCLUSIVE
                t = self.catalog.table(stmt.table)
                with self._write_lock(t, EXCLUSIVE):
                    self.catalog.rename_table(stmt.table, stmt.new_name)
                # repoint children's FK edges at the new name
                for other in self.catalog.tables.values():
                    for fk in other.foreign_keys:
                        if fk["ref_table"] == stmt.table:
                            fk["ref_table"] = stmt.new_name
            else:
                raise UnsupportedFeatureError(f"ALTER TABLE {stmt.action} not supported")
            self.catalog.commit()
            self._plan_cache.clear()
            return Result(columns=[], rows=[])
        if isinstance(stmt, A.Merge):
            from citus_tpu.executor.merge_executor import execute_merge
            from citus_tpu.transaction.locks import EXCLUSIVE
            _mt = self.catalog.table(stmt.target.name)
            if _mt.foreign_keys or self.catalog.referencing_fks(_mt.name):
                # the merge executor writes through the storage layer
                # directly; fail closed rather than bypass FK enforcement
                raise UnsupportedFeatureError(
                    "MERGE on tables with foreign key constraints is not "
                    "supported")
            # unique indexes are enforced inside execute_merge (pre-commit
            # delete-aware probe); FK targets stay refused above
            with self._write_lock(self.catalog.table(stmt.target.name), EXCLUSIVE):
                st = execute_merge(
                    self.catalog, self.txlog, stmt,
                    encode_value=lambda tbl, col, v:
                        int(self.catalog.encode_strings(tbl, col, [v])[0]))
            self._plan_cache.clear()
            if self._cdc_captures(stmt.target.name):
                self.cdc.emit(stmt.target.name, "merge",
                              self.clock.transaction_clock(), force=True,
                              count=sum(st.values()))
            return Result(columns=[], rows=[], explain=st)
        if isinstance(stmt, A.Truncate):
            from citus_tpu.integrity import forbid_truncate_referenced
            # validate EVERY relation up front (existence + FK rule with
            # list-awareness: a referenced parent is fine when all its
            # children are in the same list, like PostgreSQL): truncation
            # deletes files irreversibly, so a bad later name must not
            # leave earlier tables already emptied
            names = (stmt.table,) + tuple(stmt.more)
            expanded = []
            for name in names:
                t0 = self.catalog.table(name)
                expanded.append(name)
                if t0.is_partitioned:
                    expanded += [p.name
                                 for p in self.catalog.partitions_of(name)]
            for name in expanded:
                forbid_truncate_referenced(self.catalog, name,
                                           also_truncated=set(expanded))
            # acquire every relation's EXCLUSIVE lock (sorted, to dodge
            # lock-order inversions) BEFORE the first irreversible flip:
            # PostgreSQL's TRUNCATE a, b is all-or-nothing, so a later
            # table's lock timeout must fail the statement while no
            # table has been emptied yet
            import contextlib as _ctxlib
            from citus_tpu.transaction.locks import EXCLUSIVE
            from citus_tpu.transaction.write_locks import group_resource
            metas = {}
            for name in expanded:
                t0 = self.catalog.table(name)
                if not t0.is_partitioned:
                    metas.setdefault(group_resource(t0), t0)
            with _ctxlib.ExitStack() as stack:
                for res in sorted(metas):
                    stack.enter_context(
                        self._write_lock(metas[res], EXCLUSIVE))
                for name in names:
                    self._truncate_one(name)
            return Result(columns=[], rows=[])
        if isinstance(stmt, A.Vacuum):
            from citus_tpu.executor.dml import execute_vacuum
            from citus_tpu.transaction.locks import EXCLUSIVE
            t = self.catalog.table(stmt.table)
            if t.is_partitioned:
                # the parent holds no data: vacuum every partition
                return self._fanout_partitions(stmt, aggregate_explain=True)
            with self._write_lock(t, EXCLUSIVE):
                st = execute_vacuum(self.catalog, self.catalog.table(stmt.table))
            self._plan_cache.clear()
            return Result(columns=[], rows=[], explain=st)
        if isinstance(stmt, A.SetConfig):
            return self._execute_set(stmt)
        if isinstance(stmt, A.ShowConfig):
            return self._execute_show(stmt)
        if isinstance(stmt, A.Analyze):
            return self._execute_analyze(stmt.table)
        if isinstance(stmt, A.VacuumAnalyze):
            self._execute_stmt(A.Vacuum(stmt.table, stmt.full))
            return self._execute_analyze(stmt.table)
        if isinstance(stmt, A.Reindex):
            return self._execute_reindex(stmt)
        if isinstance(stmt, A.UtilityCall):
            return self._execute_utility(stmt)
        if isinstance(stmt, A.Explain):
            return self._execute_explain(stmt)
        raise UnsupportedFeatureError(f"cannot execute {type(stmt).__name__}")

    def _compute_ndistinct(self, table: str, columns: list) -> int:
        """count(DISTINCT (cols)) — the extended-statistics ndistinct."""
        sel = A.Select(
            [A.SelectItem(A.FuncCall("count", (A.Star(),)))],
            A.SubqueryRef(A.Select(
                [A.SelectItem(A.ColumnRef(c)) for c in columns],
                A.TableRef(table), distinct=True), "d"))
        return int(self._execute_stmt(sel).rows[0][0])

    #: SET/SHOW surface: GUC name -> (settings section, field, coercion)
    #: (reference: the citus.* GUCs, shared_library_init.c:980+).
    #: Settings apply to this Cluster handle (every session of it).
    _GUCS = {
        "citus.task_executor_backend": ("executor", "task_executor_backend", str),
        "citus.max_shared_pool_size": ("executor", "max_shared_pool_size", int),
        "citus.max_adaptive_executor_pool_size": ("executor", "max_tasks_in_flight", int),
        "citus.use_secondary_nodes": ("executor", "use_secondary_nodes", "secondary"),
        "citus.use_pallas_scan": ("executor", "use_pallas_scan", "bool"),
        "citus.enable_repartition_joins": ("planner", "enable_repartition_joins", "bool"),
        "citus.shard_count": ("sharding", "shard_count", int),
        "citus.shard_replication_factor": ("sharding", "shard_replication_factor", int),
        "citus.enable_change_data_capture": (None, "enable_change_data_capture", "bool"),
        "citus.distributed_deadlock_detection_interval": (None, "deadlock_detection_interval_s", float),
        # PostgreSQL spelling: bare numbers are MILLISECONDS; unit
        # suffixes ('3s', '500ms') accepted
        "lock_timeout": ("executor", "lock_timeout_s", "ms_duration"),
    }

    def _guc_key(self, name: str) -> str:
        name = name.lower()
        if name in self._GUCS:
            return name
        if f"citus.{name}" in self._GUCS:
            return f"citus.{name}"
        raise CatalogError(f'unrecognized configuration parameter "{name}"')

    def _execute_set(self, stmt: A.SetConfig) -> Result:
        import dataclasses as _dc
        key = self._guc_key(stmt.name)
        section, field_, coerce = self._GUCS[key]
        v = stmt.value
        if coerce == "bool":
            if not isinstance(v, bool):
                s = str(v).lower()
                if s in ("true", "on", "1", "yes"):
                    v = True
                elif s in ("false", "off", "0", "no"):
                    v = False
                else:
                    raise CatalogError(
                        f'parameter "{stmt.name}" requires a Boolean '
                        f"value (got {stmt.value!r})")
        elif coerce == "secondary":
            # PostgreSQL spelling: citus.use_secondary_nodes = always|never
            if isinstance(v, bool):
                pass
            elif str(v).lower() in ("always", "never"):
                v = str(v).lower() == "always"
            else:
                raise CatalogError(
                    f'invalid value for parameter "{stmt.name}": '
                    f"{stmt.value!r} (expected always or never)")
        elif coerce == "ms_duration":
            # bare numbers are milliseconds (PostgreSQL); 's'/'ms'
            # suffixes accepted
            s = str(v).strip().lower()
            try:
                if s.endswith("ms"):
                    v = float(s[:-2]) / 1000.0
                elif s.endswith("s"):
                    v = float(s[:-1])
                else:
                    v = float(s) / 1000.0
            except ValueError:
                raise CatalogError(
                    f'invalid value for parameter "{stmt.name}": '
                    f"{stmt.value!r}")
        else:
            try:
                v = coerce(v)
            except (TypeError, ValueError):
                raise CatalogError(
                    f'invalid value for parameter "{stmt.name}": {stmt.value!r}')
        from citus_tpu.storage.overlay import current_overlay
        txn = current_overlay()
        if txn is not None:
            # PostgreSQL: a non-LOCAL SET is undone if the transaction
            # aborts
            prev_settings, prev_cdc = self.settings, self.cdc.enabled

            def _restore(prev_settings=prev_settings, prev_cdc=prev_cdc):
                self.settings = prev_settings
                self.cdc.enabled = prev_cdc
                self._plan_cache.clear()
            txn.on_rollback.append(_restore)
        if section is None:
            self.settings = _dc.replace(self.settings, **{field_: v})
        else:
            sec = _dc.replace(getattr(self.settings, section), **{field_: v})
            self.settings = _dc.replace(self.settings, **{section: sec})
        if key == "citus.enable_change_data_capture":
            self.cdc.enabled = bool(v)
        self._plan_cache.clear()  # backend/knob changes invalidate plans
        return Result(columns=[], rows=[])

    def _guc_value(self, key: str) -> str:
        section, field_, coerce = self._GUCS[key]
        v = getattr(self.settings, field_) if section is None \
            else getattr(getattr(self.settings, section), field_)
        if coerce == "secondary":
            return "always" if v else "never"
        if isinstance(v, bool):
            return "on" if v else "off"  # PostgreSQL boolean rendering
        if coerce == "ms_duration":
            return f"{v * 1000:g}ms"
        return str(v)

    def _execute_show(self, stmt: A.ShowConfig) -> Result:
        if stmt.name == "all":
            rows = [(k, self._guc_value(k)) for k in sorted(self._GUCS)]
            return Result(columns=["name", "setting"], rows=rows)
        key = self._guc_key(stmt.name)
        return Result(columns=[stmt.name], rows=[(self._guc_value(key),)])

    def _execute_analyze(self, table: Optional[str]) -> Result:
        """ANALYZE [table]: recompute extended-statistics ndistinct
        (column min/max stats are always skip-list-live here, so there
        is no per-column histogram pass to run)."""
        if table is not None:
            self.catalog.table(table)  # PostgreSQL: unknown relation errors
        refreshed = 0
        for name, st in self.catalog.statistics.items():
            if table is not None and st["table"] != table:
                continue
            if not self.catalog.has_table(st["table"]):
                continue
            st["ndistinct"] = self._compute_ndistinct(st["table"],
                                                      st["columns"])
            refreshed += 1
        if refreshed:
            self.catalog.commit()
        return Result(columns=[], rows=[],
                      explain={"statistics_refreshed": refreshed})

    def _execute_reindex(self, stmt: A.Reindex) -> Result:
        """REINDEX INDEX name | REINDEX TABLE name: rebuild segment
        files from the stripe data (recovers from lost/corrupted
        segments; a missing segment is only a slow path, never wrong)."""
        from citus_tpu.storage.index import backfill_index
        from citus_tpu.transaction.locks import EXCLUSIVE
        if stmt.kind == "index":
            t, ix = self._find_index(stmt.name)
            if ix is None:
                raise CatalogError(f'index "{stmt.name}" does not exist')
            targets = [(t, [ix["column"]])]
        else:
            t = self.catalog.table(stmt.name)
            if t.is_partitioned:
                targets = [(p, p.index_columns)
                           for p in self.catalog.partitions_of(t.name)
                           if p.indexes]
            else:
                targets = [(t, t.index_columns)] if t.indexes else []
        rebuilt = 0
        for tt, cols in targets:
            with self._write_lock(tt, EXCLUSIVE):
                for col in cols:
                    self._drop_index_segments(tt, col)
                rebuilt += backfill_index(self.catalog, tt, list(cols))
                tt.version += 1
        if targets:
            self.catalog.ddl_epoch += 1
            self.catalog.commit()
            self._plan_cache.clear()
        return Result(columns=[], rows=[],
                      explain={"segments_rebuilt": rebuilt})

    def _returning_result(self, table_name, where, items, subst=None):
        """Evaluate a RETURNING clause as a distributed SELECT over the
        affected rows (pre-image WHERE); for UPDATE, assignment
        expressions are substituted into the items so the NEW values are
        returned (reference: adaptive_executor.c DML RETURNING tuples)."""
        t = self.catalog.table(table_name)
        expanded = _expand_returning_items(t, items, subst)
        # constant items (e.g. SET c = 'z' substituted into RETURNING c)
        # cannot ride the distributed select: fold them on the host and
        # splice one copy per affected row
        consts, sel_items = {}, []
        for idx, (e, alias) in enumerate(expanded):
            try:
                consts[idx] = _eval_const(e)
            except Exception:
                sel_items.append((idx, A.SelectItem(e, alias)))
        if sel_items:
            inner = self._execute_stmt(A.Select(
                [si for _, si in sel_items], A.TableRef(table_name), where))
            nrows, inner_rows = len(inner.rows), inner.rows
        else:
            cnt = A.Select([A.SelectItem(A.FuncCall("count", (A.Star(),)))],
                           A.TableRef(table_name), where)
            nrows = int(self._execute_stmt(cnt).rows[0][0] or 0)
            inner_rows = [()] * nrows
        rows = []
        for r in inner_rows:
            full, j = [None] * len(expanded), 0
            for idx in range(len(expanded)):
                if idx in consts:
                    full[idx] = consts[idx]
                else:
                    full[idx] = r[j]
                    j += 1
            rows.append(tuple(full))
        return Result(columns=[a for _, a in expanded], rows=rows)

    def _execute_insert(self, stmt: A.Insert) -> Result:
        t = self.catalog.table(stmt.table)
        if stmt.select is not None:
            if stmt.on_conflict is not None:
                raise UnsupportedFeatureError(
                    "ON CONFLICT with INSERT..SELECT is not supported")
            if stmt.returning:
                raise UnsupportedFeatureError(
                    "RETURNING on INSERT..SELECT is not supported")
            names = stmt.columns or t.schema.names
            # FK-constrained, unique-indexed, and partitioned targets —
            # and partitioned sources — take the pull path: copy_from's
            # probes and partition routing only run there, and a
            # partitioned source must expand through _execute_stmt
            def _refs_partitioned(item) -> bool:
                if isinstance(item, A.Join):
                    return _refs_partitioned(item.left) \
                        or _refs_partitioned(item.right)
                return (isinstance(item, A.TableRef)
                        and self.catalog.has_table(item.name)
                        and self.catalog.table(item.name).is_partitioned)
            direct_ok = not (t.foreign_keys or t.unique_indexes
                             or t.is_partitioned
                             or self._domain_columns_of(t))
            if direct_ok and isinstance(stmt.select, A.Select) \
                    and stmt.select.from_ is not None:
                direct_ok = not _refs_partitioned(stmt.select.from_)
            res = None if not direct_ok \
                else self._insert_select_arrays(t, stmt.select, list(names))
            if res is None:
                # general path: materialize rows through the coordinator
                # (reference: the pull-to-coordinator INSERT..SELECT
                # strategy, insert_select_executor.c)
                inner = self._execute_stmt(stmt.select)
                n = self.copy_from(stmt.table, rows=inner.rows,
                                   column_names=list(names))
                strategy = "pull"
            else:
                n, strategy = res
            return Result(columns=[], rows=[],
                          explain={"inserted": n,
                                   "strategy": f"insert_select:{strategy}"})
        rows = []
        for row_exprs in stmt.rows:
            row = []
            for e in row_exprs:
                if not isinstance(e, A.Literal):
                    if isinstance(e, A.UnOp) and e.op == "-" and isinstance(e.operand, A.Literal):
                        row.append(-e.operand.value)
                        continue
                    if isinstance(e, A.FuncCall) and e.name in ("nextval", "currval") \
                            and e.args and isinstance(e.args[0], A.Literal):
                        seq = str(e.args[0].value)
                        row.append(self.catalog.nextval(seq) if e.name == "nextval"
                                   else self.catalog.currval(seq))
                        continue
                    raise UnsupportedFeatureError("INSERT VALUES must be literals")
                row.append(e.value)
            rows.append(row)
        if stmt.on_conflict is not None:
            return self._execute_upsert(t, stmt, rows)
        n = self.copy_from(stmt.table, rows=rows, column_names=stmt.columns)
        if stmt.returning:
            names = list(stmt.columns or t.schema.names)
            out_rows = []
            for row in rows:
                m = {}
                for cn, v in zip(names, row):
                    typ = t.schema.column(cn).type
                    if v is not None and not typ.is_text:
                        # what a subsequent SELECT would read back
                        v = typ.from_physical(typ.to_physical(v))
                    lit = A.Literal(v, "null" if v is None else
                                    "string" if isinstance(v, str) else "int")
                    m[A.ColumnRef(cn)] = lit
                    m[A.ColumnRef(cn, stmt.table)] = lit
                for cn in t.schema.names:
                    m.setdefault(A.ColumnRef(cn), A.Literal(None, "null"))
                    m.setdefault(A.ColumnRef(cn, stmt.table),
                                 A.Literal(None, "null"))
                exp = _expand_returning_items(t, stmt.returning, m)
                out_rows.append(tuple(_eval_const(e) for e, _ in exp))
            cols = [a for _, a in _expand_returning_items(t, stmt.returning)]
            return Result(columns=cols, rows=out_rows,
                          explain={"inserted": n})
        return Result(columns=[], rows=[], explain={"inserted": n})

    def _execute_upsert(self, t, stmt: A.Insert, rows: list) -> Result:
        """INSERT ... ON CONFLICT: the conflict target is the declared
        key (the reference requires it to include the distribution
        column so conflicts resolve within one shard group —
        multi_router_planner.c rejects others).  Runs under the
        colocation group's EXCLUSIVE write lock so check+write is atomic
        against concurrent writers and shard moves."""
        oc = stmt.on_conflict
        if stmt.returning:
            raise UnsupportedFeatureError(
                "RETURNING with ON CONFLICT is not supported")
        if not oc.targets:
            raise UnsupportedFeatureError(
                "ON CONFLICT requires an explicit (column, ...) target")
        names = list(stmt.columns or t.schema.names)
        for c in oc.targets:
            if not t.schema.has(c):
                raise AnalysisError(f"column {c!r} does not exist")
            if c not in names:
                raise AnalysisError(
                    "ON CONFLICT target columns must be inserted columns")
        if t.is_distributed and t.dist_column not in oc.targets:
            raise UnsupportedFeatureError(
                "ON CONFLICT target must include the distribution column")
        for c, _e in oc.assignments:
            if not t.schema.has(c):
                raise AnalysisError(f"column {c!r} does not exist")
            if t.is_distributed and c == t.dist_column:
                raise UnsupportedFeatureError(
                    "ON CONFLICT DO UPDATE cannot modify the distribution "
                    "column")
        key_idx = [names.index(c) for c in oc.targets]

        def norm_key(vals) -> tuple:
            """Canonicalize proposed key values to what a SELECT reads
            back (physical round-trip), so they compare equal to probed
            rows: 5.0 -> Decimal('5.00'), '2020-01-01' -> date."""
            out = []
            for c, v in zip(oc.targets, vals):
                typ = t.schema.column(c).type
                if v is None or typ.is_text:
                    out.append(v)
                else:
                    out.append(typ.from_physical(typ.to_physical(v)))
            return tuple(out)

        if oc.action == "update":
            # PostgreSQL raises error 21000 whenever two proposed rows
            # would affect the same target row; checking up front keeps
            # the statement all-or-nothing (no partially applied updates)
            dup_check: set = set()
            for row in rows:
                raw = tuple(row[i] for i in key_idx)
                if any(v is None for v in raw):
                    continue
                key = norm_key(raw)
                if key in dup_check:
                    raise ExecutionError(
                        "ON CONFLICT DO UPDATE command cannot affect row "
                        "a second time")
                dup_check.add(key)
        inserted = updated = skipped = 0
        from citus_tpu.transaction.locks import EXCLUSIVE
        with self._write_lock(t, EXCLUSIVE):
            # one batched probe instead of a per-row count(*) under the
            # lock: fetch the conflict-target columns of candidate rows
            # (pruned by the distribution-column IN-list) into a set
            probe_rows = [row for row in rows
                          if not any(row[i] is None for i in key_idx)]
            existing: set = set()
            if probe_rows:
                where = None
                if t.is_distributed and t.dist_column in names:
                    di = names.index(t.dist_column)
                    dvals = sorted({row[di] for row in probe_rows})
                    where = A.InList(A.ColumnRef(t.dist_column),
                                     tuple(_pylit(v) for v in dvals), False)
                chk = A.Select([A.SelectItem(A.ColumnRef(c))
                                for c in oc.targets],
                               A.TableRef(t.name), where)
                existing = {tuple(r) for r in self._execute_stmt(chk).rows}
            to_insert: list = []
            affected: set = set()  # keys inserted/updated by this command
            for row in rows:
                raw = tuple(row[i] for i in key_idx)
                if any(v is None for v in raw):
                    # NULL never equals NULL: no conflict possible
                    to_insert.append(row)
                    inserted += 1
                    continue
                key = norm_key(raw)
                if key in affected:
                    # only reachable for DO NOTHING (DO UPDATE duplicate
                    # keys were rejected before any mutation)
                    skipped += 1
                    continue
                if key not in existing:
                    affected.add(key)
                    to_insert.append(row)
                    inserted += 1
                    continue
                if oc.action == "nothing":
                    skipped += 1
                    continue
                affected.add(key)
                cond = None
                for c, v in zip(oc.targets, raw):
                    eq = A.BinOp("=", A.ColumnRef(c), _pylit(v))
                    cond = eq if cond is None else A.BinOp("and", cond, eq)
                excl = {c: _pylit(v) for c, v in zip(names, row)}
                assignments = [(c, _subst_excluded(e, excl))
                               for c, e in oc.assignments]
                where = cond
                if oc.where is not None:
                    where = A.BinOp("and", cond,
                                    _subst_excluded(oc.where, excl))
                upd: A.Statement = A.Update(t.name, assignments, where)
                import threading as _threading
                exec_role = self._exec_roles.get(_threading.get_ident())
                if exec_role is not None:
                    # the conflicting row must pass the role's UPDATE
                    # policies regardless of the conflict WHERE clause
                    # (PostgreSQL raises the RLS violation whenever the
                    # existing row fails USING)
                    pol = self._policy_predicate(exec_role, t.name,
                                                 "update")
                    if pol is not None:
                        vis = A.Select(
                            [A.SelectItem(A.FuncCall("count", (A.Star(),)))],
                            A.TableRef(t.name), A.BinOp("and", cond, pol))
                        if not self._execute_stmt(vis).rows[0][0]:
                            raise AnalysisError(
                                f'new row violates row-level security '
                                f'policy for table "{t.name}"')
                    upd, _ = self._apply_rls(exec_role, upd)
                r = self._execute_stmt(upd)
                n_upd = r.explain.get("updated", 0)
                updated += n_upd
                skipped += 0 if n_upd else 1  # DO UPDATE ... WHERE filtered
            if to_insert:
                self.copy_from(t.name, rows=to_insert,
                               column_names=stmt.columns)
        if oc.action == "update":
            # PostgreSQL fires statement-level UPDATE triggers whenever
            # DO UPDATE is specified (INSERT triggers fire at execute())
            self._fire_triggers_for(t.name, "update", 0)
        return Result(columns=[], rows=[],
                      explain={"inserted": inserted, "updated": updated,
                               "skipped": skipped, "strategy": "upsert"})

    def _insert_select_arrays(self, target, sel: A.Select,
                              names: list[str]) -> Optional[int]:
        """Array-streaming INSERT..SELECT (the repartition strategy,
        reference: insert_select_planner.c IsRedistributablePlan): when
        the SELECT is a plain single-table projection whose output types
        match the target physically, move numpy columns straight from
        the scan into the hash-routing ingest — no Python row
        materialization.  Returns None when ineligible."""
        if not isinstance(sel, A.Select) or not isinstance(sel.from_, A.TableRef):
            return None
        if sel.group_by or sel.having or sel.order_by or sel.limit or sel.distinct:
            return None
        try:
            bound = bind_select(self.catalog, sel)
        except Exception:
            return None
        if bound.has_aggs or len(bound.final_exprs) != len(names):
            return None
        from citus_tpu.planner.bound import (
            BColumn, BDictRemap, compile_expr, predicate_mask,
        )
        from citus_tpu.planner.physical import plan_select
        final_exprs = list(bound.final_exprs)
        for i, (e, cname) in enumerate(zip(final_exprs, names)):
            tgt = target.schema.column(cname).type
            if e.type != tgt:
                return None
            if tgt.is_text:
                if not isinstance(e, BColumn):
                    return None
                if bound.table.name != target.name or e.name != cname:
                    # re-encode source dictionary ids into the target's
                    # dictionary space (grows the target dictionary)
                    src_words = self.catalog.dictionary(bound.table.name, e.name)
                    mapping = tuple(int(x) for x in self.catalog.encode_strings(
                        target.name, cname, src_words))
                    final_exprs[i] = BDictRemap(e, mapping)
        plan = plan_select(self.catalog, bound,
                           direct_limit=self.settings.planner.direct_gid_limit)
        from citus_tpu.transaction.locks import SHARED
        fns = [compile_expr(e, np) for e in final_exprs]
        ffn = compile_expr(bound.filter, np) if bound.filter is not None else None
        strategy = self._insert_select_strategy(target, bound, final_exprs, names)
        with self._write_lock(target, SHARED):
            n = self._run_insert_select_arrays(
                target, bound, plan, fns, ffn, names, strategy)
        return n, strategy

    def _insert_select_strategy(self, target, bound, final_exprs, names) -> str:
        """The reference's INSERT..SELECT strategy ladder
        (insert_select_planner.c, README:1187-1238): *colocated pushdown*
        when source and target share a colocation group and the target's
        distribution column is fed directly by the source's distribution
        column (rows already live on the right shard — no re-hash, no
        routing); else *repartition* (array-streaming re-hash through the
        hash-routing ingest).  The caller falls back to *pull* (row
        materialization) when the arrays path is ineligible entirely."""
        from citus_tpu.planner.bound import BColumn
        src = bound.table
        if not (src.is_distributed and target.is_distributed):
            return "repartition"
        if src.colocation_id != target.colocation_id:
            return "repartition"
        if target.dist_column is None or target.dist_column not in names:
            return "repartition"
        i = names.index(target.dist_column)
        e = final_exprs[i]
        # plain column (no dict remap / cast) referencing the source's
        # distribution column: hash(source row) == hash(target row)
        if isinstance(e, BColumn) and e.name == src.dist_column:
            return "colocated"
        return "repartition"

    def _run_insert_select_arrays(self, target, bound, plan, fns, ffn,
                                  names, strategy) -> int:
        from citus_tpu.storage.overlay import current_overlay
        txn = current_overlay()
        if txn is not None:
            # inside BEGIN..COMMIT: stage under the transaction's xid.
            # On failure, register staged dirs (never abort the xid —
            # that would destroy earlier statements' staged rows)
            ing = TableIngestor(self.catalog, target, txlog=None)
            ing.xid = txn.xid
            try:
                total = self._stream_insert_select(ing, target, bound, plan,
                                                   fns, ffn, names, strategy)
                for w in ing._writers.values():
                    w.flush()
            finally:
                txn.record_ingest(
                    target.name,
                    [w.directory for w in ing._writers.values()])
            self.counters.bump("rows_ingested", total)
            return total
        ing = TableIngestor(self.catalog, target, txlog=self.txlog)
        try:
            total = self._stream_insert_select(ing, target, bound, plan,
                                               fns, ffn, names, strategy)
        except BaseException:
            ing.abort()  # failure during scan/append: staged files dropped
            raise
        # finish() manages its own failure path (releases the xid so
        # recovery decides; aborting here could roll back a logged COMMIT)
        ing.finish()
        self.counters.bump("rows_ingested", total)
        return total

    def _stream_insert_select(self, ing, target, bound, plan, fns, ffn,
                              names, strategy) -> int:
        from citus_tpu.executor.batches import load_shard_batches
        from citus_tpu.planner.bound import predicate_mask
        total = 0
        for si in plan.shard_indexes:
            for values, masks, n in load_shard_batches(
                    self.catalog, plan, si, min_batch_rows=1):
                env = {c: (values[c].astype(
                            bound.table.schema.column(c).type.device_dtype, copy=False),
                           masks[c]) for c in plan.scan_columns}
                if ffn is not None:
                    m = np.asarray(predicate_mask(np, ffn, env, np.ones(n, bool)))
                    if m.shape == ():
                        m = np.full(n, bool(m))
                else:
                    m = np.ones(n, bool)
                idx = np.nonzero(m)[0]
                if idx.size == 0:
                    continue
                out_v, out_m = {}, {}
                for fn, cname in zip(fns, names):
                    v, valid = fn(env)
                    v = np.asarray(v)
                    if v.ndim == 0:
                        v = np.broadcast_to(v, (n,))
                    if valid is True:
                        valid = np.ones(n, bool)
                    elif valid is False:
                        valid = np.zeros(n, bool)
                    st = target.schema.column(cname).type.storage_dtype
                    out_v[cname] = v[idx].astype(st)
                    out_m[cname] = np.asarray(valid)[idx]
                for cname in target.schema.names:
                    if cname not in out_v:
                        out_v[cname] = np.zeros(idx.size, target.schema.column(cname).type.storage_dtype)
                        out_m[cname] = np.zeros(idx.size, bool)
                if strategy == "colocated":
                    # pushdown: rows of source shard si belong to target
                    # shard si by construction — write straight to its
                    # placements, skipping hash + scatter entirely
                    shard = target.shards[si]
                    for node in shard.placements:
                        ing._writer(shard.shard_id, node).append_batch(out_v, out_m)
                else:
                    ing.append(out_v, out_m)
                total += idx.size
        return total

    @staticmethod
    def _resolve_window_ref(wc: A.WindowCall, windows: dict,
                            _seen: Optional[set] = None) -> A.WindowCall:
        """Resolve OVER w / OVER (w ...) against the WINDOW clause,
        following PostgreSQL's copy rules: the referencing spec may not
        re-partition, may order only when the base does not, and always
        uses its own frame (the base may not define one when copied);
        OVER w uses the named window verbatim, frame included."""
        if wc.ref_name is None:
            return wc
        if _seen is None:
            _seen = set()
        if wc.ref_name in _seen:
            raise AnalysisError(
                f'circular reference in window "{wc.ref_name}"')
        _seen.add(wc.ref_name)
        base = windows.get(wc.ref_name)
        if base is None:
            raise AnalysisError(f'window "{wc.ref_name}" does not exist')
        if base.ref_name is not None:
            base = Cluster._resolve_window_ref(base, windows, _seen)
        if wc.ref_verbatim:
            return A.WindowCall(wc.func, base.partition_by, base.order_by,
                                base.frame)
        if wc.partition_by:
            raise AnalysisError(
                "cannot override PARTITION BY of a named window")
        if wc.order_by and base.order_by:
            raise AnalysisError(
                "cannot override ORDER BY of a named window that has one")
        if base.frame is not None:
            raise AnalysisError(
                "cannot copy a named window that has a frame clause")
        return A.WindowCall(wc.func, base.partition_by,
                            wc.order_by or base.order_by, wc.frame)

    def _execute_distinct_on(self, stmt: A.Select) -> Result:
        """SELECT DISTINCT ON (exprs): keep the first row of each key
        group in ORDER BY order (PostgreSQL semantics — planned as
        Unique over Sort).  The key expressions run as trailing hidden
        outputs of the inner query; deduplication happens on the
        coordinator, then LIMIT/OFFSET apply to the deduplicated rows."""
        import dataclasses as _dc
        on = list(stmt.distinct_on)

        def resolve(e):
            # ordinals and output aliases resolve to their select item
            if isinstance(e, A.Literal) and isinstance(e.value, int) \
                    and not isinstance(e.value, bool):
                idx = e.value - 1
                if 0 <= idx < len(stmt.items):
                    return stmt.items[idx].expr
            if isinstance(e, A.ColumnRef) and e.table is None:
                for it in stmt.items:
                    if it.alias == e.name:
                        return it.expr
            return e

        for i, e in enumerate(on):
            if i < len(stmt.order_by) \
                    and resolve(stmt.order_by[i].expr) != resolve(e):
                raise AnalysisError(
                    "SELECT DISTINCT ON expressions must match initial "
                    "ORDER BY expressions")
        order_by = list(stmt.order_by) \
            or [A.OrderItem(e, True, None) for e in on]
        hidden = [A.SelectItem(resolve(e), f"__distinct_on_{i}")
                  for i, e in enumerate(on)]
        inner = _dc.replace(stmt, items=list(stmt.items) + hidden,
                            order_by=order_by, limit=None, offset=None,
                            distinct_on=())
        r = self._execute_stmt(inner)
        k = len(on)
        seen, rows = set(), []
        for row in r.rows:
            key = row[-k:]
            if key in seen:
                continue
            seen.add(key)
            rows.append(row[:-k])
        if stmt.offset:
            rows = rows[stmt.offset:]
        if stmt.limit is not None:
            rows = rows[:stmt.limit]
        return Result(columns=r.columns[:-k], rows=rows,
                      explain={**(r.explain or {}),
                               "strategy": "distinct_on"},
                      types=r.types[:-k] if r.types else r.types)

    def _execute_window(self, stmt: A.Select) -> Result:
        """Window functions: run the base projection (or grouped
        aggregation) distributed, apply the window pass on the
        coordinator (pull strategy)."""
        import dataclasses

        from citus_tpu.executor.window import AGGS, NAVIGATION, compute_window
        if stmt.distinct:
            raise UnsupportedFeatureError(
                "window functions with DISTINCT not supported yet")
        if stmt.windows or any(isinstance(i.expr, A.WindowCall)
                               and i.expr.ref_name is not None
                               for i in stmt.items):
            import dataclasses
            wmap = dict(stmt.windows)
            stmt = dataclasses.replace(stmt, items=[
                A.SelectItem(self._resolve_window_ref(i.expr, wmap)
                             if isinstance(i.expr, A.WindowCall) else i.expr,
                             i.alias)
                for i in stmt.items])
        base_items: list[A.SelectItem] = []

        def base_slot(e: A.Expr) -> int:
            base_items.append(A.SelectItem(e, f"__w{len(base_items)}"))
            return len(base_items) - 1

        def literal_value(a: A.Expr):
            if isinstance(a, A.Literal):
                return a.value
            if isinstance(a, A.UnOp) and a.op == "-" \
                    and isinstance(a.operand, A.Literal):
                return -a.operand.value
            raise UnsupportedFeatureError(
                "window function extra arguments must be literals")

        outputs = []  # ("col", slot) | ("win", fn, arg_slots, part, order, frame, params)
        names = []
        for i, item in enumerate(stmt.items):
            e = item.expr
            if isinstance(e, A.WindowCall):
                fn = e.func.name
                if e.func.filter is not None:
                    if fn not in AGGS:
                        raise AnalysisError(
                            "FILTER is only allowed for aggregate window "
                            "functions")
                    # same CASE desugar as plain aggregates: the window
                    # aggregates above skip NULL inputs
                    from citus_tpu.planner.bind import rewrite_agg_filter
                    e = dataclasses.replace(e, func=rewrite_agg_filter(e.func))
                args = [a for a in e.func.args if not isinstance(a, A.Star)]
                if fn in NAVIGATION:
                    arg_slots = [base_slot(args[0])] if args else []
                    params = tuple(literal_value(a) for a in args[1:])
                elif fn == "ntile":
                    arg_slots = []
                    params = tuple(literal_value(a) for a in args[:1])
                else:
                    arg_slots = [base_slot(a) for a in args]
                    params = ()
                part_slots = [base_slot(p) for p in e.partition_by]
                order_specs = [(base_slot(oe), asc) for oe, asc in e.order_by]
                outputs.append(("win", fn, arg_slots, part_slots, order_specs,
                                e.frame, params))
                names.append(item.alias or fn)
            else:
                outputs.append(("col", base_slot(e)))
                names.append(item.alias or (e.name if isinstance(e, A.ColumnRef)
                                            else f"column{i + 1}"))
        # the base query keeps GROUP BY/HAVING: windows then run over the
        # grouped rows (PostgreSQL semantics — windows after aggregation)
        base = A.Select(base_items, stmt.from_, stmt.where,
                        stmt.group_by, stmt.having)
        def window_pass(rows_in: list) -> list[tuple]:
            """Apply every window spec over one row set -> output rows."""
            n = len(rows_in)
            cols = [[row[j] for row in rows_in] for j in range(len(base_items))]
            out_cols = []
            for spec in outputs:
                if spec[0] == "col":
                    out_cols.append(cols[spec[1]])
                else:
                    _, fn, arg_slots, part_slots, order_specs, frame, params = spec
                    out_cols.append(compute_window(
                        n, fn, [cols[s] for s in arg_slots],
                        [cols[s] for s in part_slots],
                        [(cols[s], asc) for s, asc in order_specs],
                        frame=frame, params=params))
            return [tuple(c[i] for c in out_cols) for i in range(n)]

        strategy = "window:pull"
        if self._window_pushdown_eligible(stmt, outputs):
            # every window partitions by the distribution column, so no
            # partition spans shards: the whole window computation runs
            # per shard and results concatenate (reference: pushdown when
            # partitioned by the distribution column, multi_explain/
            # query_pushdown_planning safety proof)
            import dataclasses
            from citus_tpu.planner.physical import plan_select
            bound = bind_select(self.catalog, base)
            plan = plan_select(self.catalog, bound,
                               direct_limit=self.settings.planner.direct_gid_limit)
            rows = []
            for si in plan.shard_indexes:
                shard_plan = dataclasses.replace(plan, shard_indexes=[si])
                shard_rows = execute_select(self.catalog, bound, self.settings,
                                            plan=shard_plan).rows
                rows.extend(window_pass(shard_rows))
            strategy = "window:pushdown"
        else:
            rows = window_pass(self._execute_stmt(base).rows)
        # outer ORDER BY / LIMIT over the final outputs (name or position)
        for oi in reversed(stmt.order_by):
            idx = None
            if isinstance(oi.expr, A.Literal) and isinstance(oi.expr.value, int):
                idx = oi.expr.value - 1
            elif isinstance(oi.expr, A.ColumnRef) and oi.expr.name in names:
                idx = names.index(oi.expr.name)
            if idx is None or not (0 <= idx < len(names)):
                raise AnalysisError(
                    "ORDER BY with window functions must reference an output "
                    "name or position")
            nf = oi.nulls_first if oi.nulls_first is not None else (not oi.ascending)
            nulls = [x for x in rows if x[idx] is None]
            vals = [x for x in rows if x[idx] is not None]
            vals.sort(key=lambda x, j=idx: x[j], reverse=not oi.ascending)
            rows = (nulls + vals) if nf else (vals + nulls)
        if stmt.offset:
            rows = rows[stmt.offset:]
        if stmt.limit is not None:
            rows = rows[:stmt.limit]
        return Result(columns=names, rows=rows,
                      explain={"strategy": strategy})

    @staticmethod
    def _injective_in_column(e: A.Expr, col: str, alias: str) -> bool:
        """True when ``e`` is an injective function of the column: equal
        outputs imply equal column values, so partitioning by it can
        never group rows from different shards.  Covers the column
        itself and +/- of a constant, * by a nonzero constant, and
        unary minus, composed."""
        if isinstance(e, A.ColumnRef):
            return e.name == col and (e.table is None or e.table == alias)
        if isinstance(e, A.UnOp) and e.op == "-":
            return Cluster._injective_in_column(e.operand, col, alias)
        if isinstance(e, A.BinOp) and e.op in ("+", "-", "*"):
            def const_val(x):
                # integers only: float +/× is NOT injective over bigints
                # (rounding collapses distinct inputs at large magnitude)
                if isinstance(x, A.Literal) and isinstance(x.value, int) \
                        and not isinstance(x.value, bool):
                    return x.value
                if isinstance(x, A.UnOp) and x.op == "-":
                    v = const_val(x.operand)
                    return -v if v is not None else None
                return None
            for side, other in ((e.left, e.right), (e.right, e.left)):
                c = const_val(other)
                if c is None:
                    continue
                if e.op == "*" and c == 0:
                    return False
                if e.op == "-" and side is e.right and other is e.left:
                    # const - expr: still injective
                    pass
                if Cluster._injective_in_column(side, col, alias):
                    return True
        return False

    def _window_pushdown_eligible(self, stmt: A.Select, outputs) -> bool:
        """Safe to compute windows per shard: single distributed table,
        no GROUP BY, and every window's PARTITION BY includes the
        distribution column or an injective expression over it (equal
        partition values then imply equal distribution values, and hash
        partitions never span shards)."""
        if stmt.group_by or stmt.having:
            return False
        if not isinstance(stmt.from_, A.TableRef):
            return False
        if not self.catalog.has_table(stmt.from_.name):
            return False
        t = self.catalog.table(stmt.from_.name)
        if not t.is_distributed or t.dist_column is None:
            return False
        alias = stmt.from_.alias or stmt.from_.name
        for item in stmt.items:
            e = item.expr
            if not isinstance(e, A.WindowCall):
                continue
            if not any(self._injective_in_column(p, t.dist_column, alias)
                       for p in e.partition_by):
                return False
        return True

    _CTE_SEQ = [0]

    #: intermediate results at/above this row count distribute back out
    #: over the mesh instead of staying coordinator-local (reference:
    #: RedistributeTaskListResults / distributed_intermediate_results.c)
    DISTRIBUTED_INTERMEDIATE_ROWS = 4096

    def _schema_from_result(self, r: Result, *, strict_empty: bool = False):
        """(deduped column names, column types) for materializing a
        query result as a table.  Planner types win; otherwise infer
        from values.  ``strict_empty``: refuse to guess types for an
        empty untyped result (a PERSISTENT table must not silently get
        bigint columns; throwaway intermediates tolerate the default)."""
        names, seen = [], set()
        for i, n in enumerate(r.columns):
            base = n or f"column{i + 1}"
            cand, k = base, 1
            while cand in seen:
                k += 1
                cand = f"{base}_{k}"
            seen.add(cand)
            names.append(cand)
        types = list(r.types) if r.types else [None] * len(names)
        for i, ct_ in enumerate(types):
            if ct_ is None:
                if strict_empty and not r.rows:
                    raise UnsupportedFeatureError(
                        f"cannot infer the type of column {names[i]!r} "
                        "from an empty result; create the table "
                        "explicitly and INSERT instead")
                types[i] = _infer_column_type([row[i] for row in r.rows])
        return names, types

    def _create_temp_from_result(self, prefix: str, label: str, r: Result) -> str:
        """Store a query result as an intermediate-result table (the
        read_intermediate_result analog for CTEs / derived tables / set
        operations).  Small results stay local; large ones hash-
        distribute on their first integer-typed column so downstream
        joins and aggregations run sharded."""
        from citus_tpu import types as T
        names, types = self._schema_from_result(r)
        self._CTE_SEQ[0] += 1
        tmp = f"__{prefix}_{self._CTE_SEQ[0]}_{label}"
        self.catalog.create_table(
            tmp, Schema([Column(cn, ct_) for cn, ct_ in zip(names, types)]))
        if len(r.rows) >= self.DISTRIBUTED_INTERMEDIATE_ROWS:
            dist_col = next(
                (cn for cn, ct_ in zip(names, types)
                 if ct_.is_integer or ct_.kind in (T.DATE,)), None)
            if dist_col is not None:
                self.catalog.distribute_table(
                    tmp, dist_col, self.settings.sharding.shard_count,
                    self.catalog.active_node_ids())
                self.catalog.commit()
        if r.rows:
            self.copy_from(tmp, rows=r.rows)
        return tmp

    def _execute_derived(self, stmt: A.Select) -> Result:
        """Derived tables: execute each FROM-subquery, materialize it as
        an intermediate result, rewrite the FROM item to reference it
        (reference: RecursivelyPlanSubqueryWalker,
        recursive_planning.c:1303)."""
        temps: list[str] = []

        def repl(item):
            if isinstance(item, A.SubqueryRef):
                r = self._execute_stmt(item.select)
                if item.alias.startswith("__corr1row_") \
                        and "__cnt" in r.columns:
                    # decorrelated NON-aggregate scalar subquery: enforce
                    # PostgreSQL's runtime rule that it yields at most
                    # one row per outer key.  Stricter than PostgreSQL:
                    # we check every inner key, including ones no outer
                    # row probes — a conservative error, never a silent
                    # wrong answer
                    ci = r.columns.index("__cnt")
                    ni = (r.columns.index("__cntnull")
                          if "__cntnull" in r.columns else None)
                    for row in r.rows:
                        eff = row[ci] or 0
                        if ni is not None and (row[ni] or 0) > 0:
                            eff += 1  # NULL is one distinct row
                        if eff > 1:
                            raise AnalysisError(
                                "more than one row returned by a subquery "
                                "used as an expression")
                tmp = self._create_temp_from_result("derived", item.alias, r)
                temps.append(tmp)
                return A.TableRef(tmp, item.alias)
            if isinstance(item, A.FunctionRef):
                r = _srf_result(item.name, item.args, item.alias)
                label = item.alias or item.name
                tmp = self._create_temp_from_result("srf", label, r)
                temps.append(tmp)
                return A.TableRef(tmp, item.alias or item.name)
            if isinstance(item, A.Join):
                return A.Join(repl(item.left), repl(item.right),
                              item.kind, item.condition)
            return item

        try:
            new_stmt = A.Select(stmt.items, repl(stmt.from_), stmt.where,
                                stmt.group_by, stmt.having, stmt.order_by,
                                stmt.limit, stmt.offset, stmt.distinct,
                                stmt.windows)
            return self._execute_stmt(new_stmt)
        finally:
            for tmp in temps:
                try:
                    self.drop_table(tmp)
                except Exception:
                    pass

    def _expand_functions_stmt(self, stmt, depth: int = 0):
        """Inline user SQL functions (expression macros) everywhere in a
        SELECT/set operation — the planning-time analog of delegating a
        distributed function call next to the data
        (function_call_delegation.c)."""
        if depth > 8:
            raise AnalysisError("SQL function expansion too deep (recursive?)")
        fns = self.catalog.functions

        def rw(e, d):
            if e is None or not isinstance(e, A.Expr):
                return e
            if isinstance(e, A.FuncCall) and e.name in fns:
                spec = fns[e.name]
                if spec.get("kind") == "statement":
                    raise AnalysisError(
                        f'{e.name}() is a trigger function and cannot be '
                        "called in an expression")
                if len(e.args) != len(spec["args"]):
                    raise AnalysisError(
                        f'{e.name}() expects {len(spec["args"])} arguments')
                if d > 8:
                    raise AnalysisError(
                        "SQL function expansion too deep (recursive?)")
                from citus_tpu.planner.parser import Parser as _P
                body = _P(spec["body"]).parse_expr()
                sub = {n: rw(a, d) for n, a in zip(spec["args"], e.args)}
                return rw(_subst_args(body, sub), d + 1)
            if isinstance(e, A.BinOp):
                return A.BinOp(e.op, rw(e.left, d), rw(e.right, d))
            if isinstance(e, A.UnOp):
                return A.UnOp(e.op, rw(e.operand, d))
            if isinstance(e, A.Between):
                return A.Between(rw(e.expr, d), rw(e.lo, d), rw(e.hi, d), e.negated)
            if isinstance(e, A.InList):
                return A.InList(rw(e.expr, d), tuple(rw(i, d) for i in e.items),
                                e.negated)
            if isinstance(e, A.IsNull):
                return A.IsNull(rw(e.expr, d), e.negated)
            if isinstance(e, A.Cast):
                return A.Cast(rw(e.expr, d), e.type_name, e.type_args)
            if isinstance(e, A.CaseExpr):
                return A.CaseExpr(tuple((rw(c, d), rw(v, d)) for c, v in e.whens),
                                  rw(e.else_, d) if e.else_ is not None else None)
            if isinstance(e, A.FuncCall):
                import dataclasses
                return dataclasses.replace(
                    e, args=tuple(rw(a, d) for a in e.args),
                    agg_order=tuple((rw(oe, d), asc)
                                    for oe, asc in e.agg_order),
                    filter=rw(e.filter, d) if e.filter is not None else None)
            if isinstance(e, A.WindowCall):
                return A.WindowCall(rw(e.func, d) if e.func is not None else None,
                                    tuple(rw(p, d) for p in e.partition_by),
                                    tuple((rw(oe, d), asc) for oe, asc in e.order_by),
                                    e.frame, e.ref_name, e.ref_verbatim)
            return e

        if isinstance(stmt, A.SetOp):
            return A.SetOp(stmt.op, stmt.all,
                           self._expand_functions_stmt(stmt.left, depth + 1),
                           self._expand_functions_stmt(stmt.right, depth + 1),
                           stmt.order_by, stmt.limit, stmt.offset)
        return A.Select(
            [A.SelectItem(rw(i.expr, 0), i.alias) for i in stmt.items],
            stmt.from_, rw(stmt.where, 0),
            [rw(g, 0) for g in stmt.group_by], rw(stmt.having, 0),
            [A.OrderItem(rw(o.expr, 0), o.ascending, o.nulls_first)
             for o in stmt.order_by],
            stmt.limit, stmt.offset, stmt.distinct,
            tuple((wn, rw(spec, 0)) for wn, spec in stmt.windows),
            tuple(rw(e, 0) for e in stmt.distinct_on))

    def _execute_constant_select(self, stmt: A.Select) -> Result:
        """SELECT without FROM: constant expressions evaluated on the
        coordinator (one row), including scalar subqueries."""
        from citus_tpu.planner.recursive import rewrite_subqueries
        stmt = rewrite_subqueries(stmt, lambda sub: self._execute_stmt(sub))
        if stmt.group_by or stmt.having or stmt.distinct:
            raise UnsupportedFeatureError(
                "GROUP BY/HAVING/DISTINCT need a FROM clause")
        row, names = [], []
        for i, item in enumerate(stmt.items):
            row.append(_eval_const(item.expr))
            names.append(item.alias or (item.expr.name
                                        if isinstance(item.expr, A.ColumnRef)
                                        else f"column{i + 1}"))
        rows = [tuple(row)]
        if stmt.where is not None:
            if _eval_const(stmt.where) is not True:
                rows = []
        if stmt.offset:
            rows = rows[stmt.offset:]
        if stmt.limit is not None:
            rows = rows[:stmt.limit]
        return Result(columns=names, rows=rows,
                      explain={"strategy": "constant"})

    def _expand_views(self, item):
        """FROM references to views become derived tables over the view's
        stored SELECT (reference: views as distributed objects,
        commands/view.c; execution via recursive planning)."""
        if isinstance(item, A.TableRef) and item.name in self.catalog.views:
            sel = parse_sql(self.catalog.views[item.name])[0]
            return A.SubqueryRef(sel, item.alias or item.name)
        if isinstance(item, A.Join):
            left = self._expand_views(item.left)
            right = self._expand_views(item.right)
            if left is not item.left or right is not item.right:
                return A.Join(left, right, item.kind, item.condition)
        return item

    def _execute_grouping_sets(self, stmt: A.Select, sets) -> Result:
        """ROLLUP/CUBE/GROUPING SETS: one grouped execution per set,
        select items that are grouping expressions of an absent set pad
        to NULL, results concatenate (reference: native grouping-set
        execution; here composed over the standard grouped pipeline)."""
        all_keys = set()
        for s_ in sets:
            all_keys.update(s_)
        names = []
        for i, item in enumerate(stmt.items):
            names.append(item.alias or (item.expr.name
                                        if isinstance(item.expr, A.ColumnRef)
                                        else f"column{i + 1}"))
        rows_all: list[tuple] = []
        types_first = None
        for s_ in sets:
            keep_pos, sub_items = [], []
            grouping_marks = {}  # position -> 0/1 constant for this set
            for i, item in enumerate(stmt.items):
                e = item.expr
                if isinstance(e, A.FuncCall) and e.name == "grouping" \
                        and len(e.args) == 1:
                    # GROUPING(col): 1 when the column is rolled up
                    # (absent from this set), 0 when grouped by
                    grouping_marks[i] = 0 if e.args[0] in s_ else 1
                    continue
                if e in all_keys and e not in s_:
                    continue  # key absent from this set: pad NULL
                keep_pos.append(i)
                sub_items.append(item)
            # HAVING may reference rolled-up columns: they are NULL in
            # this set (PostgreSQL semantics)
            having = stmt.having
            if having is not None:
                absent = {k for k in all_keys if k not in s_}
                if absent:
                    having = _replace_exprs(
                        having, {k: A.Literal(None, "null") for k in absent})
            if not sub_items:
                # only grouping columns selected and this is the empty
                # set: the grand-total group is one all-NULL row
                probe = A.Select([A.SelectItem(
                    A.FuncCall("count", (A.Star(),)))],
                    stmt.from_, stmt.where, list(s_), having)
                if self._execute_stmt(probe).rows:
                    full = [None] * len(stmt.items)
                    for pos, mark in grouping_marks.items():
                        full[pos] = mark
                    rows_all.append(tuple(full))
                continue
            sub = A.Select(sub_items, stmt.from_, stmt.where, list(s_),
                           having)
            r = self._execute_stmt(sub)
            if types_first is None and not any(
                    i not in keep_pos for i in range(len(stmt.items))):
                types_first = r.types
            for row in r.rows:
                full = [None] * len(stmt.items)
                for j, pos in enumerate(keep_pos):
                    full[pos] = row[j]
                for pos, mark in grouping_marks.items():
                    full[pos] = mark
                rows_all.append(tuple(full))
        if stmt.distinct:
            rows_all = list(dict.fromkeys(rows_all))
        rows_all = _sort_rows(rows_all, names, stmt.order_by)
        if stmt.offset:
            rows_all = rows_all[stmt.offset:]
        if stmt.limit is not None:
            rows_all = rows_all[:stmt.limit]
        return Result(columns=names, rows=rows_all, types=types_first,
                      explain={"strategy": "grouping_sets",
                               "sets": len(sets)})

    def _execute_setop(self, stmt: A.SetOp) -> Result:
        """UNION / INTERSECT / EXCEPT [ALL]: execute both sides, combine
        on the coordinator with SQL bag/set semantics (NULLs compare
        equal, like DISTINCT).  Reference: set operations that cannot be
        pushed down run through recursive planning
        (recursive_planning.c:223)."""
        from collections import Counter
        lres = self._execute_stmt(stmt.left)
        rres = self._execute_stmt(stmt.right)
        if len(lres.columns) != len(rres.columns):
            raise AnalysisError(
                "each side of a set operation must return the same number "
                "of columns")
        lrows, rrows = list(lres.rows), list(rres.rows)
        if stmt.op == "union":
            rows = lrows + rrows
            if not stmt.all:
                rows = list(dict.fromkeys(rows))
        elif stmt.op == "intersect":
            rc = Counter(rrows)
            if stmt.all:
                rows, used = [], Counter()
                for row in lrows:
                    if used[row] < rc.get(row, 0):
                        used[row] += 1
                        rows.append(row)
            else:
                rows = [row for row in dict.fromkeys(lrows) if rc.get(row, 0)]
        else:  # except
            if stmt.all:
                rc = Counter(rrows)
                rows, used = [], Counter()
                for row in lrows:
                    if used[row] < rc.get(row, 0):
                        used[row] += 1
                    else:
                        rows.append(row)
            else:
                rset = set(rrows)
                rows = [row for row in dict.fromkeys(lrows) if row not in rset]
        rows = _sort_rows(rows, lres.columns, stmt.order_by)
        if stmt.offset:
            rows = rows[stmt.offset:]
        if stmt.limit is not None:
            rows = rows[:stmt.limit]
        return Result(columns=lres.columns, rows=rows,
                      types=lres.types or rres.types,
                      explain={"strategy": f"setop:{stmt.op}"})

    def _execute_with(self, stmt: A.WithSelect) -> Result:
        """Materialize each CTE as a temporary local table (the
        intermediate-result strategy of recursive_planning.c), rewrite
        references in later CTEs and the body, execute, drop."""
        mapping: dict[str, str] = {}
        temps: list[str] = []

        def remap_from(item):
            if isinstance(item, A.TableRef):
                if item.name in mapping:
                    return A.TableRef(mapping[item.name], item.alias or item.name)
                return item
            if isinstance(item, A.Join):
                return A.Join(remap_from(item.left), remap_from(item.right),
                              item.kind, item.condition)
            if isinstance(item, A.SubqueryRef):
                return A.SubqueryRef(remap_select(item.select), item.alias)
            return item

        def remap_select(sel):
            import dataclasses
            if isinstance(sel, A.SetOp):
                return A.SetOp(sel.op, sel.all, remap_select(sel.left),
                               remap_select(sel.right), sel.order_by,
                               sel.limit, sel.offset)
            # dataclasses.replace carries every other field (windows,
            # future additions) — positional rebuilds have dropped
            # fields here before
            return dataclasses.replace(sel, from_=remap_from(sel.from_))

        try:
            for name, sel in stmt.ctes:
                r = self._execute_stmt(remap_select(sel))
                tmp = self._create_temp_from_result("cte", name, r)
                mapping[name] = tmp
                temps.append(tmp)
            body = remap_select(stmt.body)
            return self._execute_stmt(body)
        finally:
            for tmp in temps:
                try:
                    self.drop_table(tmp)
                except Exception:
                    pass

    def _policy_predicate(self, role: str, table: str, cmd: str,
                          kind: str = "using") -> Optional[A.Expr]:
        """RLS predicate for (role, table, command): None when RLS is
        off for the table; FALSE when enabled with no applicable policy
        (default deny); else the OR of applicable policies' expressions
        (permissive policies, PostgreSQL default).  ``kind`` selects
        USING or WITH CHECK (check falls back to using, as PG does)."""
        if not self.catalog.rls.get(table):
            return None
        texts = []
        for p in self.catalog.policies.get(table, ()):
            if p["cmd"] not in ("all", cmd):
                continue
            if "public" not in p["roles"] and role not in p["roles"]:
                continue
            text = p.get(kind) or (p.get("using") if kind == "check" else None)
            if text:
                texts.append(text)
        if not texts:
            return A.Literal(False, "bool")
        from citus_tpu.planner.parser import Parser as _P
        cache = getattr(self, "_policy_expr_cache", None)
        if cache is None:
            cache = self._policy_expr_cache = {}
        exprs = []
        for t in texts:
            parsed = cache.get(t)
            if parsed is None:
                parsed = cache[t] = _P(t).parse_expr()
            exprs.append(parsed)
        out = exprs[0]
        for e in exprs[1:]:
            out = A.BinOp("or", out, e)
        return out

    def _apply_rls(self, role: str, stmt: A.Statement):
        """Row-level security rewrite for a non-superuser role ->
        (statement, changed).  Every table reference of an RLS-enabled
        table — in FROM (incl. joins/derived tables), set operations,
        CTEs, and expression subqueries (scalar/IN/EXISTS) — wraps in a
        policy-filtered derived table; UPDATE/DELETE additionally AND
        the predicate into WHERE and enforce WITH CHECK on assignments;
        INSERT VALUES rows evaluate WITH CHECK per row (reference:
        commands/policy.c; superuser role=None bypasses, like table
        owners in PG)."""
        import dataclasses
        changed = [False]
        EMPTY = frozenset()

        def rew_from(item, shadow):
            if isinstance(item, A.TableRef):
                if item.name in shadow:
                    return item  # resolves to a CTE, not the base table
                if not self.catalog.has_table(item.name):
                    return item
                f = self._policy_predicate(role, item.name, "select")
                if f is None:
                    return item
                changed[0] = True
                sel = A.Select([A.SelectItem(A.Star())],
                               A.TableRef(item.name), f)
                return A.SubqueryRef(sel,
                                     item.alias or item.name.split(".")[-1])
            if isinstance(item, A.Join):
                return A.Join(rew_from(item.left, shadow),
                              rew_from(item.right, shadow),
                              item.kind, item.condition)
            if isinstance(item, A.SubqueryRef):
                return A.SubqueryRef(rew_stmt(item.select, shadow),
                                     item.alias)
            return item

        def rew_expr(e, shadow):
            if e is None or not isinstance(e, A.Expr):
                return e
            if isinstance(e, A.Subquery):
                return A.Subquery(rew_stmt(e.select, shadow))
            if isinstance(e, A.Exists):
                return A.Exists(rew_stmt(e.select, shadow))
            if isinstance(e, A.BinOp):
                return A.BinOp(e.op, rew_expr(e.left, shadow),
                               rew_expr(e.right, shadow))
            if isinstance(e, A.UnOp):
                return A.UnOp(e.op, rew_expr(e.operand, shadow))
            if isinstance(e, A.Between):
                return A.Between(rew_expr(e.expr, shadow),
                                 rew_expr(e.lo, shadow),
                                 rew_expr(e.hi, shadow), e.negated)
            if isinstance(e, A.InList):
                return A.InList(rew_expr(e.expr, shadow),
                                tuple(rew_expr(i, shadow) for i in e.items),
                                e.negated)
            if isinstance(e, A.IsNull):
                return A.IsNull(rew_expr(e.expr, shadow), e.negated)
            if isinstance(e, A.Cast):
                return A.Cast(rew_expr(e.expr, shadow), e.type_name,
                              e.type_args)
            if isinstance(e, A.CaseExpr):
                return A.CaseExpr(
                    tuple((rew_expr(c, shadow), rew_expr(v, shadow))
                          for c, v in e.whens),
                    rew_expr(e.else_, shadow) if e.else_ is not None
                    else None)
            if isinstance(e, A.FuncCall):
                import dataclasses
                return dataclasses.replace(
                    e, args=tuple(rew_expr(a, shadow) for a in e.args),
                    agg_order=tuple((rew_expr(oe, shadow), asc)
                                    for oe, asc in e.agg_order),
                    filter=rew_expr(e.filter, shadow)
                    if e.filter is not None else None)
            if isinstance(e, A.WindowCall):
                return A.WindowCall(
                    rew_expr(e.func, shadow) if e.func is not None else None,
                    tuple(rew_expr(p, shadow) for p in e.partition_by),
                    tuple((rew_expr(oe, shadow), asc)
                          for oe, asc in e.order_by),
                    e.frame, e.ref_name, e.ref_verbatim)
            return e

        def rew_stmt(s, shadow):
            if isinstance(s, A.SetOp):
                return dataclasses.replace(s, left=rew_stmt(s.left, shadow),
                                           right=rew_stmt(s.right, shadow))
            if isinstance(s, A.WithSelect):
                # a CTE's definition may reference only EARLIER CTE
                # names; later refs resolve to the base relations
                seen = set(shadow)
                new_ctes = []
                for n, sel in s.ctes:
                    new_ctes.append((n, rew_stmt(sel, frozenset(seen))))
                    seen.add(n)
                return A.WithSelect(new_ctes,
                                    rew_stmt(s.body, frozenset(seen)))
            if not isinstance(s, A.Select):
                return s
            return dataclasses.replace(
                s,
                items=[A.SelectItem(rew_expr(i.expr, shadow), i.alias)
                       for i in s.items],
                from_=rew_from(s.from_, shadow) if s.from_ is not None
                else None,
                where=rew_expr(s.where, shadow),
                group_by=[rew_expr(g, shadow) for g in s.group_by],
                having=rew_expr(s.having, shadow),
                order_by=[A.OrderItem(rew_expr(o.expr, shadow), o.ascending,
                                      o.nulls_first) for o in s.order_by])

        if isinstance(stmt, (A.Select, A.SetOp, A.WithSelect)):
            new_stmt = rew_stmt(stmt, EMPTY)
            return (new_stmt, True) if changed[0] else (stmt, False)
        if isinstance(stmt, (A.Update, A.Delete)):
            cmd = "update" if isinstance(stmt, A.Update) else "delete"
            f = self._policy_predicate(role, stmt.table, cmd)
            # embedded subqueries (WHERE / SET) read through RLS too,
            # regardless of whether the TARGET table has policies
            new_where = rew_expr(stmt.where, EMPTY)
            if isinstance(stmt, A.Update):
                new_assign = [(c, rew_expr(e, EMPTY))
                              for c, e in stmt.assignments]
            if f is None:
                if isinstance(stmt, A.Update):
                    return (dataclasses.replace(
                        stmt, assignments=new_assign, where=new_where),
                        changed[0])
                return dataclasses.replace(stmt, where=new_where), changed[0]
            if isinstance(stmt, A.Update):
                self._rls_check_update(role, stmt)
            where = f if new_where is None else A.BinOp("and", new_where, f)
            if isinstance(stmt, A.Update):
                return (dataclasses.replace(
                    stmt, assignments=new_assign, where=where), True)
            return dataclasses.replace(stmt, where=where), True
        if isinstance(stmt, A.Insert):
            # the SELECT source / row expressions read through RLS
            new_select = (rew_stmt(stmt.select, EMPTY)
                          if stmt.select is not None else None)
            new_rows = ([[rew_expr(v, EMPTY) for v in row]
                         for row in stmt.rows] if stmt.rows else stmt.rows)
            f = self._policy_predicate(role, stmt.table, "insert",
                                       kind="check")
            if f is None:
                if changed[0]:
                    return dataclasses.replace(
                        stmt, select=new_select, rows=new_rows), True
                return stmt, False
            if stmt.select is not None or not stmt.rows:
                raise UnsupportedFeatureError(
                    "INSERT ... SELECT under row-level security is not "
                    "supported")
            t = self.catalog.table(stmt.table)
            cols = stmt.columns or t.schema.names
            for row in stmt.rows:
                subst = {c: v for c, v in zip(cols, row)}
                checked = _subst_args(f, subst)
                try:
                    ok = _eval_const(checked)
                except Exception:
                    raise UnsupportedFeatureError(
                        "row-level security WITH CHECK over non-constant "
                        "inserts is not supported")
                if ok is not True:
                    raise AnalysisError(
                        f'new row violates row-level security policy for '
                        f'table "{stmt.table}"')
            return (dataclasses.replace(stmt, rows=new_rows), True) \
                if changed[0] else (stmt, False)
        return stmt, False

    def _rls_check_update(self, role: str, stmt: A.Update) -> None:
        """WITH CHECK enforcement for UPDATE: the NEW row must satisfy
        the policy (PostgreSQL raises when an update rewrites a row out
        of policy scope).  Assigned-constant columns substitute into the
        check expression; a fully-constant result enforces directly;
        assignments that don't touch any check column are safe when the
        check falls back to USING (the untouched columns already passed
        it); anything else fails closed."""
        eff = self._policy_predicate(role, stmt.table, "update",
                                     kind="check")
        if eff is None:
            return
        from citus_tpu.planner.recursive import (
            _walk_columns as _walk_ast_columns,
        )
        check_cols = {c.name for c in _walk_ast_columns(eff)
                      if c.table is None}
        assigned = dict(stmt.assignments)
        subst = {}
        for col, val in assigned.items():
            if col in check_cols:
                subst[col] = val
        if subst:
            checked = _subst_args(eff, subst)
            remaining = {c.name for c in _walk_ast_columns(checked)}
            if remaining:
                raise UnsupportedFeatureError(
                    "cannot verify row-level security WITH CHECK for this "
                    "UPDATE (non-constant or mixed-column assignment)")
            try:
                ok = _eval_const(checked)
            except Exception:
                raise UnsupportedFeatureError(
                    "cannot verify row-level security WITH CHECK for this "
                    "UPDATE (non-constant assignment)")
            if ok is not True:
                raise AnalysisError(
                    "new row violates row-level security policy for "
                    f'table "{stmt.table}"')
            return
        # no check column assigned: safe only when check == using (the
        # unchanged columns already satisfied USING via the row filter)
        using = self._policy_predicate(role, stmt.table, "update",
                                       kind="using")
        if repr(eff) != repr(using):
            raise UnsupportedFeatureError(
                "cannot verify row-level security WITH CHECK for this "
                "UPDATE (policy has a distinct WITH CHECK expression)")

    def _fire_triggers(self, stmt: A.Statement, depth: int = 0) -> None:
        """Statement-level AFTER triggers: run each matching trigger's
        function body after a DML statement completes (reference:
        commands/trigger.c; bodies are stored SQL statements)."""
        if isinstance(stmt, A.Insert):
            table, event = stmt.table, "insert"
        elif isinstance(stmt, A.Update):
            table, event = stmt.table, "update"
        elif isinstance(stmt, A.Delete):
            table, event = stmt.table, "delete"
        elif isinstance(stmt, A.Merge):
            # MERGE may insert, update, or delete: fire all three
            for evt in ("insert", "update", "delete"):
                self._fire_triggers_for(stmt.target.name, evt, depth)
            return
        else:
            return
        self._fire_triggers_for(table, event, depth)

    def _fire_triggers_for(self, table: str, event: str, depth: int) -> None:
        matching = [t for t in self.catalog.triggers.values()
                    if t["table"] == table and t["event"] == event]
        if not matching:
            return
        if depth >= 8:
            raise ExecutionError(
                "trigger recursion limit exceeded (8 levels)")
        for trig in matching:
            fn = self.catalog.functions.get(trig["function"])
            if fn is None:
                continue
            for body_stmt in parse_sql(fn["body"]):
                self._execute_stmt(body_stmt)
                self._fire_triggers(body_stmt, depth + 1)

    def _check_privileges(self, role: str, stmt: A.Statement) -> None:
        """Table-level privilege enforcement for a non-superuser role
        (reference: standard ACLs propagated by commands/grant.c; a
        missing grant denies).  DDL and utility statements require
        superuser (role=None)."""
        from citus_tpu.errors import CatalogError
        if role not in self.catalog.roles:
            raise CatalogError(f'role "{role}" does not exist')

        def deny(priv, table):
            raise CatalogError(
                f'permission denied for {table}: role "{role}" lacks {priv}')

        def tables_of(item):
            if isinstance(item, A.TableRef):
                return [item.name]
            if isinstance(item, A.SubqueryRef):
                return stmt_tables(item.select)
            if isinstance(item, A.Join):
                return tables_of(item.left) + tables_of(item.right)
            return []

        def expr_subselects(e):
            from citus_tpu.planner.recursive import _walk_expr
            if e is None or not isinstance(e, A.Expr):
                return []
            return [n.select for n in _walk_expr(e)]

        def stmt_tables(s):
            if isinstance(s, A.SetOp):
                return stmt_tables(s.left) + stmt_tables(s.right)
            if not isinstance(s, A.Select):
                return []
            out = tables_of(s.from_) if s.from_ is not None else []
            # subqueries anywhere in expressions read tables too
            exprs = ([i.expr for i in s.items] + [s.where, s.having]
                     + list(s.group_by) + [o.expr for o in s.order_by])
            for e in exprs:
                for sub in expr_subselects(e):
                    out.extend(stmt_tables(sub))
            return out

        def check_read(s, skip=frozenset()):
            for t in stmt_tables(s):
                if t in skip:
                    continue  # CTE name, not a real relation
                if not self.catalog.has_privilege(role, t, "select"):
                    deny("SELECT", t)

        if isinstance(stmt, (A.Select, A.SetOp)):
            check_read(stmt)
        elif isinstance(stmt, A.WithSelect):
            # a CTE's definition may reference only EARLIER CTE names —
            # a same-named reference inside its own body resolves to the
            # real relation and must be privilege-checked as one
            seen: set = set()
            for n, sel in stmt.ctes:
                check_read(sel, skip=frozenset(seen))
                seen.add(n)
            check_read(stmt.body, skip=frozenset(seen))
        elif isinstance(stmt, A.Insert):
            if not self.catalog.has_privilege(role, stmt.table, "insert"):
                deny("INSERT", stmt.table)
            if stmt.on_conflict is not None \
                    and stmt.on_conflict.action == "update" \
                    and not self.catalog.has_privilege(role, stmt.table,
                                                       "update"):
                # DO UPDATE modifies existing rows (PostgreSQL requires
                # UPDATE privilege in addition to INSERT)
                deny("UPDATE", stmt.table)
            if stmt.select is not None:
                check_read(stmt.select)
        elif isinstance(stmt, A.Update):
            if not self.catalog.has_privilege(role, stmt.table, "update"):
                deny("UPDATE", stmt.table)
            for _c, e in stmt.assignments:
                for sub in expr_subselects(e):
                    check_read(sub)
            for sub in expr_subselects(stmt.where):
                check_read(sub)
        elif isinstance(stmt, A.Delete):
            if not self.catalog.has_privilege(role, stmt.table, "delete"):
                deny("DELETE", stmt.table)
            for sub in expr_subselects(stmt.where):
                check_read(sub)
        elif isinstance(stmt, A.Truncate):
            for name in (stmt.table,) + tuple(stmt.more):
                if not self.catalog.has_privilege(role, name, "truncate"):
                    deny("TRUNCATE", name)
        elif isinstance(stmt, (A.Prepare, A.ExecutePrepared, A.Deallocate)):
            # any role may manage prepared statements (PostgreSQL);
            # EXECUTE re-enters execute() with the same role, which
            # checks privileges on the underlying statement
            pass
        else:
            from citus_tpu.errors import CatalogError as _CE
            raise _CE(f'permission denied: role "{role}" cannot run '
                      f'{type(stmt).__name__} statements')

    def _execute_utility(self, stmt: A.UtilityCall) -> Result:
        name, args = stmt.name, stmt.args
        if name == "create_distributed_table":
            shard_count = int(args[2]) if len(args) > 2 else None
            self.create_distributed_table(args[0], args[1], shard_count)
            return Result(columns=[name], rows=[(None,)])
        if name == "create_reference_table":
            self.create_reference_table(args[0])
            return Result(columns=[name], rows=[(None,)])
        if name == "create_time_partitions":
            from citus_tpu.partitioning import create_time_partitions
            n = create_time_partitions(
                self, args[0], args[1], args[2],
                args[3] if len(args) > 3 else None)
            return Result(columns=[name], rows=[(n > 0,)],
                          explain={"partitions_created": n})
        if name == "drop_old_time_partitions":
            from citus_tpu.partitioning import drop_old_time_partitions
            n = drop_old_time_partitions(self, args[0], args[1])
            return Result(columns=[name], rows=[(n,)],
                          explain={"partitions_dropped": n})
        if name == "time_partitions":
            # the time_partitions view (reference: a SQL view over
            # pg_class + partition bounds)
            rows = []
            for t in self.catalog.tables.values():
                if t.partition_of is not None:
                    rows.append((t.partition_of["parent"], t.name,
                                 t.partition_of["lo"], t.partition_of["hi"]))
            return Result(
                columns=["parent_table", "partition", "from_value",
                         "to_value"], rows=sorted(rows))
        if name == "citus_extensions":
            return Result(columns=["name", "version"],
                          rows=sorted((k, v.get("version"))
                                      for k, v in self.catalog.extensions.items()))
        if name == "citus_domains":
            return Result(
                columns=["name", "base_type", "not_null", "check"],
                rows=sorted((k, v["base"], v["not_null"], v.get("check"))
                            for k, v in self.catalog.domains.items()))
        if name == "citus_collations":
            return Result(columns=["name", "locale", "provider"],
                          rows=sorted((k, v.get("locale"), v.get("provider"))
                                      for k, v in self.catalog.collations.items()))
        if name == "citus_publications":
            rows = []
            for k, v in sorted(self.catalog.publications.items()):
                tl = v.get("tables")
                rows.append((k, "ALL TABLES" if tl == "all"
                             else ", ".join(tl)))
            return Result(columns=["name", "tables"], rows=rows)
        if name == "citus_statistics_objects":
            return Result(
                columns=["name", "table", "columns", "ndistinct"],
                rows=sorted((k, v["table"], ", ".join(v["columns"]),
                             v["ndistinct"])
                            for k, v in self.catalog.statistics.items()))
        if name == "citus_stat_pool":
            # shared task-pool admission counters (the
            # citus.max_shared_pool_size / shared_connection_stats view)
            from citus_tpu.executor.admission import GLOBAL_POOL
            st = GLOBAL_POOL.stats()
            st["pool_size"] = self.settings.executor.max_shared_pool_size
            cols = ["pool_size", "in_use", "high_water", "granted",
                    "denied_optional", "waits"]
            return Result(columns=cols, rows=[tuple(st[c] for c in cols)])
        if name == "citus_table_size":
            return Result(columns=["citus_table_size"],
                          rows=[(self._table_size(args[0]),)])
        if name == "citus_shard_sizes":
            import os as _os
            rows = []
            for t in self.catalog.tables.values():
                for s_ in t.shards:
                    for node in s_.placements:
                        d = self.catalog.shard_dir(t.name, s_.shard_id, node)
                        size = sum(_os.path.getsize(_os.path.join(d, f))
                                   for f in _os.listdir(d)) if _os.path.isdir(d) else 0
                        rows.append((t.name, s_.shard_id, node, size))
            return Result(columns=["table_name", "shardid", "node", "size"], rows=rows)
        if name == "citus_check_cluster_node_health":
            import os as _os
            rows = []
            for nid in self.catalog.active_node_ids():
                ok = True
                for t in self.catalog.tables.values():
                    for s_ in t.shards:
                        if nid in s_.placements:
                            d = self.catalog.shard_dir(t.name, s_.shard_id, nid)
                            if _os.path.isdir(d) and not _os.access(d, _os.R_OK):
                                ok = False
                rows.append((nid, ok))
            return Result(columns=["node", "healthy"], rows=rows)
        if name == "master_get_active_worker_nodes":
            return Result(columns=["node_id"],
                          rows=[(nid,) for nid in self.catalog.active_node_ids()])
        if name == "citus_add_node":
            from citus_tpu.catalog.catalog import NodeMeta
            nid = max(self.catalog.nodes, default=-1) + 1
            self.catalog.nodes[nid] = NodeMeta(nid)
            self.catalog.ddl_epoch += 1
            self.catalog.commit()
            return Result(columns=["citus_add_node"], rows=[(nid,)])
        if name == "citus_remove_node":
            nid = int(args[0]) if args else None
            if nid is None or nid not in self.catalog.nodes:
                raise CatalogError(f"node {nid} does not exist")
            for t in self.catalog.tables.values():
                for s in t.shards:
                    if nid in s.placements:
                        raise CatalogError(
                            f"cannot remove node {nid}: it still has shard placements")
            del self.catalog.nodes[nid]
            self.catalog.ddl_epoch += 1
            self.catalog.commit()
            return Result(columns=["citus_remove_node"], rows=[(None,)])
        if name == "citus_move_shard_placement":
            from citus_tpu.operations import move_shard_placement
            move_shard_placement(self.catalog, int(args[0]), int(args[1]),
                                 int(args[2]), lock_manager=self.locks)
            self._plan_cache.clear()
            return Result(columns=[name], rows=[(None,)])
        if name == "get_rebalance_table_shards_plan":
            from citus_tpu.operations import get_rebalance_plan
            moves = get_rebalance_plan(
                self.catalog, args[0] if args else None,
                strategy=str(args[1]) if len(args) > 1 else "by_disk_size")
            return Result(columns=["shardid", "sourcenode", "targetnode"],
                          rows=[m.to_row() for m in moves])
        if name == "rebalance_table_shards":
            from citus_tpu.operations import rebalance_table_shards
            moves = rebalance_table_shards(
                self.catalog, args[0] if args else None,
                strategy=str(args[1]) if len(args) > 1 else "by_disk_size",
                lock_manager=self.locks)
            self._plan_cache.clear()
            return Result(columns=["rebalance_table_shards"],
                          rows=[(len(moves),)])
        if name == "citus_rebalance_start":
            from citus_tpu.operations import get_rebalance_plan
            moves = get_rebalance_plan(self.catalog)
            jid = self.background_jobs.create_job("Rebalance all colocation groups")
            prev = None
            for m in moves:
                prev = self.background_jobs.add_task(
                    jid, "move_shard",
                    {"shard_id": m.shard_id, "source": m.source_node, "target": m.target_node},
                    depends_on=[prev] if prev is not None else None,
                    node=m.target_node)
            return Result(columns=["citus_rebalance_start"], rows=[(jid,)])
        if name == "citus_job_wait":
            status = self.background_jobs.wait_for_job(int(args[0]))
            self._plan_cache.clear()
            return Result(columns=["citus_job_wait"], rows=[(status,)])
        if name == "citus_cleanup_orphaned_resources":
            from citus_tpu.operations import try_drop_orphaned_resources
            n = try_drop_orphaned_resources(self.catalog)
            return Result(columns=["citus_cleanup_orphaned_resources"], rows=[(n,)])
        if name == "citus_copy_shard_placement":
            from citus_tpu.operations import copy_shard_placement
            copy_shard_placement(self.catalog, int(args[0]), int(args[1]), int(args[2]))
            self._plan_cache.clear()
            return Result(columns=[name], rows=[(None,)])
        if name == "citus_split_shard_by_split_points":
            from citus_tpu.operations.shard_split import split_shard
            points = [int(a) for a in args[1:] if not isinstance(a, str) or a.lstrip("-").isdigit()]
            new_ids = split_shard(self.catalog, int(args[0]), points,
                                  lock_manager=self.locks)
            self._plan_cache.clear()
            return Result(columns=["new_shard_ids"], rows=[(i,) for i in new_ids])
        if name == "isolate_tenant_to_new_shard":
            # reference: isolate_shards.c — put one distribution-key value
            # in its own shard by splitting around its hash
            from citus_tpu.catalog.hashing import hash_int64_scalar, shard_index_for_hash
            from citus_tpu.operations.shard_split import split_shard
            import numpy as _np
            t = self.catalog.table(args[0])
            h = hash_int64_scalar(int(args[1]))
            si = int(shard_index_for_hash(_np.array([h], _np.int32), t.shard_count)[0])
            shard = t.shards[si]
            points = []
            if h - 1 >= shard.hash_min:
                points.append(h - 1)
            if h < shard.hash_max:
                points.append(h)
            new_ids = split_shard(self.catalog, shard.shard_id, points,
                                  lock_manager=self.locks)
            self._plan_cache.clear()
            return Result(columns=["isolate_tenant_to_new_shard"],
                          rows=[(new_ids[1 if h - 1 >= shard.hash_min else 0],)])
        if name == "citus_stat_counters":
            snap = self.counters.snapshot()
            return Result(columns=["counter", "value"],
                          rows=sorted(snap.items()))
        if name == "citus_stat_counters_reset":
            self.counters.reset()
            return Result(columns=[name], rows=[(None,)])
        if name == "citus_stat_statements":
            return Result(columns=["query", "executor", "partition_key",
                                   "calls", "total_time_ms", "rows"],
                          rows=self.query_stats.rows_view())
        if name == "citus_stat_statements_reset":
            self.query_stats.reset()
            return Result(columns=[name], rows=[(None,)])
        if name == "citus_schemas":
            rows = []
            for sname, info in self.catalog.schemas.items():
                members = [t for t in self.catalog.tables if t.startswith(sname + ".")]
                size = sum(self._table_size(m) for m in members)
                rows.append((sname, info["colocation_id"], info["home_node"],
                             len(members), size))
            return Result(columns=["schema_name", "colocation_id", "node",
                                   "table_count", "schema_size"], rows=rows)
        if name == "citus_stat_tenants":
            return Result(columns=["tenant", "query_count", "total_time_ms"],
                          rows=self.tenant_stats.rows_view())
        if name == "get_rebalance_progress":
            rows = []
            if self._background_jobs is not None:
                with self._background_jobs._lock:
                    jobs = [j["job_id"] for j in self._background_jobs._state["jobs"]]
                for jid in jobs:
                    rows.extend(self._background_jobs.job_progress(jid))
            return Result(columns=["task_id", "op", "args", "status", "attempts"],
                          rows=rows)
        if name == "citus_stat_activity":
            return Result(columns=["global_pid", "state", "elapsed_s", "query"],
                          rows=self.activity.rows_view())
        if name == "citus_locks":
            return Result(columns=["resource", "session", "mode", "granted"],
                          rows=self.locks.lock_rows())
        if name == "citus_lock_waits":
            graph = self.locks.wait_graph()
            return Result(columns=["waiting_session", "blocking_session"],
                          rows=[(w, b) for w, bs in graph.items() for b in sorted(bs)])
        if name == "citus_shards":
            rows = []
            for t in self.catalog.tables.values():
                for s in t.shards:
                    for node in s.placements:
                        rows.append((t.name, s.shard_id, t.method, t.colocation_id,
                                     node, s.hash_min, s.hash_max))
            return Result(columns=["table_name", "shardid", "citus_table_type",
                                   "colocation_id", "nodename", "shardminvalue",
                                   "shardmaxvalue"], rows=rows)
        if name == "citus_tables":
            from citus_tpu.catalog.stats import table_row_count
            rows = []
            for t in self.catalog.tables.values():
                rows.append((t.name, t.method, t.dist_column, t.colocation_id,
                             self._table_size(t.name), t.shard_count,
                             table_row_count(self.catalog, t)))
            return Result(columns=["table_name", "citus_table_type",
                                   "distribution_column", "colocation_id",
                                   "table_size", "shard_count", "row_count"],
                          rows=rows)
        if name == "undistribute_table":
            from citus_tpu.operations.alter_table import undistribute_table
            undistribute_table(self.catalog, args[0], txlog=self.txlog)
            self._plan_cache.clear()
            return Result(columns=[name], rows=[(None,)])
        if name == "alter_distributed_table":
            from citus_tpu.operations.alter_table import alter_distributed_table
            kw = {}
            if len(args) > 1:
                kw["shard_count"] = int(args[1])
            if len(args) > 2:
                kw["distribution_column"] = str(args[2])
            alter_distributed_table(self.catalog, args[0], txlog=self.txlog, **kw)
            self._plan_cache.clear()
            return Result(columns=[name], rows=[(None,)])
        if name == "citus_get_node_clock":
            return Result(columns=["citus_get_node_clock"],
                          rows=[(self.clock.now(),)])
        if name == "citus_get_transaction_clock":
            return Result(columns=["citus_get_transaction_clock"],
                          rows=[(self.clock.transaction_clock(),)])
        if name == "citus_create_restore_point":
            from citus_tpu.operations.restore import create_restore_point
            create_restore_point(self.catalog, str(args[0]))
            return Result(columns=["citus_create_restore_point"], rows=[(str(args[0]),)])
        if name == "citus_list_restore_points":
            from citus_tpu.operations.restore import list_restore_points
            return Result(columns=["name", "created_at"],
                          rows=list_restore_points(self.catalog))
        if name == "nextval":
            return Result(columns=["nextval"],
                          rows=[(self.catalog.nextval(str(args[0])),)])
        if name == "currval":
            return Result(columns=["currval"],
                          rows=[(self.catalog.currval(str(args[0])),)])
        if name == "setval":
            v = self.catalog.setval(str(args[0]), int(args[1]))
            return Result(columns=["setval"], rows=[(v,)])
        if name == "citus_cdc_events":
            # consumer API: changes for a table after an LSN (reference:
            # the decoder stream a subscriber reads)
            table = str(args[0])
            from_lsn = int(args[1]) if len(args) > 1 else 0
            rows = [(e["lsn"], e["op"], e.get("count"),
                     json.dumps(e.get("rows")) if e.get("rows") else None)
                    for e in self.cdc.events(table, from_lsn)]
            return Result(columns=["lsn", "op", "count", "rows"], rows=rows)
        if name == "citus_roles":
            return Result(columns=["role_name"],
                          rows=[(r,) for r in sorted(self.catalog.roles)])
        if name == "citus_grants":
            rows = []
            for tbl, by_role in sorted(self.catalog.grants.items()):
                for r, privs in sorted(by_role.items()):
                    rows.append((tbl, r, ",".join(privs)))
            return Result(columns=["table_name", "role_name", "privileges"],
                          rows=rows)
        if name == "get_shard_id_for_distribution_column":
            from citus_tpu.catalog.hashing import hash_int64_scalar, shard_index_for_hash
            import numpy as _np
            t2 = self.catalog.table(str(args[0]))
            if not t2.is_distributed:
                return Result(columns=[name], rows=[(t2.shards[0].shard_id,)])
            h = hash_int64_scalar(int(args[1]))
            si = int(shard_index_for_hash(_np.array([h], _np.int32),
                                          t2.shard_count)[0])
            return Result(columns=[name], rows=[(t2.shards[si].shard_id,)])
        if name in ("citus_relation_size", "citus_total_relation_size"):
            return Result(columns=[name],
                          rows=[(self._table_size(str(args[0])),)])
        if name == "citus_disable_node":
            nid = int(args[0])
            if nid not in self.catalog.nodes:
                raise CatalogError(f"node {nid} does not exist")
            self.catalog.nodes[nid].is_active = False
            self.catalog.ddl_epoch += 1
            self.catalog.commit()
            self._plan_cache.clear()
            return Result(columns=[name], rows=[(None,)])
        if name == "citus_activate_node":
            nid = int(args[0])
            if nid not in self.catalog.nodes:
                raise CatalogError(f"node {nid} does not exist")
            self.catalog.nodes[nid].is_active = True
            self.catalog.ddl_epoch += 1
            self.catalog.commit()
            self._plan_cache.clear()
            return Result(columns=[name], rows=[(nid,)])
        if name == "citus_get_active_worker_nodes":
            return Result(columns=["node_id"],
                          rows=[(n,) for n in self.catalog.active_node_ids()])
        if name == "citus_version":
            from citus_tpu.version import __version__ as _v
            return Result(columns=["citus_version"],
                          rows=[(f"citus_tpu {_v} (capability parity target: "
                                 "Citus 15.0devel)",)])
        if name == "citus_dist_stat_activity":
            return Result(columns=["global_pid", "state", "elapsed_s", "query"],
                          rows=self.activity.rows_view())
        if name == "citus_types":
            return Result(columns=["type_name", "labels"],
                          rows=[(n, ",".join(ls)) for n, ls in
                                sorted(self.catalog.types.items())])
        if name == "citus_policies":
            rows = []
            for tbl in sorted(self.catalog.policies):
                for p in self.catalog.policies[tbl]:
                    rows.append((tbl, p["name"], p["cmd"],
                                 ",".join(p["roles"]), p.get("using"),
                                 p.get("check")))
            return Result(columns=["table_name", "policy_name", "cmd",
                                   "roles", "using_expr", "check_expr"],
                          rows=rows)
        if name == "citus_triggers":
            return Result(
                columns=["trigger_name", "table_name", "event", "function"],
                rows=[(n, t["table"], t["event"], t["function"])
                      for n, t in sorted(self.catalog.triggers.items())])
        if name == "citus_text_search_configs":
            return Result(
                columns=["config_name", "parser"],
                rows=[(n, c.get("parser", "default"))
                      for n, c in sorted(self.catalog.ts_configs.items())])
        if name == "citus_views":
            return Result(columns=["view_name", "definition"],
                          rows=sorted(self.catalog.views.items()))
        if name == "citus_sequences":
            rows = [(n, s["value"], s["increment"], s["start"])
                    for n, s in sorted(self.catalog.sequences.items())]
            return Result(columns=["sequence_name", "next_block_start",
                                   "increment", "start"], rows=rows)
        if name == "recover_prepared_transactions":
            from citus_tpu.transaction.recovery import recover_transactions
            st = recover_transactions(self.catalog, self.txlog,
                                      peer_inflight=self._peer_inflight())
            return Result(columns=["recover_prepared_transactions"],
                          rows=[(st["rolled_forward"] + st["rolled_back"],)])
        if name == "run_command_on_workers":
            # reference: operations/citus_tools.c run_command_on_workers —
            # one row per node.  Nodes here share one engine, so the
            # command runs ONCE and the result row replicates per node
            # (running it N times would also repeat side effects)
            try:
                r = self.execute(str(args[0]))
                cell = r.rows[0][0] if r.rows and r.rows[0] else ""
                ok, res = True, str(cell)
            except Exception as exc:
                ok, res = False, str(exc)
            rows = [(nid, ok, res)
                    for nid in sorted(self.catalog.active_node_ids())]
            return Result(columns=["nodeid", "success", "result"], rows=rows)
        if name in ("run_command_on_shards", "run_command_on_placements"):
            return self._run_command_on_shards(
                str(args[0]), str(args[1]),
                per_placement=(name == "run_command_on_placements"))
        if name == "master_get_table_ddl_events":
            return Result(columns=["master_get_table_ddl_events"],
                          rows=[(d,) for d in self._table_ddl(str(args[0]))])
        if name == "citus_backend_gpid":
            import threading as _threading
            return Result(columns=["citus_backend_gpid"],
                          rows=[(_threading.get_ident(),)])
        if name == "citus_coordinator_nodeid":
            nids = sorted(self.catalog.active_node_ids())
            return Result(columns=["citus_coordinator_nodeid"],
                          rows=[(nids[0] if nids else 0,)])
        raise UnsupportedFeatureError(f"utility {name}() not supported yet")

    def _run_command_on_shards(self, table_name: str, command: str,
                               per_placement: bool = False) -> Result:
        """reference: citus_tools.c run_command_on_shards/_placements —
        the %s placeholder becomes the shard; here the command is a
        SELECT template executed with the plan restricted to one shard
        (the shard-suffix-name trick has no meaning without SQL-visible
        shard relations)."""
        import dataclasses as _dc

        from citus_tpu.planner.physical import plan_select
        t = self.catalog.table(table_name)
        sql = command.replace("%s", table_name)
        stmt = parse_sql(sql)[0]
        if not isinstance(stmt, A.Select):
            raise UnsupportedFeatureError(
                "run_command_on_shards supports SELECT commands")
        if not (isinstance(stmt.from_, A.TableRef)
                and stmt.from_.name == t.name):
            raise AnalysisError(
                "run_command_on_shards command must read the named table "
                "(use %s as the relation)")
        bound = bind_select(self.catalog, stmt)
        plan = plan_select(self.catalog, bound,
                           direct_limit=self.settings.planner.direct_gid_limit)
        rows = []
        # one row per shard of the table (reference behavior), even when
        # the command's WHERE clause would prune some shards
        for si in range(len(t.shards)):
            shard = t.shards[si]
            targets = shard.placements if per_placement else [None]
            for node in targets:
                try:
                    sp = _dc.replace(plan, shard_indexes=[si])
                    r = execute_select(self.catalog, bound, self.settings,
                                       plan=sp)
                    cell = r.rows[0][0] if r.rows and r.rows[0] else ""
                    row = (shard.shard_id, True, str(cell))
                except Exception as exc:
                    row = (shard.shard_id, False, str(exc))
                if per_placement:
                    row = (row[0], node) + row[1:]
                rows.append(row)
        cols = ["shardid", "nodeid", "success", "result"] if per_placement \
            else ["shardid", "success", "result"]
        return Result(columns=cols, rows=rows)

    def _table_ddl(self, name: str) -> list[str]:
        """Reconstruct the DDL statements that recreate a table
        (reference: master_get_table_ddl_events,
        operations/node_protocol.c)."""
        t = self.catalog.table(name)
        sql_names = {"bool": "boolean", "int16": "smallint", "int32": "int",
                     "int64": "bigint", "float32": "real",
                     "float64": "double", "date": "date",
                     "timestamp": "timestamp", "text": "text"}
        cols = []
        for c in t.schema:
            enum_t = self.catalog.enum_columns.get(f"{name}.{c.name}")
            tn = enum_t if enum_t else sql_names.get(c.type.kind, str(c.type))
            if c.type.is_decimal:
                tn = str(c.type)  # decimal(p,s) spells itself
            cols.append(f"{c.name} {tn}"
                        + (" NOT NULL" if c.not_null else ""))
        for fk in t.foreign_keys:
            action = "" if fk["on_delete"] == "restrict" \
                else f" ON DELETE {fk['on_delete'].upper()}"
            cols.append(
                f"FOREIGN KEY ({', '.join(fk['columns'])}) REFERENCES "
                f"{fk['ref_table']} ({', '.join(fk['ref_columns'])})"
                + action)
        out = [f"CREATE TABLE {name} ({', '.join(cols)})"]
        if t.is_distributed:
            out.append(f"SELECT create_distributed_table('{name}', "
                       f"'{t.dist_column}', {t.shard_count})")
        elif t.is_reference:
            out.append(f"SELECT create_reference_table('{name}')")
        return out

    def _table_size(self, name: str) -> int:
        import os
        t = self.catalog.table(name)
        total = 0
        for shard in t.shards:
            for node in shard.placements:
                d = self.catalog.shard_dir(name, shard.shard_id, node)
                if os.path.isdir(d):
                    total += sum(os.path.getsize(os.path.join(d, f))
                                 for f in os.listdir(d))
        return total

    def profile(self, sql: str, trace_dir: str) -> Result:
        """Execute under the JAX/XLA profiler (the tracing-integration
        analog of SURVEY §5.1); view the trace with TensorBoard or
        xprof."""
        with jax.profiler.trace(trace_dir):
            return self.execute(sql)

    def _execute_explain(self, stmt: A.Explain) -> Result:
        if isinstance(stmt.statement, A.SetOp):
            so = stmt.statement
            lines = [f"Set Operation: {so.op.upper()}{' ALL' if so.all else ''}"]
            for side, sub in (("left", so.left), ("right", so.right)):
                r = self._execute_explain(A.Explain(sub, analyze=stmt.analyze))
                lines.append(f"  -> {side}:")
                lines.extend("     " + row[0] for row in r.rows)
            return Result(columns=["QUERY PLAN"], rows=[(l,) for l in lines])
        if isinstance(stmt.statement, A.Insert) \
                and stmt.statement.select is not None:
            ins = stmt.statement
            t = self.catalog.table(ins.table)
            names = list(ins.columns or t.schema.names)
            strategy = "pull"
            sel = ins.select
            if isinstance(sel, A.Select) and isinstance(sel.from_, A.TableRef) \
                    and not (sel.group_by or sel.having or sel.order_by
                             or sel.limit or sel.distinct):
                try:
                    bound = bind_select(self.catalog, sel)
                    if not bound.has_aggs and len(bound.final_exprs) == len(names):
                        strategy = self._insert_select_strategy(
                            t, bound, list(bound.final_exprs), names)
                except Exception:
                    pass
            lines = [f"Insert into {ins.table} ({', '.join(names)})",
                     f"  Strategy: {strategy}"
                     + {"colocated": "  (per-shard pushdown, no re-hash)",
                        "repartition": "  (array-streaming re-hash)",
                        "pull": "  (coordinator row materialization)"}[strategy]]
            if isinstance(sel, (A.Select, A.SetOp)):
                sub = self._execute_explain(A.Explain(sel, analyze=False))
                lines.append("  -> source:")
                lines.extend("     " + row[0] for row in sub.rows)
            return Result(columns=["QUERY PLAN"], rows=[(l,) for l in lines])
        if not isinstance(stmt.statement, A.Select):
            raise UnsupportedFeatureError(
                "EXPLAIN supports SELECT, set operations, and INSERT..SELECT")
        sel = stmt.statement
        if len(sel.group_by) == 1 and isinstance(sel.group_by[0],
                                                 A.GroupingSetsSpec):
            spec = sel.group_by[0]
            full = max(spec.sets, key=len)
            lines = [f"Grouping Sets: {len(spec.sets)} grouped executions"]
            inner = A.Select(
                [i for i in sel.items
                 if not (isinstance(i.expr, A.FuncCall)
                         and i.expr.name == "grouping")],
                sel.from_, sel.where, list(full))
            sub = self._execute_explain(A.Explain(inner, analyze=stmt.analyze))
            lines.extend("  " + row[0] for row in sub.rows)
            return Result(columns=["QUERY PLAN"], rows=[(l,) for l in lines])
        if isinstance(stmt.statement.from_, A.Join):
            return self._explain_join(stmt)
        sel0 = stmt.statement
        if isinstance(sel0.from_, A.TableRef) \
                and self.catalog.has_table(sel0.from_.name) \
                and self.catalog.table(sel0.from_.name).is_partitioned:
            from citus_tpu.partitioning import prune_partitions
            pt = self.catalog.table(sel0.from_.name)
            parts = self.catalog.partitions_of(pt.name)
            surv = prune_partitions(self.catalog, pt, sel0.where)
            lines = [f"Append on {pt.name} "
                     f"(partitions: {len(surv)}/{len(parts)})"]
            if surv:
                import dataclasses as _dc
                rep = _dc.replace(sel0, from_=A.TableRef(
                    surv[0].name, sel0.from_.alias or pt.name))
                sub = self._execute_explain(A.Explain(rep, analyze=False))
                lines.append(f"  Partitions Shown: One of {len(surv)}")
                lines.extend("  " + r[0] for r in sub.rows)
            return Result(columns=["QUERY PLAN"], rows=[(l,) for l in lines])
        bound = bind_select(self.catalog, stmt.statement)
        from citus_tpu.planner.physical import plan_select
        plan = plan_select(self.catalog, bound,
                           direct_limit=self.settings.planner.direct_gid_limit)
        t = bound.table
        lines = []
        kind = ("Router" if plan.is_router else "Distributed") if t.is_distributed else "Local"
        lines.append(f"{kind} Scan on {t.name} "
                     f"(shards: {len(plan.shard_indexes)}/{t.shard_count})")
        if plan.index_eq is not None:
            icol, ival, iname = plan.index_eq
            if t.schema.column(icol).type.is_text:
                # literal was bound to its dictionary id; show the string
                decoded = self.catalog.decode_strings(t.name, icol, [int(ival)])
                ival = decoded[0] if decoded else ival
            lines.append(f"  Index Lookup: {icol} = {ival!r} using {iname}")
        if plan.intervals:
            lines.append("  Chunk Pruning: " +
                         ", ".join(sorted({c.column for c in plan.intervals})))
        if bound.has_aggs:
            mode = plan.group_mode
            desc = {"scalar": "Global Aggregate",
                    "direct": f"Direct GroupBy (groups: {mode.n_groups}, combine: psum)",
                    "hash_host": "Hash GroupBy (host combine)"}[mode.kind]
            lines.append(f"  Partial Aggregate per shard -> {desc}")
            lines.append(f"    Partials: " + ", ".join(
                f"{op.kind}[{op.dtype}]" for op in plan.partial_ops))
        if stmt.analyze:
            r = execute_select(self.catalog, bound, self.settings)
            lines.append(f"  Rows: {r.rowcount}  Elapsed: {r.explain['elapsed_s']*1000:.2f} ms")
            tasks = r.explain.get("tasks") or []
            if tasks:
                lines.append(f"  Tasks: {len(tasks)}  Tasks Shown: One of {len(tasks)}")
                si, nrows, dt = tasks[0]
                lines.append(f"    -> Task (shard index {si}): {nrows} rows, "
                             f"{dt*1000:.2f} ms device dispatch")
        return Result(columns=["QUERY PLAN"], rows=[(l,) for l in lines])

    def _explain_join(self, stmt: A.Explain) -> Result:
        from citus_tpu.executor.join_executor import execute_join_select
        from citus_tpu.planner.join_planner import bind_join_select
        bj = bind_join_select(self.catalog, stmt.statement)
        lines = [f"Join ({bj.strategy}) over {len(bj.rels)} relations"]
        for s_ in bj.steps:
            keys = ", ".join(f"{l} = {r}" for l, r in
                             zip(s_.left_keys, s_.right_keys)) or "(cross)"
            lines.append(f"  {s_.kind.upper()} JOIN {s_.right_alias} ON {keys}")
        for alias, _t in bj.rels:
            rp = bj.rel_plans[alias]
            f = f" filter: {rp.filter}" if rp.filter is not None else ""
            lines.append(f"  Scan {alias} [{', '.join(rp.columns)}]{f}")
        if bj.has_aggs:
            lines.append(f"  GroupBy keys={len(bj.group_keys)} "
                         f"partials={len(bj.partial_ops)} (host combine)")
        if stmt.analyze:
            r = execute_join_select(self.catalog, bj, self.settings)
            lines.append(f"  Rows: {r.rowcount}  Tasks: {r.explain['tasks']}  "
                         f"Elapsed: {r.explain['elapsed_s']*1000:.2f} ms")
        return Result(columns=["QUERY PLAN"], rows=[(l,) for l in lines])
