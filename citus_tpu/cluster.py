"""Cluster: the public entry point.

One Cluster = one coordinator over a data directory + a logical node set
that maps onto the JAX device mesh at execution time.  SQL goes through
``execute``; the control-plane operations the reference exposes as UDFs
(create_distributed_table, create_reference_table, ...) are available
both as Python methods and through their SQL spellings
(``SELECT create_distributed_table('t','col')``).
"""

from __future__ import annotations

import json
from typing import Any, Iterable, Optional, Sequence

import numpy as np

import jax

from citus_tpu.catalog import Catalog, DistributionMethod
from citus_tpu.config import Settings, current_settings
from citus_tpu.errors import (
    AnalysisError, CatalogError, ExecutionError, TransactionError,
    UnsupportedFeatureError,
)
from citus_tpu.executor import Result, execute_select
from citus_tpu.ingest import TableIngestor, encode_columns, rows_to_columns
from citus_tpu import stats as _stats
from citus_tpu.observability import trace as _trace
from citus_tpu.planner import ast as A
from citus_tpu.planner import parse_sql
from citus_tpu.planner.bind import bind_select
from citus_tpu.schema import Column, Schema
from citus_tpu.types import type_from_sql


def _option_bool(v) -> bool:
    return str(v).lower() in ("true", "1", "on")


def _has_derived(item) -> bool:
    if isinstance(item, (A.SubqueryRef, A.FunctionRef)):
        return True
    if isinstance(item, A.Join):
        return _has_derived(item.left) or _has_derived(item.right)
    return False


def _srf_result(name: str, args, alias) -> "Result":
    """Evaluate a set-returning FROM function to rows (reference:
    PostgreSQL SRFs; only constant arguments are supported since the
    call is unlateral)."""
    vals = [_eval_const(a) for a in args]
    if name == "generate_series":
        if len(vals) not in (2, 3):
            raise AnalysisError(
                "generate_series(start, stop [, step]) expects 2 or 3 "
                "arguments")
        if any(v is None for v in vals):
            # PostgreSQL: a NULL bound yields zero rows
            return Result(columns=[alias or "generate_series"], rows=[])
        import decimal as _dec
        import math as _math
        numeric = False
        for v in vals:
            if isinstance(v, bool) \
                    or not isinstance(v, (int, float, _dec.Decimal)):
                raise AnalysisError(
                    "generate_series requires numeric bounds "
                    f"(got {v!r}); timestamp series are not supported")
            if (isinstance(v, float) and not _math.isfinite(v)) \
                    or (isinstance(v, _dec.Decimal) and not v.is_finite()):
                raise AnalysisError(
                    "generate_series bound cannot be infinity or NaN")
            if not isinstance(v, int):
                # PostgreSQL: any numeric argument makes the whole
                # series numeric (2.0..4.0 -> 2.0, 3.0, 4.0)
                numeric = True
        if numeric:
            # PostgreSQL numeric generate_series(1.1, 4.0, 1.3) ->
            # 1.1, 2.4, 3.7 — exact decimal stepping
            start = _dec.Decimal(str(vals[0]))
            stop = _dec.Decimal(str(vals[1]))
            step = _dec.Decimal(str(vals[2])) if len(vals) > 2 \
                else _dec.Decimal(1)
            if step == 0:
                raise ExecutionError("step size cannot equal zero")
            rows = []
            v = start
            while (v <= stop) if step > 0 else (v >= stop):
                rows.append((v,))
                v += step
            return Result(columns=[alias or "generate_series"], rows=rows)
        start, stop = int(vals[0]), int(vals[1])
        step = int(vals[2]) if len(vals) > 2 else 1
        if step == 0:
            raise ExecutionError("step size cannot equal zero")
        end = stop + (1 if step > 0 else -1)
        rows = [(v,) for v in range(start, end, step)]
        return Result(columns=[alias or "generate_series"], rows=rows)
    if name == "unnest":
        # reference: unnest(anyarray) SRF — one row per element
        if len(vals) != 1:
            raise AnalysisError("unnest(array) expects one argument")
        arr = vals[0]
        if arr is None:
            return Result(columns=[alias or "unnest"], rows=[])
        if not isinstance(arr, (list, tuple)):
            raise AnalysisError(f"unnest requires an array (got {arr!r})")
        return Result(columns=[alias or "unnest"],
                      rows=[(v,) for v in arr])
    raise UnsupportedFeatureError(
        f"set-returning function {name}() is not supported in FROM")


def _max_param_index(stmt) -> int:
    """Highest $N referenced anywhere in a SELECT (0 when none)."""
    mx = 0

    def visit(e):
        nonlocal mx
        if isinstance(e, A.Param):
            mx = max(mx, e.index)
        elif isinstance(e, A.BinOp):
            visit(e.left), visit(e.right)
        elif isinstance(e, A.UnOp):
            visit(e.operand)
        elif isinstance(e, A.Between):
            visit(e.expr), visit(e.lo), visit(e.hi)
        elif isinstance(e, A.InList):
            visit(e.expr)
            for it in e.items:
                visit(it)
        elif isinstance(e, (A.IsNull, A.Cast)):
            visit(e.expr)
        elif isinstance(e, A.CaseExpr):
            for c, v in e.whens:
                visit(c), visit(v)
            if e.else_ is not None:
                visit(e.else_)
        elif isinstance(e, A.FuncCall):
            for a in e.args:
                visit(a)

    for item in stmt.items:
        visit(item.expr)
    visit(stmt.where)
    visit(stmt.having)
    for g in stmt.group_by:
        visit(g)
    for o in stmt.order_by:
        visit(o.expr)
    return mx


def _eval_const(e):
    """Evaluate a literal-only expression tree to a Python value (SELECT
    without FROM); NULL-propagating arithmetic/comparisons."""
    import decimal as _dec
    if isinstance(e, A.Literal):
        return e.value
    if isinstance(e, A.UnOp):
        v = _eval_const(e.operand)
        if e.op == "-":
            return None if v is None else -v
        return None if v is None else (not v)
    if isinstance(e, A.BinOp):
        if isinstance(e.left, A.IntervalLiteral) \
                or isinstance(e.right, A.IntervalLiteral):
            import datetime as _dt

            from citus_tpu.planner.bound import py_add_interval
            if e.op not in ("+", "-"):
                raise UnsupportedFeatureError(
                    f"operator {e.op} is not defined for intervals")
            ivl = e.right if isinstance(e.right, A.IntervalLiteral) \
                else e.left
            other = e.left if ivl is e.right else e.right
            if ivl is e.left and e.op != "+":
                raise UnsupportedFeatureError(
                    "interval arithmetic supports date/timestamp ± interval")
            v = _eval_const(other)
            if v is None:
                return None
            if not isinstance(v, (_dt.date, _dt.datetime)):
                raise AnalysisError(
                    "cannot add an interval to a non-date value "
                    "(use a typed literal: date '...')")
            sign = 1 if e.op == "+" else -1
            return py_add_interval(v, sign * ivl.months, sign * ivl.days,
                                   sign * ivl.micros)
        l, r = _eval_const(e.left), _eval_const(e.right)
        if e.op == "and":
            if l is False or r is False:
                return False
            return None if (l is None or r is None) else True
        if e.op == "or":
            if l is True or r is True:
                return True
            return None if (l is None or r is None) else False
        if l is None or r is None:
            return None
        if isinstance(l, (int, float)) and isinstance(r, _dec.Decimal):
            l = _dec.Decimal(str(l))
        if isinstance(r, (int, float)) and isinstance(l, _dec.Decimal):
            r = _dec.Decimal(str(r))
        ops = {"+": lambda: l + r, "-": lambda: l - r, "*": lambda: l * r,
               "/": lambda: l / r if r else None,
               "%": lambda: l % r if r else None,
               "=": lambda: l == r, "<>": lambda: l != r,
               "<": lambda: l < r, "<=": lambda: l <= r,
               ">": lambda: l > r, ">=": lambda: l >= r}
        if e.op not in ops:
            raise UnsupportedFeatureError(f"operator {e.op} without FROM")
        return ops[e.op]()
    if isinstance(e, A.IsNull):
        v = _eval_const(e.expr)
        return (v is not None) if e.negated else (v is None)
    if isinstance(e, A.Cast):
        v = _eval_const(e.expr)
        if v is None:
            return None
        t = type_from_sql(e.type_name, list(e.type_args) or None)
        try:
            return t.from_physical(t.to_physical(v))
        except (ValueError, TypeError):
            raise AnalysisError(
                f"invalid input syntax for type {e.type_name}: {v!r}")
    if isinstance(e, A.CaseExpr):
        for c, v in e.whens:
            if _eval_const(c) is True:
                return _eval_const(v)
        return _eval_const(e.else_) if e.else_ is not None else None
    if isinstance(e, A.FuncCall) and e.name == "coalesce":
        for a in e.args:
            v = _eval_const(a)
            if v is not None:
                return v
        return None
    if isinstance(e, A.FuncCall):
        v = _eval_const_func(e)
        if v is not NotImplemented:
            return v
    raise UnsupportedFeatureError(
        f"cannot evaluate {type(e).__name__} without a FROM clause")


def _eval_const_func(e):
    """Constant evaluation of the scalar math/string surface (SELECT
    without FROM); NotImplemented when the function is unknown."""
    import decimal as _dec
    import math as _math
    args = [_eval_const(a) for a in e.args]
    name = e.name
    if name == "pi":
        return _math.pi
    if name in ("current_date", "current_timestamp", "now"):
        import datetime as _dt
        return _dt.date.today() if name == "current_date" \
            else _dt.datetime.now()
    if name == "nullif":
        # NULLIF is not strict: it returns the first argument unless the
        # comparison is true, so nullif(5, NULL) = 5 (PostgreSQL).
        return None if args[0] == args[1] else args[0]
    if any(a is None for a in args):
        # all these functions are strict (NULL in -> NULL out)
        known = {"abs", "floor", "ceil", "ceiling", "round", "trunc",
                 "sign", "sqrt", "exp", "ln", "log", "log10", "log2",
                 "power", "pow", "mod", "degrees", "radians", "greatest",
                 "least", "upper", "lower", "length", "char_length",
                 "strpos", "reverse", "initcap", "trim",
                 "btrim", "ltrim", "rtrim", "replace", "left", "right"}
        if name in ("greatest", "least"):
            vals = [a for a in args if a is not None]
            if not vals:
                return None
            return max(vals) if name == "greatest" else min(vals)
        return None if name in known else NotImplemented
    try:
        if name == "abs":
            return abs(args[0])
        if name in ("floor", "ceil", "ceiling"):
            f = _math.floor if name == "floor" else _math.ceil
            v = f(args[0])
            return _dec.Decimal(v) if isinstance(args[0], _dec.Decimal) \
                else (float(v) if isinstance(args[0], float) else v)
        if name == "round":
            nd = int(args[1]) if len(args) > 1 else 0
            if isinstance(args[0], float):
                # round(double precision) ties to even in PostgreSQL
                return float(round(args[0], nd))
            d = args[0] if isinstance(args[0], _dec.Decimal) \
                else _dec.Decimal(str(args[0]))
            return d.quantize(_dec.Decimal(1).scaleb(-nd),
                              rounding=_dec.ROUND_HALF_UP)
        if name == "trunc":
            nd = int(args[1]) if len(args) > 1 else 0
            d = args[0] if isinstance(args[0], _dec.Decimal) \
                else _dec.Decimal(str(args[0]))
            q = d.quantize(_dec.Decimal(1).scaleb(-nd),
                           rounding=_dec.ROUND_DOWN)
            return float(q) if isinstance(args[0], float) else q
        if name == "sign":
            v = args[0]
            return (v > 0) - (v < 0)
        if name == "sqrt":
            return _math.sqrt(args[0]) if args[0] >= 0 else None
        if name == "exp":
            return _math.exp(args[0])
        if name in ("ln", "log", "log10", "log2"):
            if name == "log" and len(args) == 2:
                return (_math.log(args[1]) / _math.log(args[0])
                        if args[1] > 0 and args[0] > 0 else None)
            if args[0] <= 0:
                return None
            return _math.log(args[0]) if name == "ln" else (
                _math.log2(args[0]) if name == "log2"
                else _math.log10(args[0]))
        if name in ("power", "pow"):
            return float(args[0]) ** float(args[1])
        if name == "mod":
            a, b = args
            if not b:
                return None
            # SQL mod truncates toward zero; exact integer arithmetic
            # (float division would lose precision past 2^53)
            q = abs(a) // abs(b)
            if (a < 0) != (b < 0):
                q = -q
            return a - q * b
        if name == "degrees":
            return _math.degrees(args[0])
        if name == "radians":
            return _math.radians(args[0])
        if name in ("greatest", "least"):
            return max(args) if name == "greatest" else min(args)
        if args and isinstance(args[0], str):
            s = args[0]
            if name == "upper":
                return s.upper()
            if name == "lower":
                return s.lower()
            if name in ("length", "char_length"):
                return len(s)
            if name == "strpos":
                return s.find(str(args[1])) + 1
            if name == "reverse":
                return s[::-1]
            if name == "initcap":
                return s.title()
            if name in ("trim", "btrim"):
                return s.strip(str(args[1]) if len(args) > 1 else None)
            if name == "ltrim":
                return s.lstrip(str(args[1]) if len(args) > 1 else None)
            if name == "rtrim":
                return s.rstrip(str(args[1]) if len(args) > 1 else None)
            if name == "replace":
                return s.replace(str(args[1]), str(args[2]))
            if name == "left":
                return s[:int(args[1])]
            if name == "right":
                n = int(args[1])
                return s[max(0, len(s) - n):] if n >= 0 else s[-n:]
    except (ValueError, OverflowError, ArithmeticError):
        return None
    return NotImplemented


def _expand_returning_items(t, items, subst=None):
    """Expand a RETURNING list to [(expr, output name)]: * becomes the
    table's columns; substitutions (UPDATE assignments, INSERT row
    values) apply after expansion."""
    expanded = []
    for it in items:
        if isinstance(it.expr, A.Star):
            for n in t.schema.names:
                e = A.ColumnRef(n)
                if subst:
                    e = _replace_exprs(e, subst)
                expanded.append((e, n))
        else:
            e = _replace_exprs(it.expr, subst) if subst else it.expr
            expanded.append((e, it.alias or str(it.expr)))
    return expanded


def _replace_exprs(e, mapping: dict):
    """Structural replacement of whole sub-expressions (used to NULL out
    rolled-up grouping columns inside HAVING)."""
    if e in mapping:
        return mapping[e]
    if isinstance(e, A.BinOp):
        return A.BinOp(e.op, _replace_exprs(e.left, mapping),
                       _replace_exprs(e.right, mapping))
    if isinstance(e, A.UnOp):
        return A.UnOp(e.op, _replace_exprs(e.operand, mapping))
    if isinstance(e, A.Between):
        return A.Between(_replace_exprs(e.expr, mapping),
                         _replace_exprs(e.lo, mapping),
                         _replace_exprs(e.hi, mapping), e.negated)
    if isinstance(e, A.InList):
        return A.InList(_replace_exprs(e.expr, mapping),
                        tuple(_replace_exprs(i, mapping) for i in e.items),
                        e.negated)
    if isinstance(e, A.IsNull):
        return A.IsNull(_replace_exprs(e.expr, mapping), e.negated)
    if isinstance(e, A.Cast):
        return A.Cast(_replace_exprs(e.expr, mapping), e.type_name, e.type_args)
    if isinstance(e, A.CaseExpr):
        return A.CaseExpr(tuple((_replace_exprs(c, mapping),
                                 _replace_exprs(v, mapping))
                                for c, v in e.whens),
                          _replace_exprs(e.else_, mapping)
                          if e.else_ is not None else None)
    if isinstance(e, A.FuncCall):
        import dataclasses
        return dataclasses.replace(
            e, args=tuple(_replace_exprs(a, mapping) for a in e.args),
            agg_order=tuple((_replace_exprs(oe, mapping), asc)
                            for oe, asc in e.agg_order),
            filter=_replace_exprs(e.filter, mapping)
            if e.filter is not None else None)
    return e


def _subst_args(e, sub: dict):
    """Replace bare ColumnRefs naming function parameters with the call
    arguments (used by SQL function inlining)."""
    if isinstance(e, A.ColumnRef) and e.table is None and e.name in sub:
        return sub[e.name]
    if isinstance(e, A.BinOp):
        return A.BinOp(e.op, _subst_args(e.left, sub), _subst_args(e.right, sub))
    if isinstance(e, A.UnOp):
        return A.UnOp(e.op, _subst_args(e.operand, sub))
    if isinstance(e, A.Between):
        return A.Between(_subst_args(e.expr, sub), _subst_args(e.lo, sub),
                         _subst_args(e.hi, sub), e.negated)
    if isinstance(e, A.InList):
        return A.InList(_subst_args(e.expr, sub),
                        tuple(_subst_args(i, sub) for i in e.items), e.negated)
    if isinstance(e, A.IsNull):
        return A.IsNull(_subst_args(e.expr, sub), e.negated)
    if isinstance(e, A.Cast):
        return A.Cast(_subst_args(e.expr, sub), e.type_name, e.type_args)
    if isinstance(e, A.CaseExpr):
        return A.CaseExpr(tuple((_subst_args(c, sub), _subst_args(v, sub))
                                for c, v in e.whens),
                          _subst_args(e.else_, sub) if e.else_ is not None else None)
    if isinstance(e, A.FuncCall):
        import dataclasses
        return dataclasses.replace(
            e, args=tuple(_subst_args(a, sub) for a in e.args),
            agg_order=tuple((_subst_args(oe, sub), asc)
                            for oe, asc in e.agg_order),
            filter=_subst_args(e.filter, sub)
            if e.filter is not None else None)
    return e


def _pylit(v) -> A.Literal:
    """Python value -> literal AST node (for synthesized statements)."""
    import decimal as _dec
    if v is None:
        return A.Literal(None, "null")
    if isinstance(v, bool):
        return A.Literal(v, "bool")
    if isinstance(v, int):
        return A.Literal(v, "int")
    if isinstance(v, float):
        return A.Literal(v, "float")
    if isinstance(v, _dec.Decimal):
        return A.Literal(v, "decimal")
    return A.Literal(str(v), "string")


def _subst_excluded(e, excl: dict):
    """Replace ``excluded.col`` references with the proposed row's
    literal values (ON CONFLICT DO UPDATE, PostgreSQL semantics)."""
    if isinstance(e, A.ColumnRef) and e.table == "excluded":
        return excl.get(e.name, A.Literal(None, "null"))
    if isinstance(e, A.BinOp):
        return A.BinOp(e.op, _subst_excluded(e.left, excl),
                       _subst_excluded(e.right, excl))
    if isinstance(e, A.UnOp):
        return A.UnOp(e.op, _subst_excluded(e.operand, excl))
    if isinstance(e, A.Between):
        return A.Between(_subst_excluded(e.expr, excl),
                         _subst_excluded(e.lo, excl),
                         _subst_excluded(e.hi, excl), e.negated)
    if isinstance(e, A.InList):
        return A.InList(_subst_excluded(e.expr, excl),
                        tuple(_subst_excluded(i, excl) for i in e.items),
                        e.negated)
    if isinstance(e, A.IsNull):
        return A.IsNull(_subst_excluded(e.expr, excl), e.negated)
    if isinstance(e, A.Cast):
        return A.Cast(_subst_excluded(e.expr, excl), e.type_name, e.type_args)
    if isinstance(e, A.CaseExpr):
        return A.CaseExpr(
            tuple((_subst_excluded(c, excl), _subst_excluded(v, excl))
                  for c, v in e.whens),
            _subst_excluded(e.else_, excl) if e.else_ is not None else None)
    if isinstance(e, A.FuncCall):
        import dataclasses
        return dataclasses.replace(
            e, args=tuple(_subst_excluded(a, excl) for a in e.args),
            agg_order=tuple((_subst_excluded(oe, excl), asc)
                            for oe, asc in e.agg_order),
            filter=_subst_excluded(e.filter, excl)
            if e.filter is not None else None)
    return e


def _sort_rows(rows, names, order_by):
    """ORDER BY over materialized rows: items resolve by output position
    or output column name (PostgreSQL's rule for set operations)."""
    for oi in reversed(order_by):
        idx = None
        if isinstance(oi.expr, A.Literal) and isinstance(oi.expr.value, int):
            idx = oi.expr.value - 1
        elif isinstance(oi.expr, A.ColumnRef) and oi.expr.table is None \
                and oi.expr.name in names:
            idx = names.index(oi.expr.name)
        if idx is None or not (0 <= idx < len(names)):
            raise AnalysisError(
                "ORDER BY on a set operation must reference an output "
                "column name or position")
        nf = oi.nulls_first if oi.nulls_first is not None else (not oi.ascending)
        nulls = [x for x in rows if x[idx] is None]
        vals = [x for x in rows if x[idx] is not None]
        vals.sort(key=lambda x, j=idx: x[j], reverse=not oi.ascending)
        rows = (nulls + vals) if nf else (vals + nulls)
    return rows


def _limit0(stmt):
    """A zero-row variant of a SELECT-shaped statement (column/type
    probing without scanning)."""
    import dataclasses as _dc
    if isinstance(stmt, (A.Select, A.SetOp)):
        return _dc.replace(stmt, limit=0)
    if isinstance(stmt, A.WithSelect):
        return _dc.replace(stmt, body=_dc.replace(stmt.body, limit=0))
    return stmt


def _from_relations_scope(node) -> set:
    """Relations referenced inside one WITH scope (CTE bodies + body)."""
    inner: set = set()
    for _n, sub in node.ctes:
        inner |= _from_relations(sub)
    inner |= _from_relations(node.body)
    return inner


def _from_relations(s) -> set:
    """Relation names referenced in FROM clauses (incl. joins, derived
    tables, set-op arms) — the self-reference guard for CREATE OR
    REPLACE VIEW."""
    out: set = set()

    def from_item(item):
        if isinstance(item, A.TableRef):
            out.add(item.name)
        elif isinstance(item, A.Join):
            from_item(item.left)
            from_item(item.right)
        elif isinstance(item, A.SubqueryRef):
            walk(item.select)

    def walk(node):
        if isinstance(node, A.SetOp):
            walk(node.left)
            walk(node.right)
        elif isinstance(node, A.WithSelect):
            cte_names = {n for n, _ in node.ctes}
            inner = _from_relations_scope(node)
            out.update(inner - cte_names)
        elif isinstance(node, A.Select) and node.from_ is not None:
            from_item(node.from_)

    walk(s)
    return out


def _infer_column_type(vals):
    """Fallback type inference for intermediate results whose planner
    types are unknown (e.g. window outputs): first non-NULL value wins;
    decimals take the column's max scale."""
    import datetime as _dt
    import decimal as _dec
    from citus_tpu import types as T
    kind = None
    max_scale = 0
    for v in vals:
        if v is None:
            continue
        if isinstance(v, bool):
            return T.BOOL_T
        if isinstance(v, _dec.Decimal):
            kind = "decimal"
            max_scale = max(max_scale, -v.as_tuple().exponent)
        elif isinstance(v, float):
            return T.FLOAT64_T
        elif isinstance(v, int):
            kind = kind or "int"
        elif isinstance(v, str):
            return T.TEXT_T
        elif isinstance(v, _dt.datetime):
            return T.TIMESTAMP_T
        elif isinstance(v, _dt.date):
            return T.DATE_T
        else:
            raise AnalysisError(f"cannot infer a column type from {v!r}")
    if kind == "decimal":
        return T.decimal_t(max(18, max_scale), max(max_scale, 0))
    return T.INT64_T


class Cluster:
    def __init__(self, data_dir: str, *, n_nodes: Optional[int] = None,
                 settings: Optional[Settings] = None,
                 serve_port: Optional[int] = None,
                 coordinator: Optional[tuple] = None,
                 data_port: Optional[int] = None,
                 hosted_nodes: Optional[set] = None,
                 secret: Optional[bytes] = None,
                 data_bind_host: str = "127.0.0.1"):
        """``serve_port``/``coordinator``: control-plane role (metadata
        authority / attached peer).  ``data_port``: serve this process's
        shard placements to peers over the bulk data plane
        (net/data_plane.py; reference: executor/transmit.c file
        transfer).  ``hosted_nodes``: node ids whose placements live in
        THIS data dir — None means all (single-host mode); a set enables
        remote placement reads/writes through node endpoints.
        ``secret``: shared HMAC secret for all RPC (reference:
        pg_dist_authinfo / enable_ssl.c)."""
        if isinstance(secret, str):
            secret = secret.encode()
        self._secret = secret
        self.settings = settings or current_settings()
        self.catalog = Catalog(data_dir)
        if hosted_nodes is not None:
            self.catalog.hosted_nodes = set(hosted_nodes)
        if n_nodes is None:
            n_nodes = 0 if hosted_nodes is not None \
                else max(len(jax.devices()), 1)
        if n_nodes:
            self.catalog.ensure_nodes(n_nodes)
        self.catalog.commit()
        self._data_server = None
        if data_port is not None:
            from citus_tpu.net.data_plane import DataPlaneServer
            self._data_server = DataPlaneServer(self, port=data_port,
                                                secret=secret,
                                                bind_host=data_bind_host)
        if hosted_nodes is not None:
            from citus_tpu.net.data_plane import DataPlaneClient
            self.catalog.remote_data = DataPlaneClient(self.catalog,
                                                       secret=secret)
        # transaction log + recovery on open (reference: 2PC recovery at
        # maintenance-daemon startup, transaction_recovery.c)
        from citus_tpu.transaction import TransactionLog
        from citus_tpu.transaction.recovery import recover_transactions
        self.txlog = TransactionLog(data_dir)
        recover_transactions(self.catalog, self.txlog)
        from citus_tpu.cdc import ChangeDataCapture
        from citus_tpu.utils.clock import CausalClock
        self.clock = CausalClock(data_dir)
        self.cdc = ChangeDataCapture(data_dir, self.settings.enable_change_data_capture)
        # plan cache keyed by SQL text (reference analog: prepared-statement
        # plan caching + local_plan_cache.c); entries are validated per
        # lookup against their table's identity/version and the catalog
        # object-state token — DDL on one table no longer evicts plans
        # for others (planner/plan_cache.py)
        from citus_tpu.executor.kernel_cache import (
            GLOBAL_KERNELS, configure_persistent_cache,
        )
        from citus_tpu.planner.plan_cache import PlanCache
        self._plan_cache = PlanCache()
        GLOBAL_KERNELS.set_capacity(self.settings.executor.kernel_cache_size)
        if self.settings.executor.jit_cache_dir:
            configure_persistent_cache(self.settings.executor.jit_cache_dir)
        self._background_jobs = None
        self._maintenance = None
        # per-thread implicit sessions: {thread ident: (Thread, Session)}
        self._default_sessions: dict = {}
        # observability (citus_stat_* / citus_locks analogs)
        from citus_tpu.executor.executor import GLOBAL_COUNTERS
        from citus_tpu.stats import ActivityTracker, QueryStats, TenantStats
        from citus_tpu.transaction import LockManager
        self.counters = GLOBAL_COUNTERS
        self.query_stats = QueryStats()
        self.tenant_stats = TenantStats()
        self.activity = ActivityTracker()
        self.locks = LockManager()
        # flight recorder: continuous metric history + health events
        # (observability/flight_recorder.py); its sampler only runs
        # while citus.flight_recorder_interval_ms > 0.  The reset hook
        # keeps its rate baselines coherent with counter resets — and is
        # removed in close(): GLOBAL_COUNTERS outlives this handle.
        from citus_tpu.observability.flight_recorder import FlightRecorder
        self.flight_recorder = FlightRecorder(self, data_dir)
        self.counters.add_reset_hook(self.flight_recorder.reset_baselines)
        self.flight_recorder.apply()
        # per-placement load attribution re-zeros with the counters so
        # the ledger-balance invariant survives stat resets
        from citus_tpu.observability.load_attribution import (
            GLOBAL_ATTRIBUTION,
        )
        self.counters.add_reset_hook(GLOBAL_ATTRIBUTION.reset)
        # autopilot decision loop (services/autopilot.py): evaluated as
        # a maintenance duty, gated on citus.autopilot (default off)
        from citus_tpu.services.autopilot import Autopilot
        self.autopilot = Autopilot(self)
        # continuous aggregation (rollup/manager.py): the CDC-fed
        # incremental refresh loop only runs while
        # citus.rollup_refresh_interval_ms > 0
        from citus_tpu.rollup import RollupManager
        self.rollup_manager = RollupManager(self)
        self.rollup_manager.apply()
        # thread id -> role active in that thread's execute() call
        self._exec_roles: dict[int, Optional[str]] = {}
        # control plane (reference: metadata sync + 2PC votes over libpq;
        # here an RPC skeleton — net/control_plane.py).  serve_port=N
        # makes this coordinator the metadata authority; coordinator=
        # (host, port) joins one.  Without either, multi-coordinator
        # invalidation falls back to catalog-file mtime polling.
        self._catalog_dirty = False
        self._control = None
        if serve_port is not None or coordinator is not None:
            from citus_tpu.net.control_plane import ControlPlane
            self._control = ControlPlane(self, serve_port=serve_port,
                                         coordinator=coordinator,
                                         secret=secret)
            # catalog commits serialize through the authority's DDL
            # lease and ship the document over RPC (push_catalog)
            self.catalog.commit_transport = self._control
            # placement-mirror sync elision trusts the data_changed
            # invalidation stream only while it is attached; the probe
            # is re-evaluated on every sync (net/data_plane.py)
            if self.catalog.remote_data is not None:
                self.catalog.remote_data.invalidation_fresh = (
                    lambda: self._control is not None
                    and self._control.connected)
        self.catalog.on_commit = self._on_catalog_commit
        # metadata sync engine (metadata/sync.py): per-object
        # pull-on-mismatch convergence against the authority; the
        # interval loop only runs while attached and
        # citus.metadata_sync_interval_ms > 0
        from citus_tpu.metadata import MetadataSync, hydrate_tenant_registry
        self.metadata_sync = MetadataSync(self)
        self.metadata_sync.apply()
        # mirror the catalog-persisted tenant control plane into the
        # process-local registry, so this coordinator admits identically
        # to every other holder of the same document from statement one
        hydrate_tenant_registry(self.catalog)
        # mtime-poll baseline: our own open-time commit; anything newer
        # is a foreign change (avoids missing commits that land between
        # construction and the first execute)
        self._catalog_mtime = getattr(self.catalog, "self_mtime", None)
        # the maintenance daemon starts with the cluster (reference: the
        # per-database daemon starts with the database, maintenanced.c:138)
        # — opt out via settings.start_maintenance_daemon for embedded
        # uses that drive run_once() themselves
        if self.settings.start_maintenance_daemon:
            self.maintenance  # noqa: B018 — property constructs + starts

    def _peer_inflight(self) -> set:
        if self._control is not None:
            return self._control.peer_inflight_xids()
        return set()

    def _gxid_outcome(self, gxid: str):
        """Resolve a cross-host branch against the authority's outcome
        store ('commit'/'abort'/None while undecided or unreachable)."""
        if self._control is not None:
            return self._control.txn_outcome(gxid)
        return None

    def _on_catalog_commit(self) -> None:
        if self._control is not None:
            self._control.publish_catalog_change()

    def _on_foreign_catalog_applied(self) -> None:
        """A pushed catalog document was just stored into our live
        catalog (authority side): drop cached plans keyed on the old
        metadata and re-mirror the replicated tenant sections."""
        self._plan_cache.clear()
        from citus_tpu.metadata import hydrate_tenant_registry
        hydrate_tenant_registry(self.catalog)

    @property
    def control_port(self) -> Optional[int]:
        if self._control is not None and self._control.server is not None:
            return self._control.server.port
        return None

    @property
    def background_jobs(self):
        """Lazy background task runner (reference: background_jobs.c)."""
        if self._background_jobs is None:
            from citus_tpu.operations import move_shard_placement
            from citus_tpu.services import BackgroundJobRunner
            r = BackgroundJobRunner(self.catalog)
            r.register("move_shard", lambda shard_id, source, target:
                       move_shard_placement(self.catalog, shard_id, source, target,
                                            lock_manager=self.locks,
                                            settings=self.settings))
            r.start()
            self._background_jobs = r
        return self._background_jobs

    @property
    def maintenance(self):
        """Lazy maintenance daemon (reference: maintenanced.c)."""
        if self._maintenance is None:
            from citus_tpu.services import MaintenanceDaemon
            from citus_tpu.transaction.recovery import recover_transactions
            d = MaintenanceDaemon(self.catalog)
            # 2PC recovery duty (reference: Recover2PCInterval, default 60 s)
            d.register("transaction_recovery",
                       lambda: recover_transactions(
                           self.catalog, self.txlog,
                           peer_inflight=self._peer_inflight(),
                           gxid_outcome=self._gxid_outcome),
                       interval_s=60.0)
            if self._data_server is not None:
                # abandoned cross-host branches must resolve (and drop
                # their write locks) even if no further RPC arrives
                d.register("branch_expiry",
                           self._data_server.expire_branches,
                           interval_s=30.0)
            # global deadlock detection (reference:
            # CheckForDistributedDeadlocks every 2 s,
            # distributed_deadlock_detection.c:105)
            from citus_tpu.transaction.global_deadlock import run_detection
            # priority: a due detection pass runs before any other due
            # duty in the same tick — under load (an autopilot move, a
            # slow cleanup) victim selection must not wait a tick out
            d.register("deadlock_detection",
                       lambda: run_detection(self),
                       interval_s=lambda:
                       self.settings.deadlock_detection_interval_s,
                       priority=10)
            # autopilot decision loop; the duty itself checks the mode
            # GUC every tick, so SET citus.autopilot takes effect on a
            # running daemon without re-registration
            d.register("autopilot", self.autopilot.duty,
                       interval_s=lambda:
                       self.settings.autopilot.interval_s)
            if self._control is not None:
                # authority health / lease-based promotion (reference:
                # node_promotion.c; HA via external failover managers in
                # the reference, built-in here)
                d.register("authority_watch",
                           lambda: self._control.ensure_authority(),
                           interval_s=lambda:
                           self.settings.authority_watch_interval_s)
            d.start()
            self._maintenance = d
        return self._maintenance

    def close(self) -> None:
        # open transactions on the per-thread default sessions roll back
        # (connection-close semantics)
        for _owner, ds in list(getattr(self, "_default_sessions", {}).values()):
            if ds.txn is not None:
                self._rollback_txn(ds)
        if self._background_jobs is not None:
            self._background_jobs.stop()
        if self._maintenance is not None:
            self._maintenance.stop()
        self.rollup_manager.stop()
        self.metadata_sync.stop()
        # sampler joined before the servers drop; the reset hook must
        # not outlive this handle (GLOBAL_COUNTERS is process-global)
        self.flight_recorder.stop()
        self.counters.remove_reset_hook(self.flight_recorder.reset_baselines)
        from citus_tpu.observability.load_attribution import (
            GLOBAL_ATTRIBUTION,
        )
        self.counters.remove_reset_hook(GLOBAL_ATTRIBUTION.reset)
        if self._control is not None:
            self._control.close()
        if self._data_server is not None:
            self._data_server.stop()
        if self.catalog.remote_data is not None:
            self.catalog.remote_data.close()
        # release the transaction-log owner marker: our undecided
        # transactions become recoverable by other coordinators
        self.txlog.close()

    # ------------------------------------------------ cross-host topology
    @property
    def data_port(self) -> Optional[int]:
        """Port of this coordinator's bulk data-plane server."""
        return self._data_server.port if self._data_server else None

    def register_node(self, host: str = "127.0.0.1") -> int:
        """Join the cluster as a shard-hosting worker: add a node whose
        placements live in THIS coordinator's data dir, advertising our
        data-plane endpoint so peers can read/write them over the wire
        (reference: citus_add_node(nodename, nodeport) +
        metadata/node_metadata.c ActivateNode)."""
        if self._data_server is None:
            raise AnalysisError(
                "register_node requires data_port= (no data-plane server)")
        from citus_tpu.catalog.catalog import NodeMeta
        # adopt the authority's current node map BEFORE allocating an id
        # (an attached coordinator's local file lags the authority)
        self._reload_catalog()
        nid = max(self.catalog.nodes, default=-1) + 1
        self.catalog.nodes[nid] = NodeMeta(nid, True, host,
                                           self._data_server.port)
        if self.catalog.hosted_nodes is None:
            self.catalog.hosted_nodes = set()
        self.catalog.hosted_nodes.add(nid)
        self.catalog.ddl_epoch += 1
        self.catalog.commit()
        return nid

    def _ingest_local_batch(self, table_name: str, values: dict,
                            validity: dict) -> int:
        """Data-plane server entry: write a physical-encoded batch whose
        rows hash to shards hosted HERE (the receiving half of a
        cross-host COPY; reference: the worker side of per-shard COPY
        streams).  Runs this coordinator's own 2PC."""
        self._maybe_reload_catalog()
        t = self.catalog.table(table_name)
        from citus_tpu.transaction.locks import SHARED
        with self._write_lock(t, SHARED):
            t = self.catalog.table(table_name)
            ing = TableIngestor(self.catalog, t, txlog=self.txlog)
            try:
                ing.append(values, validity)
            except BaseException:
                ing.abort()
                raise
            ing.finish()
        n = len(next(iter(values.values()))) if values else 0
        self.counters.bump("rows_ingested_remote", n)
        return n

    def _write_lock(self, table_meta, mode: str):
        """Serialize writers on a table's colocation group (the analog of
        LockShardResource / SerializeNonCommutativeWrites,
        utils/resource_lock.c): EXCLUSIVE for UPDATE/DELETE/MERGE/
        TRUNCATE/VACUUM (their scan→bitmap→re-insert sequences are not
        commutative), SHARED for append-only ingest.  Shard moves/splits
        take EXCLUSIVE on the same resource across their final catch-up
        and metadata flip, so a writer can never commit into a placement
        being retired.  Two-layer (thread LockManager + process flock);
        after acquisition the catalog is refreshed so a writer that
        waited out a foreign mover sees the flipped placements."""
        import contextlib

        @contextlib.contextmanager
        def _ctx():
            from citus_tpu.storage.overlay import current_overlay
            txn = current_overlay()
            if txn is not None:
                # inside BEGIN..COMMIT: two-phase locking — acquire into
                # the transaction and retain until COMMIT/ROLLBACK
                # (reference holds shard locks to transaction end)
                txn.hold_group_lock(self, table_meta, mode)
                yield
                return
            from citus_tpu.transaction.write_locks import group_write_lock
            try:
                with group_write_lock(self.catalog, table_meta, mode,
                                      lock_manager=self.locks,
                                      timeout=self.settings.executor.lock_timeout_s):
                    # force_sync: an RPC invalidation push may not have
                    # arrived yet; a writer that just waited out a mover
                    # must check staleness synchronously before touching
                    # placements
                    self._maybe_reload_catalog(force_sync=True)
                    yield
            finally:
                # every auto-commit write funnels through here: expire
                # placement-mirror elision tokens cluster-wide (spurious
                # on a failed write — costs one RTT, never staleness)
                self._publish_data_changed(table_meta.name)
        return _ctx()

    def _publish_data_changed(self, table_name: str) -> None:
        """A committed write touched ``table_name``: expire our own
        placement-mirror elision tokens (our mirrors of its remote
        placements may now trail their sources) and broadcast the
        data_changed event so every peer coordinator expires theirs."""
        rd = getattr(self.catalog, "remote_data", None)
        if rd is not None:
            rd.note_data_changed(table_name)
        if self._control is not None:
            self._control.publish_data_change(table_name)

    def _maybe_reload_catalog(self, force_sync: bool = False) -> None:
        """Pick up metadata written by other coordinators sharing this
        data dir (the query-from-any-node / MX analog: any process can
        plan and execute once metadata is synced; reference:
        metadata/metadata_sync.c).  With a control plane attached,
        invalidation arrives as an RPC push (syscache-invalidation
        analog); otherwise fall back to catalog-file mtime polling.
        Writes made by THIS process must not trigger a reload:
        concurrent sessions hold references into the live catalog, and
        reloading underneath them (clear + load) is a read-tear race."""
        import os
        if self._control is not None and self._control.connected:
            if self._catalog_dirty:
                self._catalog_dirty = False
                # this statement would have planned against stale
                # metadata had the invalidation not been honored
                self.counters.bump("metadata_stale_reads")
                # incremental first: pull exactly the divergent objects
                # (metadata/sync.py); fall back to the full document
                if not self.metadata_sync.pull_on_mismatch():
                    self._reload_catalog()
                try:
                    self._catalog_mtime = os.path.getmtime(self.catalog._path())
                except OSError:
                    pass
                return
            if not force_sync:
                return
            # fall through to the synchronous mtime check: write paths
            # cannot rely on the asynchronous push having arrived
        p = self.catalog._path()
        try:
            mtime = os.path.getmtime(p)
        except OSError:
            return
        if mtime == getattr(self.catalog, "self_mtime", None):
            self._catalog_mtime = mtime
            return
        if getattr(self, "_catalog_mtime", None) is None:
            self._catalog_mtime = mtime
            return
        if mtime != self._catalog_mtime:
            self._catalog_mtime = mtime
            self._reload_catalog()

    def _reload_catalog(self) -> None:
        # with an authority attached, the catalog document itself comes
        # over RPC (fetch_catalog) — the file is only the fallback
        doc = None
        if self._control is not None and self._control.connected:
            try:
                doc = self._control.fetch_catalog_doc()
            except Exception:
                doc = None
        with self.catalog._lock:
            # swap, never clear-then-refill: load_document reassigns each
            # section dict atomically, so concurrent readers see either
            # the old or the new state — no read-tear window
            self.catalog._dicts = {}
            self.catalog._dict_index = {}
            self.catalog._dict_sig = {}
            import os as _os
            if doc is not None:
                self.catalog.load_document(doc)
            elif _os.path.exists(self.catalog._path()):
                self.catalog._load()
            else:
                self.catalog.tables = {}
                self.catalog.nodes = {}
            self.catalog.ddl_epoch += 1  # invalidate cached plans
        self._plan_cache.clear()
        # replicated tenant sections may have changed with the document
        from citus_tpu.metadata import hydrate_tenant_registry
        hydrate_tenant_registry(self.catalog)

    # ------------------------------------------------------------- DDL
    def create_table(self, name: str, schema: Schema, *, if_not_exists: bool = False,
                     **columnar_opts) -> None:
        if if_not_exists and self.catalog.has_table(name):
            return
        col = self.settings.columnar
        opts = {
            "chunk_row_limit": int(columnar_opts.get("chunk_group_row_limit", col.chunk_group_row_limit)),
            "stripe_row_limit": int(columnar_opts.get("stripe_row_limit", col.stripe_row_limit)),
            "compression": columnar_opts.get("compression", col.compression),
            "compression_level": int(columnar_opts.get("compression_level", col.compression_level)),
        }
        self.catalog.create_table(name, schema, **opts)
        self.catalog.commit()

    def drop_table(self, name: str, *, if_exists: bool = False) -> None:
        if if_exists and not self.catalog.has_table(name):
            return
        from citus_tpu.integrity import forbid_drop_referenced
        forbid_drop_referenced(self.catalog, name)
        t = self.catalog.table(name)
        if t.is_partitioned:
            # PostgreSQL: dropping the parent drops its partitions
            for p in list(self.catalog.partitions_of(name)):
                self.drop_table(p.name)
        # owned serial sequences die with the table (PostgreSQL drops
        # sequences owned by a dropped column); ownership here = the
        # column's default references nextval('<table>_<col>_seq')
        import re as _re
        for col in t.schema:
            m = _re.fullmatch(r"nextval\('([A-Za-z_0-9.]+)'\)",
                              col.default_sql or "")
            if m and m.group(1) == f"{name}_{col.name}_seq" \
                    and m.group(1) in self.catalog.sequences:
                self.catalog.drop_sequence(m.group(1))
        self.catalog.drop_table(name)
        for key in [k for k in self.catalog.enum_columns
                    if k.startswith(name + ".")]:
            del self.catalog.enum_columns[key]
        if self.catalog.policies.pop(name, None) is not None:
            self.catalog.tombstone("policies", name)
        if self.catalog.rls.pop(name, None) is not None:
            self.catalog.tombstone("rls", name)
        for tn in [n for n, t in self.catalog.triggers.items()
                   if t.get("table") == name]:
            del self.catalog.triggers[tn]
            self.catalog.tombstone("triggers", tn)
        for key in [k for k in self.catalog.domain_columns
                    if k.startswith(name + ".")]:
            del self.catalog.domain_columns[key]
            self.catalog.tombstone("domain_columns", key)
        for pub in self.catalog.publications.values():
            tl = pub.get("tables")
            if isinstance(tl, list) and name in tl:
                tl.remove(name)  # PostgreSQL drops the table from pubs
        self.catalog.commit()

    # ------------------------------------------------------- partitioning
    def _internal_txn(self):
        """All-or-nothing wrapper for engine-generated multi-statement
        work (multi-partition writes): inside a user transaction it is
        transparent (that transaction provides atomicity); otherwise it
        opens, stages, and 2PC-commits an internal one, rolling back on
        any failure."""
        import contextlib

        @contextlib.contextmanager
        def _ctx():
            from citus_tpu.storage.overlay import (
                current_overlay, transaction_overlay,
            )
            if current_overlay() is not None:
                yield
                return
            from citus_tpu.transaction.session import OpenTransaction
            s = self.session()
            xid = self.txlog.begin()
            s.txn = OpenTransaction(xid, s.lock_sid)
            s.txn.tombstones_snapshot = {
                k: set(v) for k, v in self.catalog._tombstones.items()}
            try:
                with transaction_overlay(s.txn):
                    yield
            except BaseException:
                self._rollback_txn(s)
                raise
            self._commit_txn(s)
        return _ctx()

    def _create_partition(self, name: str, parent: str, lo_raw, hi_raw,
                          *, if_not_exists: bool = False) -> None:
        """CREATE TABLE name PARTITION OF parent FOR VALUES FROM..TO:
        clone the parent's schema, record physical bounds, inherit the
        parent's distribution (siblings colocate).  Reference:
        PostgreSQL partition DDL distributed per-partition
        (multi_partitioning_utils.c)."""
        from citus_tpu.partitioning import bound_to_physical, check_new_partition
        if if_not_exists and self.catalog.has_table(name):
            return
        pt = self.catalog.table(parent)
        if not pt.is_partitioned:
            raise CatalogError(f'"{parent}" is not partitioned')
        col = pt.schema.column(pt.partition_by["column"])
        lo = bound_to_physical(col.type, lo_raw)
        hi = bound_to_physical(col.type, hi_raw)
        check_new_partition(self.catalog, pt, lo, hi)
        self.catalog.create_table(
            name, pt.schema,
            chunk_row_limit=pt.chunk_row_limit,
            stripe_row_limit=pt.stripe_row_limit,
            compression=pt.compression,
            compression_level=pt.compression_level)
        t = self.catalog.table(name)
        t.partition_of = {"parent": parent, "lo": lo, "hi": hi}
        # constraints declared on the parent apply to every partition
        # (PostgreSQL propagates FK, CHECK, and unique constraints;
        # unique keys were validated at parent creation to include the
        # partition column)
        import json as _json
        t.foreign_keys = _json.loads(_json.dumps(pt.foreign_keys))
        t.check_constraints = _json.loads(
            _json.dumps(pt.check_constraints))
        if pt.method == DistributionMethod.HASH:
            siblings = [p for p in self.catalog.partitions_of(parent)
                        if p.name != name and p.is_distributed]
            self.catalog.distribute_table(
                name, pt.dist_column,
                pt.partition_by.get("shard_count")
                or self.settings.sharding.shard_count,
                self.catalog.active_node_ids(),
                colocate_with=siblings[0].name if siblings else None,
                replication_factor=self.settings.sharding.shard_replication_factor)
        self.catalog.commit()
        for ix in pt.indexes:
            self.create_index(f"{name}_{ix['column']}_key", name,
                              ix["column"], unique=ix.get("unique", False))
        self._plan_cache.clear()

    def _truncate_one(self, name: str) -> None:
        """Truncate one (possibly partitioned) relation; FK validation
        happens at the statement level, list-aware."""
        from citus_tpu.executor.dml import execute_truncate
        from citus_tpu.transaction.locks import EXCLUSIVE
        t = self.catalog.table(name)
        if t.is_partitioned:
            for p in self.catalog.partitions_of(name):
                self._truncate_one(p.name)
            return
        with self._write_lock(t, EXCLUSIVE):
            execute_truncate(self.catalog, self.catalog.table(name))
        self._plan_cache.invalidate_table(name)
        if self._cdc_captures(t.name):
            self.cdc.emit(t.name, "truncate",
                          self.clock.transaction_clock(), force=True)

    def _fanout_partitions(self, stmt, *, aggregate_explain: bool = False
                           ) -> Result:
        """Run a single-table utility statement (TRUNCATE, VACUUM) on
        every partition of the named parent, optionally summing the
        integer explain stats."""
        import dataclasses as _dc
        agg: dict = {}
        for p in self.catalog.partitions_of(stmt.table):
            sub = self._execute_stmt(_dc.replace(stmt, table=p.name))
            if aggregate_explain:
                for k, v in sub.explain.items():
                    agg[k] = agg.get(k, 0) + v
        return Result(columns=[], rows=[], explain=agg)

    def _partition_dml(self, stmt, t) -> Result:
        """UPDATE/DELETE against a partitioned parent: run per surviving
        partition (pruned on the WHERE) and sum the counts."""
        import dataclasses
        from citus_tpu.partitioning import prune_partitions
        if getattr(stmt, "returning", None):
            raise UnsupportedFeatureError(
                "RETURNING on a partitioned parent is not supported")
        if isinstance(stmt, A.Update):
            pcol = t.partition_by["column"]
            if any(c == pcol for c, _ in stmt.assignments):
                raise UnsupportedFeatureError(
                    "updating the partition column (row movement) is "
                    "not supported; DELETE the rows and re-INSERT them "
                    "through the parent so they route to the right "
                    "partition")
        total_key = "updated" if isinstance(stmt, A.Update) else "deleted"
        total = 0
        # atomic across partitions: a later partition's failure must not
        # leave earlier partitions' writes committed
        with self._internal_txn():
            for p in prune_partitions(self.catalog, t, stmt.where):
                sub = dataclasses.replace(stmt, table=p.name)
                r = self._execute_stmt(sub)
                total += r.explain.get(total_key, 0)
        return Result(columns=[], rows=[], explain={total_key: total})

    def _copy_into_partitions(self, t, columns) -> int:
        """Route an ingest batch against a partitioned parent to its
        partitions by range (the multi-level ShardIdForTuple)."""
        from citus_tpu.partitioning import partition_for_rows
        pcol = t.partition_by["column"]
        if pcol not in columns:
            raise AnalysisError(f"missing column {pcol!r} in ingest batch")
        col = t.schema.column(pcol)
        raw = columns[pcol]
        if isinstance(raw, np.ndarray) and raw.dtype != object \
                and raw.dtype.kind in "iuf":
            # mirror encode_columns' numeric fast path exactly (decimal
            # floats scale by 10^scale with ROUND_HALF_UP; integer input
            # is already physical), so routing and storage agree
            if col.type.kind == "decimal" \
                    and np.issubdtype(raw.dtype, np.floating):
                x = raw * float(10 ** col.type.scale)
                phys = np.where(x >= 0, np.floor(x + 0.5),
                                np.ceil(x - 0.5)).astype(np.int64)
            else:
                phys = raw.astype(col.type.storage_dtype)
        else:
            vals = list(raw)
            if any(v is None for v in vals):
                raise AnalysisError(
                    f'no partition of relation "{t.name}" found for row '
                    f"({pcol} is null)")
            phys = np.asarray([col.type.to_physical(v) for v in vals])
        n = 0
        cols_np = {c: (v if isinstance(v, np.ndarray)
                       else np.asarray(v, dtype=object))
                   for c, v in columns.items()}
        routed = partition_for_rows(self.catalog, t, phys)
        # atomic across partitions (a unique violation in the second
        # partition must not leave the first partition's rows behind)
        with self._internal_txn():
            for pname, mask in routed:
                sub = {c: v[mask] for c, v in cols_np.items()}
                n += self.copy_from(pname, columns=sub)
        return n

    def _drop_catalog_object(self, section: str, stmt) -> Result:
        """DROP for the simple metadata-object sections (extension,
        domain, collation, publication, statistics)."""
        store = getattr(self.catalog, section)
        if stmt.name not in store:
            if stmt.if_exists:
                return Result(columns=[], rows=[])
            raise CatalogError(
                f'{section[:-1]} "{stmt.name}" does not exist')
        del store[stmt.name]
        self.catalog.tombstone(section, stmt.name)
        self.catalog.ddl_epoch += 1
        self.catalog.commit()
        return Result(columns=[], rows=[])

    # ----------------------------------------------------------- indexes
    def _find_index(self, name: str):
        """-> (table_meta, index dict) or (None, None)."""
        for t in self.catalog.tables.values():
            for ix in t.indexes:
                if ix["name"] == name:
                    return t, ix
        return None, None

    def _drop_index_segments(self, t, column: str) -> None:
        from citus_tpu.storage.index import drop_segments
        import os as _os
        for shard in t.shards:
            for node in shard.placements:
                d = self.catalog.shard_dir(t.name, shard.shard_id, node)
                if _os.path.isdir(d):
                    drop_segments(d, column)

    def _drop_index_segments_if_unindexed(self, table_name: str,
                                          column: str) -> None:
        """Deferred (COMMIT-time) segment removal: a same-name index
        recreated later in the transaction must keep its fresh segments;
        a dropped table's removal owns its whole directory."""
        if not self.catalog.has_table(table_name):
            return
        t2 = self.catalog.table(table_name)
        if t2.index_on(column) is None:
            self._drop_index_segments(t2, column)

    def create_index(self, name: str, table: str, column: str, *,
                     unique: bool = False,
                     if_not_exists: bool = False) -> None:
        """CREATE [UNIQUE] INDEX: register the index, validate existing
        data for UNIQUE, and backfill per-stripe segments on every
        placement (reference: commands/index.c DDL propagation +
        columnar_index_build_range_scan, columnar_tableam.c:1444)."""
        from citus_tpu.storage.index import backfill_index
        from citus_tpu.transaction.locks import EXCLUSIVE
        existing_t, existing = self._find_index(name)
        if existing is not None:
            if if_not_exists:
                return
            raise CatalogError(f'index "{name}" already exists')
        t = self.catalog.table(table)
        if t.is_partitioned:
            raise UnsupportedFeatureError(
                "CREATE INDEX on a partitioned parent is not supported; "
                "create the index on each partition")
        t.schema.column(column)  # must exist
        if t.schema.column(column).type.is_float and unique:
            raise UnsupportedFeatureError(
                "UNIQUE indexes over floating-point columns are not "
                "supported (no exact equality)")
        if t.index_on(column) is not None:
            raise CatalogError(
                f'column "{column}" of "{table}" is already indexed')
        ix = {"name": name, "column": column, "unique": bool(unique)}
        # EXCLUSIVE write lock: no ingest may slip between the uniqueness
        # validation / backfill and the catalog flip
        from citus_tpu.storage.overlay import current_overlay
        with self._write_lock(t, EXCLUSIVE):
            if unique:
                from citus_tpu.integrity import validate_unique_backfill
                validate_unique_backfill(self.catalog, t, ix)
            # segments first, catalog second: a backfill failure must
            # leave no in-memory claim of an index that was never built
            backfill_index(self.catalog, t, [column])
            txn = current_overlay()
            if txn is not None:
                # ROLLBACK must remove the backfilled segments (additive
                # files: invisible to peers until the catalog commits)
                txn.on_rollback.append(
                    lambda: self._drop_index_segments(t, column))
            t.indexes.append(ix)
            t.version += 1
            self.catalog.ddl_epoch += 1
            self.catalog.commit()
        self._plan_cache.invalidate_table(t.name)

    def _execute_create_index(self, stmt: A.CreateIndex) -> Result:
        self.create_index(stmt.name, stmt.table, stmt.column,
                          unique=stmt.unique,
                          if_not_exists=stmt.if_not_exists)
        return Result(columns=[], rows=[])

    def _execute_drop_index(self, stmt: A.DropIndex) -> Result:
        t, ix = self._find_index(stmt.name)
        if ix is None:
            if stmt.if_exists:
                return Result(columns=[], rows=[])
            raise CatalogError(f'index "{stmt.name}" does not exist')
        from citus_tpu.storage.overlay import current_overlay
        from citus_tpu.transaction.locks import EXCLUSIVE
        with self._write_lock(t, EXCLUSIVE):
            t.indexes.remove(ix)
            # another index may not share the column (enforced at CREATE)
            txn = current_overlay()
            if txn is not None:
                # segment removal is irreversible: defer to COMMIT
                col = ix["column"]
                tname = t.name
                txn.on_commit.append(
                    lambda: self._drop_index_segments_if_unindexed(tname, col))
            else:
                self._drop_index_segments(t, ix["column"])
            t.version += 1
            self.catalog.ddl_epoch += 1
            self.catalog.commit()
        self._plan_cache.invalidate_table(t.name)
        return Result(columns=[], rows=[])

    def create_distributed_table(self, name: str, dist_column: str,
                                 shard_count: Optional[int] = None,
                                 colocate_with: Optional[str] = None) -> None:
        """reference: create_distributed_table UDF
        (src/backend/distributed/commands/create_distributed_table.c)."""
        t = self.catalog.table(name)
        if t.is_partitioned:
            # distribute every partition (colocated siblings) and record
            # the distribution on the metadata-only parent
            shard_count = shard_count or self.settings.sharding.shard_count
            t.schema.column(dist_column)
            first = None
            for p in self.catalog.partitions_of(name):
                self.create_distributed_table(
                    p.name, dist_column, shard_count,
                    colocate_with=first or colocate_with)
                first = first or p.name
            t.method = DistributionMethod.HASH
            t.dist_column = dist_column
            t.partition_by["shard_count"] = shard_count
            if first is not None:
                t.colocation_id = self.catalog.table(first).colocation_id
            t.version += 1
            self.catalog.commit()
            return
        from citus_tpu.catalog.stats import table_row_count
        if table_row_count(self.catalog, t) > 0:
            raise UnsupportedFeatureError(
                "distributing a non-empty table is not supported yet; "
                "create, distribute, then load")
        shard_count = shard_count or self.settings.sharding.shard_count
        self.catalog.distribute_table(
            name, dist_column, shard_count, self.catalog.active_node_ids(),
            colocate_with=colocate_with,
            replication_factor=self.settings.sharding.shard_replication_factor)
        try:
            from citus_tpu.integrity import validate_fk_distribution
            validate_fk_distribution(self.catalog, name)
        except Exception:
            self.catalog._load()  # roll back the uncommitted distribution
            raise
        self.catalog.commit()

    def create_reference_table(self, name: str) -> None:
        t = self.catalog.table(name)
        from citus_tpu.catalog.stats import table_row_count
        if table_row_count(self.catalog, t) > 0:
            raise UnsupportedFeatureError(
                "converting a non-empty table is not supported yet")
        self.catalog.make_reference_table(name, self.catalog.active_node_ids())
        try:
            from citus_tpu.integrity import validate_fk_distribution
            validate_fk_distribution(self.catalog, name)
        except Exception:
            self.catalog._load()
            raise
        self.catalog.commit()

    # ----------------------------------------------------------- ingest
    def copy_from(self, table_name: str,
                  columns: Optional[dict[str, Sequence[Any]]] = None,
                  rows: Optional[Iterable[Sequence[Any]]] = None,
                  column_names: Optional[list[str]] = None,
                  session=None) -> int:
        """Bulk load (the COPY analog).  Either ``columns`` (dict of
        arrays/lists, fastest) or ``rows`` (iterable of tuples).  Inside
        an open transaction (``session`` with BEGIN, or called from a
        statement of one) the write stages under the transaction's xid
        and commits with it."""
        from citus_tpu.storage.overlay import current_overlay, transaction_overlay
        if session is None:
            # match execute(): a BEGIN issued through cl.execute() opens
            # a transaction on the shared default session, and a COPY
            # issued the same way must join it, not autocommit past it
            session = self._default_session()
        if session.txn is not None and current_overlay() is None:
            if session.txn.failed:
                from citus_tpu.transaction.session import InFailedTransaction
                raise InFailedTransaction(
                    "current transaction is aborted, commands ignored "
                    "until end of transaction block")
            with transaction_overlay(session.txn):
                try:
                    return self.copy_from(table_name, columns=columns,
                                          rows=rows,
                                          column_names=column_names)
                except Exception:
                    session.txn.failed = True
                    raise
        t = self.catalog.table(table_name)
        if (columns is None) == (rows is None):
            raise AnalysisError("provide exactly one of columns= or rows=")
        if rows is not None:
            columns = rows_to_columns(t.schema.names, rows, column_names)
            if column_names is not None:
                # rows_to_columns pads OMITTED columns with None; drop
                # them again so their DEFAULTs apply (a column the user
                # listed keeps its explicit NULLs)
                listed = set(column_names)
                columns = {c: v for c, v in columns.items()
                           if c in listed
                           or not t.schema.column(c).default_sql}
        if t.is_partitioned:
            # two-level routing: range partition first, then hash shard
            # within it (each recursive call re-enters with the same
            # session/transaction context)
            return self._copy_into_partitions(t, columns)
        columns = self._fill_defaults(t, columns)
        self._check_domains(t, columns)
        values, validity = encode_columns(self.catalog, t, columns)
        if t.partition_of is not None:
            from citus_tpu.partitioning import check_partition_bounds
            check_partition_bounds(self.catalog, t, values, validity)
        if t.check_constraints:
            from citus_tpu.integrity import enforce_check_constraints
            enforce_check_constraints(self.catalog, t, values, validity)
        remote_n = 0
        if self.catalog.remote_data is not None \
                and not getattr(self._remote_exec_guard, "v", False):
            values, validity, remote_n = self._route_remote_batch(
                t, values, validity)
            if not values or len(next(iter(values.values()))) == 0:
                # every row went to remote hosts
                self.counters.bump("rows_ingested", remote_n)
                return remote_n
        import contextlib as _ctxlib

        from citus_tpu.transaction.locks import EXCLUSIVE, SHARED
        txn = current_overlay()
        # unique enforcement needs probe+write atomicity: two SHARED
        # ingests could both miss the probe and insert the same key.
        # The mode is re-derived from the fresh TableMeta inside the
        # lock — a CREATE UNIQUE INDEX committed after our stale fetch
        # must escalate us before the probe runs.
        lock_mode = EXCLUSIVE if t.unique_indexes else SHARED
        while True:
            with self._write_lock(t, lock_mode):
                t = self.catalog.table(table_name)  # re-fetch: fresh placements
                if t.unique_indexes and lock_mode == SHARED:
                    lock_mode = EXCLUSIVE
                    continue  # retry under the stronger lock
                self._copy_from_locked(t, txn, columns, values, validity)
                break
        n = len(next(iter(values.values()))) if values else 0
        self.counters.bump("rows_ingested", n + remote_n)
        if self._cdc_captures(t.name) and n:
            self._emit_cdc(t.name, "insert",
                           rows=self._decode_rows(t, values, validity),
                           columns=t.schema.names)
        return n + remote_n

    def _route_remote_batch(self, t, values, validity):
        """Split a physical ingest batch by shard ownership: rows whose
        shard is hosted by another coordinator ship over the data plane
        (reference: distributed COPY forwarding per-shard streams to the
        owning worker, commands/multi_copy.c CitusSendTupleToPlacements);
        the local remainder continues through the normal path.  Returns
        (local_values, local_validity, rows_shipped)."""
        from citus_tpu.catalog.hashing import hash_int64
        if not t.is_distributed:
            # reference/local tables: every remote host with a placement
            # receives the FULL batch (reference tables replicate to all
            # nodes under 2PC; reference_table_utils.c) — rows counted
            # once, from the local copy when one exists
            eps = {self.catalog.node_endpoint(nd)
                   for s in t.shards for nd in s.placements
                   if self.catalog.is_remote_node(nd)}
            if not eps:
                return values, validity, 0
            from citus_tpu.storage.overlay import current_overlay
            if current_overlay() is not None:
                raise UnsupportedFeatureError(
                    "writes to remote-hosted placements inside an "
                    "explicit transaction are not supported yet")
            shipped = 0
            for ep in eps:
                shipped = self.catalog.remote_data.ship_batch(
                    ep, t.name, values, validity,
                    wire=self.settings.executor.wire_format)
            local_hosted = any(not self.catalog.is_remote_node(nd)
                               for s in t.shards for nd in s.placements)
            if local_hosted:
                return values, validity, 0  # local ingest counts them
            return {}, {}, shipped
        # replicated shards spanning hosts: routing writes the primary
        # placement only, so a replica on another host would silently
        # diverge — fail closed, like the reference-table guard above
        # (the reference replicates these writes under 2PC to every
        # placement; multi_copy.c per-placement streams)
        if any(len(s.placements) > 1
               and any(self.catalog.is_remote_node(nd)
                       for nd in s.placements)
               for s in t.shards):
            raise UnsupportedFeatureError(
                "writing to a distributed table whose replicated shard "
                "placements span hosts is not supported yet (only one "
                "placement would receive the rows, diverging replicas)")
        owners = [t.shards[si].placements[0] for si in range(t.shard_count)]
        if not any(self.catalog.is_remote_node(o) for o in owners):
            return values, validity, 0
        from citus_tpu.storage.overlay import current_overlay
        if current_overlay() is not None:
            raise UnsupportedFeatureError(
                "writes to remote-hosted shards inside an explicit "
                "transaction are not supported yet (no cross-host 2PC)")
        if t.unique_indexes or t.foreign_keys:
            raise UnsupportedFeatureError(
                "unique/FK-constrained tables cannot span remote-hosted "
                "shards yet (constraint probes are host-local)")
        dist = values[t.dist_column].astype(np.int64)
        idx = t.route_hashes(hash_int64(dist))
        # group remote shards by owning endpoint: one batch per host
        by_endpoint: dict = {}
        remote_rows = np.zeros(len(dist), bool)
        for si in range(t.shard_count):
            owner = owners[si]
            if not self.catalog.is_remote_node(owner):
                continue
            sel = idx == si
            if not sel.any():
                continue
            ep = self.catalog.node_endpoint(owner)
            m = by_endpoint.setdefault(ep, np.zeros(len(dist), bool))
            m |= sel
            remote_rows |= sel
        shipped = 0
        for ep, m in by_endpoint.items():
            sub_v = {c: v[m] for c, v in values.items()}
            sub_m = {c: x[m] for c, x in validity.items()}
            shipped += self.catalog.remote_data.ship_batch(
                ep, t.name, sub_v, sub_m,
                wire=self.settings.executor.wire_format)
        if not remote_rows.any():
            return values, validity, 0
        keep = ~remote_rows
        return ({c: v[keep] for c, v in values.items()},
                {c: x[keep] for c, x in validity.items()}, shipped)

    def _fill_defaults(self, t, columns: dict) -> dict:
        """Fill columns absent from an ingest batch from their DEFAULT
        expressions (reference: pg_attrdef defaults applied by the
        rewriter).  nextval defaults draw one value PER ROW; other
        defaults are constants folded once."""
        missing = [c for c in t.schema
                   if c.name not in columns and c.default_sql]
        if not missing:
            return columns
        n = len(next(iter(columns.values()))) if columns else 1
        out = dict(columns)
        from citus_tpu.planner.parser import Parser
        cache = self._default_expr_cache
        for col in missing:
            e = cache.get(col.default_sql)
            if e is None:
                e = Parser(col.default_sql).parse_expr()
                if len(cache) > 512:
                    cache.clear()
                cache[col.default_sql] = e
            if isinstance(e, A.FuncCall) and e.name == "nextval" \
                    and e.args and isinstance(e.args[0], A.Literal):
                seq = str(e.args[0].value)
                out[col.name] = [self.catalog.nextval(seq)
                                 for _ in range(n)]
            else:
                v = _eval_const(e)
                out[col.name] = [v] * n
        return out

    def _copy_from_locked(self, t, txn, columns, values, validity) -> None:
        """copy_from's body under the table write lock: FK + unique
        probes, then the staged or 2PC ingest."""
        import contextlib as _ctxlib

        from citus_tpu.transaction.locks import SHARED
        with _ctxlib.ExitStack() as stack:
            if t.foreign_keys:
                # hold the parents' group locks (SHARED) across
                # probe + write, so a concurrent parent DELETE
                # (EXCLUSIVE on the parent group) cannot interleave
                # between the FK check and the ingest commit
                from citus_tpu.integrity import check_ingest
                from citus_tpu.transaction.write_locks import (
                    group_resource, group_write_lock,
                )
                parents = {}
                for fk in t.foreign_keys:
                    p = self.catalog.table(fk["ref_table"])
                    parents[group_resource(p)] = p
                for res in sorted(parents):
                    if txn is not None:
                        txn.hold_group_lock(self, parents[res], SHARED)
                    else:
                        stack.enter_context(group_write_lock(
                            self.catalog, parents[res], SHARED,
                            lock_manager=self.locks,
                            timeout=self.settings.executor.lock_timeout_s))
                check_ingest(self, t, columns)
            if t.unique_indexes:
                from citus_tpu.integrity import check_unique_ingest
                check_unique_ingest(self, t, values, validity)
            if txn is not None:
                # stage under the open transaction; COMMIT flips it.
                # On failure, REGISTER (don't abort) what was staged:
                # aborting the xid would destroy earlier statements'
                # staged rows; registration lets ROLLBACK [TO
                # SAVEPOINT] clean exactly this statement's stripes.
                ing = TableIngestor(self.catalog, t, txlog=None)
                ing.xid = txn.xid
                try:
                    ing.append(values, validity)
                    for w in ing._writers.values():
                        w.flush()
                finally:
                    txn.record_ingest(
                        t.name,
                        [w.directory for w in ing._writers.values()])
            else:
                ing = TableIngestor(self.catalog, t, txlog=self.txlog)
                try:
                    ing.append(values, validity)
                except BaseException:
                    ing.abort()
                    raise
                ing.finish()

    def _domain_columns_of(self, t) -> list[tuple[str, str, dict]]:
        """[(column, domain name, domain def)] for ``t``."""
        out = []
        for cname in t.schema.names:
            dn = self.catalog.domain_columns.get(f"{t.name}.{cname}")
            if dn is None:
                continue
            dom = self.catalog.domains.get(dn)
            if dom is not None:
                out.append((cname, dn, dom))
        return out

    def _check_domain_values(self, dn: str, dom: dict, values) -> None:
        """Evaluate one domain's CHECK over an iterable of logical
        values.  Distinct-value memoization keeps categorical bulk
        ingest cheap; NULL passes CHECK (NOT NULL is the column's)."""
        import numpy as _np
        from citus_tpu.planner.parser import Parser as _P
        if not dom.get("check"):
            return
        expr = _P(dom["check"]).parse_expr()
        verdicts: dict = {}
        for v in values:
            if v is None:
                continue
            if isinstance(v, _np.generic):
                v = v.item()
            ok = verdicts.get(v)
            if ok is None:
                sub = {A.ColumnRef("value"): _pylit(v)}
                try:
                    ok = _eval_const(_replace_exprs(expr, sub)) is True
                except Exception:
                    raise UnsupportedFeatureError(
                        f'cannot evaluate CHECK of domain "{dn}" '
                        f"({dom['check']!r})")
                verdicts[v] = ok
            if not ok:
                raise ExecutionError(
                    f'value {v!r} for domain "{dn}" violates check '
                    f"constraint ({dom['check']})")

    def _check_domains(self, t, columns) -> None:
        """Domain CHECK enforcement at ingest (reference: domain
        constraints fire on every insert; VALUE names the checked
        value)."""
        for cname, dn, dom in self._domain_columns_of(t):
            if cname in columns:
                self._check_domain_values(dn, dom, columns[cname])

    def _check_domains_physical(self, t, values, validity) -> None:
        """Same enforcement over PHYSICAL column arrays (the UPDATE
        re-insert path): decode back to logical values first."""
        for cname, dn, dom in self._domain_columns_of(t):
            if cname not in values or not dom.get("check"):
                continue
            col = t.schema.column(cname)
            vals = []
            for phys, ok in zip(values[cname], validity[cname]):
                if not ok:
                    continue
                if col.type.is_text:
                    vals.append(self.catalog.decode_strings(
                        t.name, cname, [int(phys)])[0])
                elif col.type.kind == "uuid":
                    continue  # recombined below from the lane pair
                else:
                    vals.append(col.type.from_physical(
                        np.asarray(phys).item()))
            if col.type.kind == "uuid":
                from citus_tpu import types as T
                lane = values[T.uuid_lane_name(cname)]
                vals = [T.uuid_from_lane_pair(int(h), int(l))
                        for h, l, ok in zip(values[cname], lane,
                                            validity[cname]) if ok]
            self._check_domain_values(dn, dom, vals)

    def _cdc_captures(self, table: str) -> bool:
        """The table's changes are captured when CDC is globally on OR
        any publication covers it (reference: commands/publication.c —
        publications gate logical decoding per table)."""
        if self.cdc.enabled:
            return True
        if not self.catalog.publications:
            return False
        # a publication on a partitioned parent covers its partitions
        # (writes route to leaves before this gate runs)
        names = {table}
        t = self.catalog.tables.get(table)
        if t is not None and t.partition_of is not None:
            names.add(t.partition_of["parent"])
        for pub in self.catalog.publications.values():
            tl = pub.get("tables")
            if tl == "all" or (isinstance(tl, list) and names & set(tl)):
                return True
        return False

    def _emit_cdc(self, table: str, op: str, **kw) -> None:
        """Emit a change event — or, inside an open transaction, defer
        it to COMMIT (PostgreSQL logical decoding emits on commit)."""
        from citus_tpu.storage.overlay import current_overlay
        txn = current_overlay()
        if txn is not None:
            txn.cdc_events.append((table, op, kw))
        else:
            self.cdc.emit(table, op, self.clock.transaction_clock(),
                          force=True, **kw)

    def _decode_rows(self, t, values, validity) -> list:
        out = []
        names = t.schema.names
        n = len(next(iter(values.values())))
        text_cache = {}
        for c in names:
            col = t.schema.column(c)
            if col.type.is_text:
                text_cache[c] = self.catalog.decode_strings(
                    t.name, c, values[c].tolist())
        from citus_tpu import types as T
        for i in range(n):
            row = []
            for c in names:
                col = t.schema.column(c)
                if not validity[c][i]:
                    row.append(None)
                elif col.type.is_text:
                    row.append(text_cache[c][i])
                elif col.type.kind == "uuid":
                    row.append(T.uuid_from_lane_pair(
                        int(values[c][i]),
                        int(values[T.uuid_lane_name(c)][i])))
                else:
                    row.append(col.type.from_physical(values[c][i].item()))
            out.append(row)
        return out

    def copy_from_csv(self, table_name: str, path: str, *,
                      delimiter: str = ",", header: bool = False,
                      null_string: str = "", batch_rows: int = 200_000) -> int:
        """Bulk load from a CSV file, streamed in batches (the reference's
        COPY FROM with per-shard stream switchover,
        commands/multi_copy.c)."""
        import csv
        t = self.catalog.table(table_name)
        names = t.schema.names
        total = 0
        with open(path, newline="") as fh:
            reader = csv.reader(fh, delimiter=delimiter)
            if header:
                next(reader, None)
            batch: list = []
            for row in reader:
                batch.append([None if v == null_string else v for v in row])
                if len(batch) >= batch_rows:
                    total += self.copy_from(table_name, rows=batch)
                    batch = []
            if batch:
                total += self.copy_from(table_name, rows=batch)
        return total

    @staticmethod
    def _open_csv_writer(fh, columns, *, delimiter: str, header: bool):
        """One CSV emission convention for both COPY TO forms."""
        import csv
        w = csv.writer(fh, delimiter=delimiter)
        if header:
            w.writerow(columns)
        return w

    def copy_to_csv(self, table_name: str, path: str, *,
                    delimiter: str = ",", header: bool = False,
                    null_string: str = "") -> int:
        """Streaming CSV export: shards are read batch by batch, decoded,
        and written incrementally (symmetric with copy_from_csv)."""
        import os as _os
        from citus_tpu.storage import ShardReader
        from citus_tpu.transaction.snapshot import read_generation
        t = self.catalog.table(table_name)
        names = t.schema.names
        total = 0
        # NOTE: the export streams to the caller's file, so a mid-export
        # flip cannot be retried transparently; capture the generation
        # and fail loudly on a torn export instead of silently writing
        # a mixture (readers of query results get the retrying
        # snapshot_read path; COPY TO keeps PostgreSQL's "repeatable
        # read within the statement" spirit by detecting the overlap)
        gen0, busy0 = read_generation(self.catalog.data_dir, t)
        with open(path, "w", newline="") as fh:
            w = self._open_csv_writer(fh, names, delimiter=delimiter,
                                      header=header)
            for shard in t.shards:
                d = self.catalog.shard_dir(table_name, shard.shard_id,
                                           shard.placements[0])
                if not _os.path.isdir(d):
                    continue
                reader = ShardReader(d, t.schema)
                from citus_tpu import types as T
                for batch in reader.scan(t.schema.physical_names(names)):
                    decoded = {}
                    for c in names:
                        col = t.schema.column(c)
                        vals = batch.values[c]
                        if col.type.is_text:
                            decoded[c] = self.catalog.decode_strings(
                                table_name, c, vals.tolist())
                        elif col.type.kind == "uuid":
                            lane = batch.values[T.uuid_lane_name(c)]
                            decoded[c] = [T.uuid_from_lane_pair(int(h), int(l))
                                          for h, l in zip(vals, lane)]
                        else:
                            decoded[c] = [col.type.from_physical(v.item())
                                          for v in vals]
                    for i in range(batch.row_count):
                        row = []
                        for c in names:
                            m = batch.validity[c]
                            if m is not None and not m[i]:
                                row.append(null_string)
                            else:
                                row.append(decoded[c][i])
                        w.writerow(row)
                        total += 1
        gen1, busy1 = read_generation(self.catalog.data_dir, t)
        if busy0 or busy1 or gen1 != gen0:
            raise ExecutionError(
                "concurrent metadata flip during COPY TO; re-run the "
                "export")
        return total

    # -------------------------------------------------------------- SQL
    def session(self):
        """Open an interactive session (the psql-connection analog):
        supports BEGIN/COMMIT/ROLLBACK and savepoints.  Statements run
        through ``Cluster.execute`` directly use a shared default
        session, so ``cl.execute("BEGIN")`` works too."""
        from citus_tpu.transaction.session import Session
        return Session(self)

    def _default_session(self):
        """One implicit session PER THREAD (each thread of the
        session-less API is its own psql connection): a BEGIN issued on
        one thread must not pull other threads' autocommit statements
        into its transaction block, and concurrent statements keep
        distinct lock identities.  CPython reuses thread idents, so each
        entry remembers its owning Thread — a recycled ident rolls back
        the dead owner's abandoned transaction instead of inheriting it."""
        import threading as _th
        sessions = self._default_sessions
        me = _th.current_thread()
        tid = me.ident
        entry = sessions.get(tid)
        if entry is not None:
            owner, s = entry
            if owner is me:
                return s
            # ident recycled from a dead thread: its abandoned open
            # transaction rolls back (connection-close semantics)
            if s.txn is not None:
                self._rollback_txn(s)
        s = self.session()
        sessions[tid] = (me, s)
        return s

    def execute(self, sql: str, params: Optional[Sequence[Any]] = None,
                role: Optional[str] = None, session=None) -> Result:
        from citus_tpu.observability.trace import clock as _clock
        if session is None:
            session = self._default_session()
        if session.txn is None:
            # inside a transaction the catalog object must stay stable
            # (statements hold references into it; PostgreSQL blocks
            # conflicting DDL with locks instead)
            self._maybe_reload_catalog()
        # sampling gate: None on the unsampled hot path (no Span ever
        # allocates); a nested execute() (EXECUTE of a prepared
        # statement) joins the outer trace instead of rooting a new one
        qt = None
        if _trace.current() is None:
            qt = _trace.begin_query(sql, self.settings.observability)
        try:
            with _trace.span("parse"):
                stmts = parse_sql(sql)
        except BaseException:
            if qt is not None:
                qt.finish()
            raise
        if role is not None:
            for stmt in stmts:
                self._check_privileges(role, stmt)
        result = Result(columns=[], rows=[])
        gpid = self.activity.enter(sql)
        # live phase reporting: executor set_phase() calls land on this
        # statement's activity row (works with or without sampling)
        _trace.push_phase_sink(
            lambda phase, _g=gpid: self.activity.set_phase(_g, phase))
        # likewise the wait-event seam (stats.begin_wait/end_wait): a
        # blocking branch hit mid-statement lands on this row's
        # wait_event column
        _stats.push_wait_sink(
            lambda event, _g=gpid: self.activity.set_wait(_g, event))
        t0 = _clock()
        # active role for statements synthesized mid-execution (the
        # upsert's internal UPDATE must see the same RLS policies);
        # per-thread: concurrent execute() calls must not see each
        # other's roles
        import threading as _threading
        # restore (not pop) on exit: a nested execute() — EXECUTE of a
        # prepared statement — must not clear the outer call's role,
        # or later synthesized statements would skip RLS
        _tid = _threading.get_ident()
        _prev_role = self._exec_roles.get(_tid)
        self._exec_roles[_tid] = role
        try:
            for stmt in stmts:
                if isinstance(stmt, A.TransactionStmt):
                    result = self._execute_transaction_stmt(session, stmt)
                    continue
                txn = session.txn
                if txn is not None and txn.failed:
                    from citus_tpu.transaction.session import (
                        InFailedTransaction,
                    )
                    raise InFailedTransaction(
                        "current transaction is aborted, commands "
                        "ignored until end of transaction block")
                if isinstance(stmt, (A.Prepare, A.ExecutePrepared,
                                     A.Deallocate)):
                    try:
                        result = self._execute_prepared_stmt(session, stmt,
                                                             role)
                    except Exception:
                        # PostgreSQL: any error aborts the block
                        if txn is not None:
                            txn.failed = True
                        raise
                    continue
                if txn is not None:
                    from citus_tpu.storage.overlay import transaction_overlay
                    try:
                        self._guard_in_txn(stmt)
                        with transaction_overlay(txn):
                            result = self._execute_in_session(
                                stmt, sql, stmts, params, role)
                            self._fire_triggers(stmt)
                    except Exception:
                        # PostgreSQL: any error aborts the transaction
                        # block until ROLLBACK [TO SAVEPOINT]
                        txn.failed = True
                        raise
                else:
                    result = self._execute_in_session(stmt, sql, stmts,
                                                      params, role)
                    self._fire_triggers(stmt)
        finally:
            if _prev_role is None:
                self._exec_roles.pop(_tid, None)
            else:
                self._exec_roles[_tid] = _prev_role
            _trace.pop_phase_sink()
            _stats.pop_wait_sink()
            self.activity.exit(gpid)
            if qt is not None:
                self._finish_query_trace(qt, sql)
        # the nested execute() of an EXECUTE already recorded the
        # underlying statement — don't double-count the wrapper
        if not (len(stmts) == 1 and isinstance(stmts[0], A.ExecutePrepared)):
            executor = result.explain.get("strategy", "utility") if result.explain else "utility"
            elapsed = _clock() - t0
            rkey = result.explain.get("router_key") if result.explain else None
            self.query_stats.record(sql, elapsed, result.rowcount, str(executor),
                                    partition_key="" if rkey is None else str(rkey))
            if rkey is not None:
                self.tenant_stats.record(str(rkey), elapsed)
            if result.explain and "strategy" in result.explain:
                # live scheduler histogram behind citus_stat_tenants():
                # router queries under their key, analytics under "*"
                from citus_tpu.workload import GLOBAL_SCHEDULER, tenant_key
                GLOBAL_SCHEDULER.record_latency(tenant_key(rkey),
                                                elapsed * 1000.0)
            mb = result.explain.get("megabatch") if result.explain else None
            if mb:
                # per-STATEMENT occupancy attribution: one note per user
                # query that rode a batch (the per-batch half books in
                # the dispatcher itself)
                from citus_tpu.executor.megabatch import GLOBAL_MEGABATCH
                GLOBAL_MEGABATCH.note_query_occupancy(
                    int(mb.get("occupancy", 1)))
        return result

    def _finish_query_trace(self, qt, sql: str) -> None:
        """Close a sampled query's trace: slow-log capture at the
        citus.log_min_duration_ms threshold, Chrome-trace export when
        citus.trace_export_dir is set, last-trace debug hook."""
        from citus_tpu.observability.export import write_chrome_trace
        from citus_tpu.observability.slowlog import GLOBAL_SLOW_LOG
        obs = self.settings.observability
        dur_ms = qt.finish()
        slow = obs.log_min_duration_ms >= 0 \
            and dur_ms >= obs.log_min_duration_ms
        if slow:
            GLOBAL_SLOW_LOG.record(sql, dur_ms, qt.trace)
        if qt.sampled or slow:
            _trace.set_last(qt.trace)
            if obs.trace_export_dir:
                try:
                    write_chrome_trace(qt.trace, obs.trace_export_dir)
                except OSError:
                    pass  # export is best-effort; never fail the query

    def _execute_in_session(self, stmt, sql, stmts, params, role) -> Result:
        """One statement through parameter substitution, RLS rewrite,
        and plan-cache keying (the pre-session body of execute())."""
        if params is not None:
            # parameterized plans: cached generic plan + deferred
            # pruning when the query shape supports it (reference:
            # Job->deferredPruning, fast_path_router_planner.c)
            # — superuser only: the cache keys on SQL text and an
            # RLS rewrite must never leak across roles
            if len(stmts) == 1 and isinstance(stmt, A.Select) \
                    and role is None:
                r = self._execute_param_select(sql, stmt, list(params))
                if r is not None:
                    return r
            from citus_tpu.planner.recursive import rewrite_params
            stmt = rewrite_params(stmt, list(params))
        rls_rewritten = False
        if role is not None:
            # after parameter substitution so WITH CHECK sees the
            # actual inserted values
            stmt, rls_rewritten = self._apply_rls(role, stmt)
        key = sql if (len(stmts) == 1 and params is None
                      and not rls_rewritten) else None
        return self._execute_stmt(stmt, sql_text=key)

    #: statement types allowed inside BEGIN..COMMIT.  DDL and cluster
    #: operations commit catalog changes immediately, so allowing them
    #: would break transaction atomicity — refuse instead (PostgreSQL
    #: allows transactional DDL; a documented divergence for now).
    _TXN_ALLOWED = None  # initialized lazily below

    def _guard_in_txn(self, stmt) -> None:
        if Cluster._TXN_ALLOWED is None:
            Cluster._TXN_ALLOWED = (
                A.Select, A.WithSelect, A.SetOp, A.Explain, A.Insert,
                A.Update, A.Delete,
                # transactional DDL: catalog mutations stage in memory
                # (Catalog.commit defers), physical file actions defer to
                # COMMIT / register rollback cleanups (reference: DDL in
                # transaction blocks via citus_ProcessUtility,
                # utility_hook.c:148)
                A.CreateTable, A.DropTable, A.CreateIndex, A.DropIndex,
                A.CreateSchema, A.CreateView, A.DropView, A.CreateSequence,
                A.DropSequence, A.CreateFunction, A.DropFunction,
                A.CreateType, A.DropType, A.CreateRole, A.DropRole,
                A.Grant, A.CreatePolicy, A.DropPolicy, A.CreateTrigger,
                A.DropTrigger, A.AlterTableRls, A.AlterTable,
                A.CreateExtension, A.DropExtension, A.CreateDomain,
                A.DropDomain, A.CreateCollation, A.DropCollation,
                A.CreatePublication, A.DropPublication,
                A.CreateStatistics, A.DropStatistics, A.Analyze,
                A.CreateTableAs, A.SetConfig, A.ShowConfig,
                A.UtilityCall)
        if not isinstance(stmt, Cluster._TXN_ALLOWED):
            raise UnsupportedFeatureError(
                f"{type(stmt).__name__} cannot run inside a transaction "
                "block")
        if isinstance(stmt, A.AlterTable) and stmt.action in (
                "rename_table", "rename_column"):
            # renames shard-data directories / dictionary and segment
            # files in place — not stageable
            raise UnsupportedFeatureError(
                "ALTER TABLE RENAME cannot run inside a transaction block")
        if isinstance(stmt, A.UtilityCall) and stmt.name not in (
                "create_distributed_table", "create_reference_table"):
            raise UnsupportedFeatureError(
                f"{stmt.name}() cannot run inside a transaction block")

    def _execute_prepared_stmt(self, session, stmt, role) -> Result:
        """PREPARE / EXECUTE / DEALLOCATE — the stored unit is SQL text,
        so EXECUTE rides the text-keyed generic-plan cache (one compile
        serves every invocation; reference: prepared statements with
        deferred pruning, fast_path_router_planner.c)."""
        if isinstance(stmt, A.Prepare):
            if stmt.name in session.prepared:
                raise CatalogError(
                    f'prepared statement "{stmt.name}" already exists')
            session.prepared[stmt.name] = stmt.sql
            return Result(columns=[], rows=[])
        if isinstance(stmt, A.Deallocate):
            if stmt.name is None:
                session.prepared.clear()
                return Result(columns=[], rows=[])
            if session.prepared.pop(stmt.name, None) is None:
                raise CatalogError(
                    f'prepared statement "{stmt.name}" does not exist')
            return Result(columns=[], rows=[])
        sql = session.prepared.get(stmt.name)
        if sql is None:
            raise CatalogError(
                f'prepared statement "{stmt.name}" does not exist')
        args = [_eval_const(a) for a in stmt.args]
        return self.execute(sql, params=args or None, role=role,
                            session=session)

    def _execute_transaction_stmt(self, session, stmt) -> Result:
        """BEGIN/COMMIT/ROLLBACK/SAVEPOINT state machine (reference:
        CoordinatedTransactionCallback, transaction_management.c:319;
        subtransaction callback :176)."""
        from citus_tpu.transaction.session import OpenTransaction
        kind = stmt.kind
        txn = session.txn
        if kind == "begin":
            if txn is not None:
                return Result(columns=[], rows=[],
                              explain={"warning": "there is already a "
                                       "transaction in progress"})
            xid = self.txlog.begin()
            session.txn = OpenTransaction(xid, session.lock_sid)
            # DDL rollback restores drop-tombstones along with the
            # in-memory document
            session.txn.tombstones_snapshot = {
                k: set(v) for k, v in self.catalog._tombstones.items()}
            return Result(columns=[], rows=[], explain={"transaction": "begin"})
        if kind == "commit":
            if txn is None:
                return Result(columns=[], rows=[],
                              explain={"warning": "there is no transaction "
                                       "in progress"})
            if txn.failed:
                # COMMIT of an aborted transaction rolls back
                self._rollback_txn(session)
                return Result(columns=[], rows=[],
                              explain={"transaction": "rollback"})
            self._commit_txn(session)
            return Result(columns=[], rows=[], explain={"transaction": "commit"})
        if kind == "rollback":
            if txn is None:
                return Result(columns=[], rows=[],
                              explain={"warning": "there is no transaction "
                                       "in progress"})
            self._rollback_txn(session)
            return Result(columns=[], rows=[], explain={"transaction": "rollback"})
        # savepoint family requires an open transaction (PostgreSQL
        # errors outside one)
        if txn is None:
            raise TransactionError(
                f"{kind.upper()} can only be used in transaction blocks")
        if kind == "savepoint":
            if txn.failed:
                from citus_tpu.transaction.session import InFailedTransaction
                raise InFailedTransaction(
                    "current transaction is aborted, commands ignored "
                    "until end of transaction block")
            if txn.remote_endpoints:
                raise UnsupportedFeatureError(
                    "savepoints are not supported in a transaction with "
                    "remote-shard writes yet")
            txn.savepoints.append((stmt.name, txn.snapshot(self.catalog)))
            return Result(columns=[], rows=[])
        if kind == "rollback_to":
            for i in range(len(txn.savepoints) - 1, -1, -1):
                if txn.savepoints[i][0] == stmt.name:
                    txn.restore(txn.savepoints[i][1], self)
                    # the savepoint itself survives (PostgreSQL keeps it
                    # so you can roll back to it again); later ones die
                    del txn.savepoints[i + 1:]
                    self._plan_cache.clear()
                    return Result(columns=[], rows=[])
            txn.failed = True  # error in a txn block aborts it (25P02)
            raise TransactionError(f'savepoint "{stmt.name}" does not exist')
        if kind == "release":
            if txn.failed:
                from citus_tpu.transaction.session import InFailedTransaction
                raise InFailedTransaction(
                    "current transaction is aborted, commands ignored "
                    "until end of transaction block")
            for i in range(len(txn.savepoints) - 1, -1, -1):
                if txn.savepoints[i][0] == stmt.name:
                    del txn.savepoints[i:]
                    return Result(columns=[], rows=[])
            txn.failed = True  # error in a txn block aborts it (25P02)
            raise TransactionError(f'savepoint "{stmt.name}" does not exist')
        raise AnalysisError(f"unknown transaction statement {kind!r}")

    def _commit_txn(self, session) -> None:
        """PREPARED -> COMMITTED -> flip staged state -> DONE across
        every placement the transaction touched — the interactive-
        transaction generalization of the per-statement 2PC (reference:
        pre-commit PREPARE on all write connections,
        transaction_management.c:319)."""
        from citus_tpu.storage.deletes import commit_staged_deletes
        from citus_tpu.storage.writer import commit_staged
        from citus_tpu.transaction.manager import TxState

        txn = session.txn
        if txn.remote_endpoints:
            return self._commit_txn_cross_host(session)
        try:
            if not (txn.has_writes or txn.catalog_dirty or txn.on_commit):
                self.txlog.release(txn.xid)
                return
            try:
                # catalog (with version bumps + staged DDL) persisted
                # before the COMMITTED record: roll-forward must find
                # everything it references on disk (same ordering as
                # ingest.finish).  The overlay is inactive here, so this
                # commit persists and broadcasts for real — the single
                # DDL-lease application point of the transaction's DDL.
                for name in sorted(txn.tables):
                    if self.catalog.has_table(name):
                        self.catalog.table(name).version += 1
                # release the staging guard just before the persist: this
                # commit IS the transaction's DDL application point
                self.catalog._end_staging(txn)
                self.catalog.commit()
                if txn.has_writes:
                    payload = {"kind": "txn",
                               "placements": sorted(txn.delete_dirs),
                               "ingest_placements": sorted(txn.ingest_dirs),
                               "tables": sorted(txn.tables)}
                    self.txlog.log(txn.xid, TxState.PREPARED, payload)
                    self.txlog.log(txn.xid, TxState.COMMITTED, payload)
                    # one flip bracket per touched colocation group: a
                    # snapshot read observes the whole transaction's
                    # effects on a table or none of them
                    import contextlib as _ctxlib

                    from citus_tpu.transaction.snapshot import flip_generation
                    from citus_tpu.transaction.write_locks import group_resource
                    groups = {}
                    for name in sorted(txn.tables):
                        if self.catalog.has_table(name):
                            t0 = self.catalog.table(name)
                            groups.setdefault(group_resource(t0), t0)
                    with _ctxlib.ExitStack() as _flips:
                        for res in sorted(groups):
                            _flips.enter_context(flip_generation(
                                self.catalog.data_dir, groups[res]))
                        for d in sorted(txn.delete_dirs):
                            commit_staged_deletes(d, txn.xid)
                        for d in sorted(txn.ingest_dirs):
                            commit_staged(d, txn.xid)
                    self.txlog.log(txn.xid, TxState.DONE)
                else:
                    self.txlog.release(txn.xid)
                # deferred physical DDL effects (segment drops, table
                # file removal) — only after the catalog flip is durable
                for act in txn.on_commit:
                    act()
            except BaseException:
                # stop driving; recovery decides the outcome from the log
                self.txlog.release(txn.xid)
                raise
            self._plan_cache.clear()
            if txn.has_writes:
                # the txn write path bypasses _write_lock's publication:
                # expire placement-mirror elision tokens here instead
                for name in sorted(txn.tables):
                    self._publish_data_changed(name)
            if txn.cdc_events:
                clock = self.clock.transaction_clock()
                for table, op, kw in txn.cdc_events:
                    # queued only for captured tables at statement time
                    self.cdc.emit(table, op, clock, force=True, **kw)
        finally:
            self.catalog._end_staging(txn)
            txn.release_locks(self)
            session.txn = None

    # ---- cross-host branches: transaction/branches.py ----------------
    def _prepare_branch(self, session, gxid: str) -> None:
        from citus_tpu.transaction.branches import prepare_branch
        return prepare_branch(self, session, gxid)

    def _finish_branch(self, session, commit: bool) -> None:
        from citus_tpu.transaction.branches import finish_branch
        return finish_branch(self, session, commit)

    def _commit_txn_cross_host(self, session) -> None:
        from citus_tpu.transaction.branches import commit_txn_cross_host
        return commit_txn_cross_host(self, session)

    def _rollback_txn(self, session) -> None:
        from citus_tpu.storage.deletes import abort_staged_deletes
        from citus_tpu.storage.writer import abort_staged

        txn = session.txn
        if txn.remote_endpoints and self.catalog.remote_data is not None:
            # abort the remote branch sessions first (their staged
            # writes and locks die with them)
            for ep in sorted(txn.remote_endpoints):
                try:
                    self.catalog.remote_data.call(
                        ep, "txn_branch_abort", {"gxid": txn.gxid})
                # lint: disable=SWL01 -- peer unreachable: branch expiry resolves the orphan branch
                except Exception:
                    pass  # branch expiry cleans it up
        try:
            for d in sorted(txn.ingest_dirs):
                abort_staged(d, txn.xid)
            for d in sorted(txn.delete_dirs):
                abort_staged_deletes(d, txn.xid)
            # physical artifacts staged by DDL (e.g. backfilled index
            # segments) — remove in reverse order of creation
            for act in reversed(txn.on_rollback):
                try:
                    act()
                # lint: disable=SWL01 -- rollback actions are best-effort; orphan files never affect reads
                except Exception:
                    pass  # best-effort: orphan files never affect reads
            if txn.catalog_dirty:
                # discard staged DDL: the on-disk document was never
                # touched, so reloading it restores the pre-BEGIN state
                self._reload_catalog()
                self.catalog._tombstones = {
                    k: set(v) for k, v in txn.tombstones_snapshot.items()}
            self.txlog.release(txn.xid)
            self._plan_cache.clear()
        finally:
            # only now may other sessions persist the (restored) catalog
            self.catalog._end_staging(txn)
            txn.release_locks(self)
            session.txn = None

    def _execute_param_select(self, sql: str, stmt: A.Select,
                              params: list) -> Optional[Result]:
        """Execute a parameterized SELECT through the generic-plan cache:
        bind once with $N slots, prune shards at bind-value time, reuse
        jitted kernels across values.  Returns None when the query shape
        needs the literal-substitution fallback."""
        from citus_tpu.planner.recursive import has_subquery
        if not isinstance(stmt.from_, A.TableRef):
            return None
        if self.catalog.has_table(stmt.from_.name) \
                and self.catalog.table(stmt.from_.name).is_partitioned:
            # partitioned parents need the expand_from rewrite, which
            # runs in _execute_stmt — fall back to literal substitution
            return None
        if stmt.distinct_on:
            return None  # DISTINCT ON dedups through _execute_distinct_on
        if any(isinstance(i.expr, A.WindowCall) for i in stmt.items):
            return None
        exprs = ([i.expr for i in stmt.items] + [stmt.where, stmt.having]
                 + stmt.group_by + [o.expr for o in stmt.order_by])
        if any(e is not None and has_subquery(e) for e in exprs):
            return None
        n_params = _max_param_index(stmt)
        if n_params > len(params):
            raise AnalysisError(
                f"query references ${n_params} but only "
                f"{len(params)} parameters were supplied")
        key = ("$param", sql)
        backend = self.settings.executor.task_executor_backend
        cache_on = self.settings.planner.plan_cache_mode != "force_custom"
        _trace.set_phase("plan")
        if cache_on:
            entry = self._plan_cache.lookup(key, self.catalog, backend)
            if entry is not None:
                self.counters.bump("plan_cache_hits")
                with _trace.span("plan", cache_hit=True):
                    pass
                return execute_select(self.catalog, entry.bound,
                                      self.settings, plan=entry.plan,
                                      param_values=params)
        with _trace.span("plan", cache_hit=False):
            try:
                with _trace.span("bind"):
                    bound = bind_select(self.catalog, stmt,
                                        param_count=n_params)
            except UnsupportedFeatureError:
                return None  # fall back to literal substitution
            from citus_tpu.planner.physical import plan_select
            plan = plan_select(
                self.catalog, bound,
                direct_limit=self.settings.planner.direct_gid_limit)
            if cache_on:
                self._plan_cache.put(key, bound, plan, self.catalog, backend)
                self.counters.bump("plan_cache_misses")
        return execute_select(self.catalog, bound, self.settings, plan=plan,
                              param_values=params)

    def _cached_select_plan(self, stmt: A.Select, key):
        """Bind + plan a single-table SELECT through the surgical plan
        cache, auto-parameterizing filter literals so literal variants
        of one query family share a structural fingerprint (and thus
        compiled kernels, executor/kernel_cache.py) even when their SQL
        texts differ.  ``key`` None (internal recursion, no stable text)
        skips caching entirely.  Returns (bound, plan, values, hit)."""
        backend = self.settings.executor.task_executor_backend
        mode = self.settings.planner.plan_cache_mode
        cache_on = key is not None and mode != "force_custom"
        _trace.set_phase("plan")
        if cache_on:
            entry = self._plan_cache.lookup(key, self.catalog, backend)
            if entry is not None:
                self.counters.bump("plan_cache_hits")
                with _trace.span("plan", cache_hit=True) as psp:
                    if psp.recording:
                        from citus_tpu.executor.kernel_cache import (
                            plan_fingerprint,
                        )
                        psp.set(fingerprint=plan_fingerprint(entry.plan)[:12])
                return entry.bound, entry.plan, entry.values, True
        with _trace.span("plan", cache_hit=False) as psp:
            with _trace.span("bind"):
                bound = bind_select(self.catalog, stmt)
            values = None
            if cache_on:
                from citus_tpu.planner.auto_param import auto_parameterize
                with _trace.span("auto_param"):
                    ap = auto_parameterize(bound)
                if ap is not None:
                    bound, values = ap
            from citus_tpu.planner.physical import plan_select
            plan = plan_select(
                self.catalog, bound,
                direct_limit=self.settings.planner.direct_gid_limit)
            if cache_on:
                self._plan_cache.put(key, bound, plan, self.catalog, backend,
                                     values=values)
                self.counters.bump("plan_cache_misses")
            if psp.recording:
                from citus_tpu.executor.kernel_cache import plan_fingerprint
                psp.set(fingerprint=plan_fingerprint(plan)[:12])
        return bound, plan, values, False

    #: statement-recursion ceiling: subquery materialization, view
    #: expansion, and partition fan-out all re-enter _execute_stmt; a
    #: circular view reference (direct, via subqueries, or through
    #: another view) would otherwise die with a raw RecursionError
    _MAX_STMT_DEPTH = 64
    _stmt_depth = __import__("threading").local()
    # original SQL of the statement being executed (thread-local):
    # remote DML forwarding re-ships the statement text, the closest
    # thing to the reference's deparse-and-send (we deliberately have
    # no deparser — commands/dml.py _forward_remote_dml)
    _stmt_sql = __import__("threading").local()
    # set while executing a statement a PEER forwarded to us: such a
    # statement operates on OUR placements only and must never forward
    # again (two coordinators would ping-pong a TRUNCATE forever)
    _remote_exec_guard = __import__("threading").local()
    # remote branch counts of an in-transaction modify whose local part
    # still runs (commands/dml.py _txn_remote_dml sets, handlers merge)
    _remote_counts = __import__("threading").local()
    # parsed DEFAULT expressions keyed by their SQL text (immutable)
    _default_expr_cache: dict = {}

    def _execute_stmt(self, stmt: A.Statement, sql_text: Optional[str] = None) -> Result:
        depth = getattr(self._stmt_depth, "v", 0)
        if depth >= self._MAX_STMT_DEPTH:
            raise AnalysisError(
                "query nesting too deep (possible circular view "
                "reference)")
        self._stmt_depth.v = depth + 1
        prev_sql = getattr(self._stmt_sql, "v", None)
        self._stmt_sql.v = sql_text
        try:
            return self._execute_stmt_inner(stmt, sql_text)
        finally:
            self._stmt_depth.v = depth
            self._stmt_sql.v = prev_sql

    def _execute_stmt_inner(self, stmt: A.Statement, sql_text: Optional[str] = None) -> Result:
        if isinstance(stmt, (A.Select, A.SetOp, A.WithSelect)):
            from citus_tpu.storage.overlay import current_overlay
            txn0 = current_overlay()
            if txn0 is not None and txn0.remote_written_tables:
                hit = _from_relations(stmt) & txn0.remote_written_tables
                if hit:
                    raise UnsupportedFeatureError(
                        f"cannot read {sorted(hit)[0]!r} in this "
                        "transaction after writing its remote-hosted "
                        "shards (remote staged state is not visible "
                        "here); COMMIT first")
        if isinstance(stmt, A.WithSelect):
            return self._execute_with(stmt)
        if isinstance(stmt, (A.Select, A.SetOp)) and self.catalog.functions:
            stmt = self._expand_functions_stmt(stmt)
        if isinstance(stmt, A.SetOp):
            return self._execute_setop(stmt)
        if isinstance(stmt, A.Select) and stmt.distinct_on:
            return self._execute_distinct_on(stmt)
        if isinstance(stmt, A.Select) and stmt.from_ is None:
            return self._execute_constant_select(stmt)
        if isinstance(stmt, A.Select) and stmt.from_ is not None:
            from citus_tpu.planner.recursive import (
                decorrelate_scalars, decorrelate_where,
            )
            stmt = decorrelate_scalars(stmt)
            stmt = decorrelate_where(stmt)
        if isinstance(stmt, A.Select) and stmt.from_ is not None \
                and self.catalog.views:
            new_from = self._expand_views(stmt.from_)
            if new_from is not stmt.from_:
                stmt = A.Select(stmt.items, new_from, stmt.where,
                                stmt.group_by, stmt.having, stmt.order_by,
                                stmt.limit, stmt.offset, stmt.distinct,
                                stmt.windows)
        if isinstance(stmt, A.Select) and stmt.from_ is not None and any(
                t.is_partitioned for t in self.catalog.tables.values()):
            # partitioned parents rewrite to their surviving partitions
            # (partition pruning stacks on shard + chunk pruning)
            from citus_tpu.partitioning import expand_from
            new_from = expand_from(self, stmt.from_, stmt.where)
            if new_from is not stmt.from_:
                import dataclasses as _dc
                stmt = _dc.replace(stmt, from_=new_from)
        if isinstance(stmt, A.Select) and stmt.from_ is not None \
                and _has_derived(stmt.from_):
            return self._execute_derived(stmt)
        if isinstance(stmt, A.Select) and len(stmt.group_by) == 1 \
                and isinstance(stmt.group_by[0], A.GroupingSetsSpec):
            return self._execute_grouping_sets(stmt, stmt.group_by[0].sets)
        if isinstance(stmt, A.Select) and any(
                isinstance(i.expr, A.WindowCall) for i in stmt.items):
            return self._execute_window(stmt)
        if isinstance(stmt, A.Select) and any(
                isinstance(i.expr, A.FuncCall) and i.expr.name == "unnest"
                for i in stmt.items):
            from citus_tpu.commands.select_exec import _execute_unnest
            return _execute_unnest(self, stmt)
        if isinstance(stmt, A.Select):
            # recursive planning: materialize subqueries first
            from citus_tpu.planner.recursive import rewrite_subqueries
            new_stmt = rewrite_subqueries(
                stmt, lambda sub: self._execute_stmt(sub))
            if new_stmt is not stmt:
                return self._execute_stmt(new_stmt)  # plans are not cached
        if isinstance(stmt, A.Delete) and stmt.where is not None:
            from citus_tpu.planner.recursive import has_subquery, rewrite_subqueries
            if has_subquery(stmt.where):
                wrapped = A.Select([A.SelectItem(A.Literal(1, "int"))],
                                   from_=None, where=stmt.where)
                rew = rewrite_subqueries(wrapped, lambda sub: self._execute_stmt(sub))
                stmt = A.Delete(stmt.table, rew.where)
        if isinstance(stmt, A.Update):
            from citus_tpu.planner.recursive import has_subquery, rewrite_subqueries
            exprs = [e for _, e in stmt.assignments] +                 ([stmt.where] if stmt.where is not None else [])
            if any(has_subquery(e) for e in exprs):
                items = [A.SelectItem(e) for _, e in stmt.assignments]
                wrapped = A.Select(items or [A.SelectItem(A.Literal(1, "int"))],
                                   from_=None, where=stmt.where)
                rew = rewrite_subqueries(wrapped, lambda sub: self._execute_stmt(sub))
                new_assignments = [(c, it.expr) for (c, _), it in
                                   zip(stmt.assignments, rew.items)]                     if stmt.assignments else []
                stmt = A.Update(stmt.table, new_assignments, rew.where)
        if isinstance(stmt, A.Select) and isinstance(stmt.from_, A.Join):
            from citus_tpu.executor.join_executor import execute_join_select
            from citus_tpu.planner.join_planner import bind_join_select
            bj = bind_join_select(self.catalog, stmt)
            return execute_join_select(self.catalog, bj, self.settings)
        if isinstance(stmt, A.Select):
            if self.catalog.rollups:
                # continuous aggregation: a dashboard query whose shape
                # a rollup materializes is answered from stored sketch
                # state (stale by the refresh lag) instead of scanning
                from citus_tpu.rollup.routing import maybe_execute_rollup
                rres = maybe_execute_rollup(self, stmt)
                if rres is not None:
                    return rres
            bound, plan, values, _ = self._cached_select_plan(
                stmt, sql_text or None)
            return execute_select(self.catalog, bound, self.settings,
                                  plan=plan, param_values=values)
        # everything below SELECT dispatches through the per-statement
        # handler registry (commands/; the DistributeObjectOps analog)
        from citus_tpu.commands import loader as _loader
        _loader.ensure_loaded()
        from citus_tpu.commands.registry import lookup as _lookup
        handler = _lookup(stmt)
        if handler is not None:
            return handler(self, stmt)
        raise UnsupportedFeatureError(f"cannot execute {type(stmt).__name__}")

    # --- SET/SHOW/ANALYZE/REINDEX/RETURNING: commands/config_cmds.py ---
    def _compute_ndistinct(self, table, columns):
        from citus_tpu.commands.config_cmds import _compute_ndistinct
        return _compute_ndistinct(self, table, columns)

    def _guc_key(self, name):
        from citus_tpu.commands.config_cmds import _guc_key
        return _guc_key(self, name)

    def _execute_set(self, stmt):
        from citus_tpu.commands.config_cmds import _execute_set
        return _execute_set(self, stmt)

    def _guc_value(self, key):
        from citus_tpu.commands.config_cmds import _guc_value
        return _guc_value(self, key)

    def _execute_show(self, stmt):
        from citus_tpu.commands.config_cmds import _execute_show
        return _execute_show(self, stmt)

    def _execute_analyze(self, table):
        from citus_tpu.commands.config_cmds import _execute_analyze
        return _execute_analyze(self, table)

    def _execute_reindex(self, stmt):
        from citus_tpu.commands.config_cmds import _execute_reindex
        return _execute_reindex(self, stmt)

    def _returning_result(self, table_name, where, items, subst=None):
        from citus_tpu.commands.config_cmds import _returning_result
        return _returning_result(self, table_name, where, items, subst)


    def _execute_insert(self, stmt: A.Insert) -> Result:
        from citus_tpu.commands.insert import execute_insert
        return execute_insert(self, stmt)

    # --- SELECT machinery: delegated to commands/select_exec.py ---
    def _execute_distinct_on(self, stmt):
        from citus_tpu.commands.select_exec import _execute_distinct_on
        return _execute_distinct_on(self, stmt)

    def _execute_window(self, stmt):
        from citus_tpu.commands.select_exec import _execute_window
        return _execute_window(self, stmt)

    def _schema_from_result(self, r, *, strict_empty: bool = False):
        from citus_tpu.commands.select_exec import _schema_from_result
        return _schema_from_result(self, r, strict_empty=strict_empty)

    def _create_temp_from_result(self, prefix, label, r):
        from citus_tpu.commands.select_exec import _create_temp_from_result
        return _create_temp_from_result(self, prefix, label, r)

    def _execute_derived(self, stmt):
        from citus_tpu.commands.select_exec import _execute_derived
        return _execute_derived(self, stmt)

    def _expand_functions_stmt(self, stmt, depth: int = 0):
        from citus_tpu.commands.select_exec import _expand_functions_stmt
        return _expand_functions_stmt(self, stmt, depth)

    def _execute_constant_select(self, stmt):
        from citus_tpu.commands.select_exec import _execute_constant_select
        return _execute_constant_select(self, stmt)

    def _expand_views(self, item):
        from citus_tpu.commands.select_exec import _expand_views
        return _expand_views(self, item)

    def _execute_grouping_sets(self, stmt, sets):
        from citus_tpu.commands.select_exec import _execute_grouping_sets
        return _execute_grouping_sets(self, stmt, sets)

    def _execute_setop(self, stmt):
        from citus_tpu.commands.select_exec import _execute_setop
        return _execute_setop(self, stmt)

    def _execute_with(self, stmt):
        from citus_tpu.commands.select_exec import _execute_with
        return _execute_with(self, stmt)

    # --- RLS / triggers / privileges: commands/rls.py ---
    def _policy_predicate(self, role, table, cmd, kind="using"):
        from citus_tpu.commands.rls import _policy_predicate
        return _policy_predicate(self, role, table, cmd, kind)

    def _apply_rls(self, role, stmt):
        from citus_tpu.commands.rls import _apply_rls
        return _apply_rls(self, role, stmt)

    def _rls_check_update(self, role, stmt):
        from citus_tpu.commands.rls import _rls_check_update
        return _rls_check_update(self, role, stmt)

    def _fire_triggers(self, stmt, depth: int = 0):
        from citus_tpu.commands.rls import _fire_triggers
        return _fire_triggers(self, stmt, depth)

    def _fire_triggers_for(self, table, event, depth: int):
        from citus_tpu.commands.rls import _fire_triggers_for
        return _fire_triggers_for(self, table, event, depth)

    def _check_privileges(self, role, stmt):
        from citus_tpu.commands.rls import _check_privileges
        return _check_privileges(self, role, stmt)

    def _execute_utility(self, stmt: A.UtilityCall) -> Result:
        """UDF-style admin calls, dispatched through the commands
        registry (reference: sql/udfs/ entry points; see
        commands/utility.py)."""
        from citus_tpu.commands.utility import execute_utility
        return execute_utility(self, stmt)

    def _run_command_on_shards(self, table_name, command,
                               per_placement: bool = False):
        from citus_tpu.commands.shard_cmds import _run_command_on_shards
        return _run_command_on_shards(self, table_name, command,
                                      per_placement=per_placement)

    def _table_ddl(self, name):
        from citus_tpu.commands.shard_cmds import _table_ddl
        return _table_ddl(self, name)


    def _table_size(self, name: str) -> int:
        import os
        t = self.catalog.table(name)
        total = 0
        for shard in t.shards:
            for node in shard.placements:
                d = self.catalog.shard_dir(name, shard.shard_id, node)
                if os.path.isdir(d):
                    total += sum(os.path.getsize(os.path.join(d, f))
                                 for f in os.listdir(d))
        return total

    def profile(self, sql: str, trace_dir: str) -> Result:
        """Execute under the JAX/XLA profiler (the tracing-integration
        analog of SURVEY §5.1); view the trace with TensorBoard or
        xprof."""
        with jax.profiler.trace(trace_dir):
            return self.execute(sql)

    def _execute_explain(self, stmt):
        from citus_tpu.commands.explain import _execute_explain
        return _execute_explain(self, stmt)
