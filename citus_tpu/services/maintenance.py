"""Maintenance daemon.

Reference: the per-database maintenance background worker
(src/backend/distributed/utils/maintenanced.c) that periodically runs
deferred-resource cleanup, 2PC recovery, deadlock detection, and
metadata-sync retries.  Here: one daemon thread per Cluster running a
pluggable list of periodic duties; ships with cleanup and stale-lock
recovery, and later milestones register more duties (transaction
recovery, health checks).
"""

from __future__ import annotations

import threading
import time
from citus_tpu.utils.clock import now as wall_now
from dataclasses import dataclass
from typing import Callable

from citus_tpu.catalog import Catalog
from citus_tpu.operations.cleaner import try_drop_orphaned_resources


@dataclass
class Duty:
    name: str
    fn: Callable[[], object]
    # a float, or a zero-arg callable re-read every tick (so SET-style
    # runtime changes to an interval take effect on a running daemon)
    interval_s: "float | Callable[[], float]"
    # higher runs first within a tick: a due latency-critical duty
    # (deadlock detection) must never wait behind a long-running
    # housekeeping duty that happened to register earlier
    priority: int = 0
    last_run: float = 0.0
    runs: int = 0
    errors: int = 0


class MaintenanceDaemon:
    def __init__(self, cat: Catalog, *, cleanup_interval_s: float = 5.0):
        self.cat = cat
        self._duties: list[Duty] = []
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.register("deferred_cleanup",
                      lambda: try_drop_orphaned_resources(cat),
                      cleanup_interval_s)

    def register(self, name: str, fn: Callable[[], object], interval_s: float,
                 priority: int = 0) -> None:
        self._duties.append(Duty(name, fn, interval_s, priority=priority))

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="citus-tpu-maintenanced")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def run_once(self) -> None:
        """Run every duty immediately (tests + explicit triggers)."""
        for d in self._ordered():
            self._run_duty(d)

    def _ordered(self) -> list[Duty]:
        """Duties in execution order: priority desc, then registration
        order (sorted() is stable, so equal priorities keep their
        historical ordering)."""
        return sorted(self._duties, key=lambda d: -d.priority)

    @staticmethod
    def _interval(d: Duty) -> float:
        return d.interval_s() if callable(d.interval_s) else d.interval_s

    def status(self) -> list[tuple]:
        return [(d.name, self._interval(d), d.runs, d.errors)
                for d in self._duties]

    def _run_duty(self, d: Duty) -> None:
        try:
            d.fn()
            d.runs += 1
        except Exception:
            d.errors += 1
        d.last_run = wall_now()

    def _loop(self) -> None:
        while not self._stop.is_set():
            now = wall_now()
            for d in self._ordered():
                if now - d.last_run >= self._interval(d):
                    self._run_duty(d)
            self._stop.wait(timeout=0.2)
