"""Background services: job scheduler + maintenance daemon
(reference: src/backend/distributed/utils/background_jobs.c and
utils/maintenanced.c)."""

from citus_tpu.services.background_jobs import BackgroundJobRunner, JobStatus
from citus_tpu.services.maintenance import MaintenanceDaemon

__all__ = ["BackgroundJobRunner", "JobStatus", "MaintenanceDaemon"]
