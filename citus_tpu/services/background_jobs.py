"""Background job/task runner.

Reference: pg_dist_background_job / pg_dist_background_task (+ _depend)
executed by background workers (src/backend/distributed/utils/
background_jobs.c — citus_job_wait :192, StartCitusBackgroundTaskExecutor
:1650), used by the rebalancer to run shard moves with per-node
concurrency caps and retries.

Here: a thread-pool executor over a persisted job/task queue.  Tasks are
named operations with JSON arguments (a registry maps names to Python
callables), dependencies gate execution order, failures retry up to
``max_attempts``, and state survives restarts via the catalog data dir.

Each task row is a live progress record (reference: the DSM progress
monitor behind get_rebalance_progress, progress/multi_progress.c): the
running operation calls the module-level ``report_progress()`` to update
its own row's ``phase`` / ``bytes_done`` / ``bytes_total`` in place, and
views derive a rate-based ETA from ``started_at``.  Progress updates are
memory-only — a crash loses at most the progress of the task being
retried anyway; durable state still changes only at claim/finish.
"""

from __future__ import annotations

import json
import os
import threading
import time
from citus_tpu.utils.clock import now as wall_now
import traceback
from typing import Callable, Optional

from citus_tpu.catalog import Catalog

JOBS_FILE = "background_jobs.json"


class JobStatus:
    SCHEDULED = "scheduled"
    RUNNING = "running"
    DONE = "done"
    FAILED = "failed"
    CANCELLED = "cancelled"


# the task a worker thread is currently executing, so report_progress()
# from anywhere inside the operation lands on the right row without
# threading a handle through every call layer
_current_task = threading.local()


def report_progress(phase: Optional[str] = None,
                    bytes_done: Optional[int] = None,
                    bytes_total: Optional[int] = None,
                    add_bytes: int = 0) -> None:
    """Update the calling background task's progress row in place.
    No-op when the caller is not running under a background task (the
    same operations run synchronously from utility commands too)."""
    bound = getattr(_current_task, "bound", None)
    if bound is None:
        return
    runner, task = bound
    with runner._lock:
        if phase is not None:
            task["phase"] = phase
        if bytes_total is not None:
            task["bytes_total"] = int(bytes_total)
        if bytes_done is not None:
            task["bytes_done"] = int(bytes_done)
        elif add_bytes:
            task["bytes_done"] = int(task.get("bytes_done") or 0) + int(add_bytes)


class BackgroundJobRunner:
    """One runner per cluster; tasks execute on worker threads."""

    def __init__(self, cat: Catalog, max_workers: int = 2,
                 max_task_executors_per_node: int = 1):
        self.cat = cat
        self.max_workers = max_workers
        self.max_per_node = max_task_executors_per_node
        self._registry: dict[str, Callable] = {}
        self._lock = threading.RLock()
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        self._node_running: dict[int, int] = {}
        self._state = self._load()

    # ---- persistence ---------------------------------------------------
    def _path(self) -> str:
        return os.path.join(self.cat.data_dir, JOBS_FILE)

    def _load(self) -> dict:
        if os.path.exists(self._path()):
            with open(self._path()) as fh:
                state = json.load(fh)
            # tasks that were mid-flight when the process died are retried
            for t in state["tasks"]:
                if t["status"] == JobStatus.RUNNING:
                    t["status"] = JobStatus.SCHEDULED
            return state
        return {"next_job_id": 1, "next_task_id": 1, "jobs": [], "tasks": []}

    def _store(self) -> None:
        tmp = self._path() + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(self._state, fh)
        os.replace(tmp, self._path())

    # ---- registry / API --------------------------------------------------
    def register(self, name: str, fn: Callable) -> None:
        self._registry[name] = fn

    def create_job(self, description: str) -> int:
        with self._lock:
            jid = self._state["next_job_id"]
            self._state["next_job_id"] += 1
            self._state["jobs"].append({
                "job_id": jid, "description": description,
                "status": JobStatus.SCHEDULED, "created_at": wall_now(),
            })
            self._store()
            return jid

    def add_task(self, job_id: int, op: str, args: dict, *,
                 depends_on: Optional[list[int]] = None, node: Optional[int] = None,
                 max_attempts: int = 3) -> int:
        with self._lock:
            tid = self._state["next_task_id"]
            self._state["next_task_id"] += 1
            self._state["tasks"].append({
                "task_id": tid, "job_id": job_id, "op": op, "args": args,
                "status": JobStatus.SCHEDULED, "depends_on": depends_on or [],
                "node": node, "attempts": 0, "max_attempts": max_attempts,
                "error": None,
                # live progress record, updated in place by the running
                # operation through report_progress()
                "phase": "", "bytes_done": 0, "bytes_total": 0,
                "started_at": None,
            })
            self._store()
        self._wake.set()
        return tid

    @staticmethod
    def _eta_s(t: dict, now: float) -> Optional[float]:
        """Rate-derived seconds-to-completion for a running task with
        byte progress; None when no rate can be established yet."""
        done = t.get("bytes_done") or 0
        total = t.get("bytes_total") or 0
        started = t.get("started_at")
        if (t["status"] != JobStatus.RUNNING or not started
                or done <= 0 or total <= done):
            return None
        elapsed = max(1e-9, now - started)
        return round((total - done) * elapsed / done, 3)

    def job_progress(self, job_id: int) -> list[tuple]:
        """Per-task progress rows (reference: get_rebalance_progress over
        the DSM progress monitor, progress/multi_progress.c).  Columns:
        (task_id, op, args, status, attempts, phase, bytes_done,
        bytes_total, started_at, eta_s)."""
        now = wall_now()
        with self._lock:
            return [(t["task_id"], t["op"], str(t["args"]), t["status"],
                     t["attempts"], t.get("phase") or "",
                     int(t.get("bytes_done") or 0),
                     int(t.get("bytes_total") or 0),
                     t.get("started_at"), self._eta_s(t, now))
                    for t in self._state["tasks"] if t["job_id"] == job_id]

    def jobs_view(self) -> dict:
        """Public snapshot of the job/task queue — row copies, so
        callers never need (and must not reach for) ``_lock``/``_state``."""
        with self._lock:
            return {"jobs": [dict(j) for j in self._state["jobs"]],
                    "tasks": [dict(t) for t in self._state["tasks"]]}

    def job_status(self, job_id: int) -> str:
        with self._lock:
            tasks = [t for t in self._state["tasks"] if t["job_id"] == job_id]
            if any(t["status"] == JobStatus.FAILED for t in tasks):
                return JobStatus.FAILED
            if all(t["status"] == JobStatus.DONE for t in tasks):
                return JobStatus.DONE
            if any(t["status"] == JobStatus.RUNNING for t in tasks):
                return JobStatus.RUNNING
            return JobStatus.SCHEDULED

    def task_rows(self) -> list[tuple]:
        with self._lock:
            return [(t["task_id"], t["job_id"], t["op"], t["status"], t["attempts"])
                    for t in self._state["tasks"]]

    def wait_for_job(self, job_id: int, timeout: float = 60.0) -> str:
        """citus_job_wait analog."""
        deadline = wall_now() + timeout
        while wall_now() < deadline:
            st = self.job_status(job_id)
            if st in (JobStatus.DONE, JobStatus.FAILED, JobStatus.CANCELLED):
                return st
            time.sleep(0.02)
        return self.job_status(job_id)

    def cancel_job(self, job_id: int) -> None:
        with self._lock:
            for t in self._state["tasks"]:
                if t["job_id"] == job_id and t["status"] == JobStatus.SCHEDULED:
                    t["status"] = JobStatus.CANCELLED
            self._store()

    # ---- execution -------------------------------------------------------
    def start(self) -> None:
        if self._threads:
            return
        self._stop.clear()
        for i in range(self.max_workers):
            th = threading.Thread(target=self._worker_loop, daemon=True,
                                  name=f"bg-task-executor-{i}")
            th.start()
            self._threads.append(th)

    def stop(self) -> None:
        self._stop.set()
        self._wake.set()
        for th in self._threads:
            th.join(timeout=5)
        self._threads = []

    def _claim(self) -> Optional[dict]:
        with self._lock:
            done = {t["task_id"] for t in self._state["tasks"]
                    if t["status"] == JobStatus.DONE}
            for t in self._state["tasks"]:
                if t["status"] != JobStatus.SCHEDULED:
                    continue
                if any(d not in done for d in t["depends_on"]):
                    continue
                node = t.get("node")
                if node is not None and self._node_running.get(node, 0) >= self.max_per_node:
                    continue
                t["status"] = JobStatus.RUNNING
                t["attempts"] += 1
                # fresh progress record per attempt: a retry must not
                # resume a dead attempt's bytes_done or phase
                t["phase"] = "starting"
                t["bytes_done"] = 0
                t["started_at"] = wall_now()
                if node is not None:
                    self._node_running[node] = self._node_running.get(node, 0) + 1
                self._store()
                return t
        return None

    def _finish(self, task: dict, status: str, error: Optional[str]) -> None:
        with self._lock:
            task["status"] = status
            task["error"] = error
            node = task.get("node")
            if node is not None:
                self._node_running[node] = max(0, self._node_running.get(node, 0) - 1)
            self._store()
        self._wake.set()

    def _worker_loop(self) -> None:
        while not self._stop.is_set():
            task = self._claim()
            if task is None:
                self._wake.wait(timeout=0.05)
                self._wake.clear()
                continue
            fn = self._registry.get(task["op"])
            if fn is None:
                self._finish(task, JobStatus.FAILED, f"unknown op {task['op']!r}")
                continue
            _current_task.bound = (self, task)
            try:
                fn(**task["args"])
                self._finish(task, JobStatus.DONE, None)
            except Exception:
                err = traceback.format_exc(limit=4)
                if task["attempts"] < task["max_attempts"]:
                    with self._lock:
                        task["status"] = JobStatus.SCHEDULED
                        task["error"] = err
                        node = task.get("node")
                        if node is not None:
                            self._node_running[node] = max(0, self._node_running.get(node, 0) - 1)
                        self._store()
                else:
                    self._finish(task, JobStatus.FAILED, err)
            finally:
                _current_task.bound = None
