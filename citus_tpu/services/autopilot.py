"""Autopilot: the flight-recorder→rebalancer control loop.

Closes ROADMAP item 3's observe→decide→act gap: the sensors
(flight-recorder health events, per-placement load attribution) and
the actuators (non-blocking shard moves through the crash-safe
operation registry) exist — this duty connects them.

Control discipline
------------------
* ``citus.autopilot = off | observe | on`` (default off).  ``observe``
  evaluates and logs every decision — with the evidence snapshot that
  drove it — but executes nothing, making the decision log itself the
  dry-run A/B instrument.
* Hysteresis: the same plan step must recur for
  ``citus.autopilot_sustain_ticks`` consecutive evaluations before the
  autopilot acts; every action starts a ``citus.autopilot_cooldown_s``
  quiet period; at most ONE autopilot operation is ever in flight.
* Exactly-once across restarts: an ``autopilot``-kind row in the
  operation registry (operations/cleaner.py) brackets each executed
  action.  A restarted autopilot that finds a dead owner's row adopts
  it — retires the row, enters cooldown, logs the adoption — instead
  of re-deciding, so a SIGKILL mid-decision never yields two moves.
  The cooldown timestamp itself persists in
  ``<data_dir>/autopilot_state.json``.
* Conservative actuation: only ``move`` steps execute; ``split`` and
  ``isolate`` steps are logged as advisory decisions for an operator
  (the dry-run plan view shows them with scores).

Every decision — executed, observed, declined, adopted — lands in a
bounded ring surfaced cluster-wide via ``citus_autopilot_log()`` and
as ``autopilot_actions_*`` counters (Prometheus:
``citus_autopilot_actions_total{outcome=...}``).
"""

from __future__ import annotations

import json
import os
import threading
from collections import deque

from citus_tpu.utils.clock import now as wall_now

LOG_MAX = 256          # retained decision-ring entries
MODES = ("off", "observe", "on")
#: health-event kinds whose activity rides the evidence snapshot
TRIGGER_KINDS = ("p99_regression", "shed_rate_spike", "pool_saturation")

LOG_COLUMNS = ("ts", "mode", "decision", "action", "table_name",
               "shard_id", "source_node", "target_node", "score",
               "reason", "evidence")

STATE_FILE = "autopilot_state.json"


class Autopilot:
    """Per-cluster decision loop, driven as a maintenance duty."""

    def __init__(self, cluster) -> None:
        self._cl = cluster
        self._mu = threading.Lock()
        self._log: deque = deque(maxlen=LOG_MAX)
        # plan-step key -> consecutive ticks it has been the top step
        self._pending: dict[tuple, int] = {}
        self._state_path = os.path.join(cluster.catalog.data_dir,
                                        STATE_FILE)
        self._state = self._load_state()
        # (kind, subject) of our last emitted health event, resolved
        # once the cooldown that action started expires
        self._live_event: tuple | None = None

    # ------------------------------------------------------------ state

    def _load_state(self) -> dict:
        try:
            with open(self._state_path, "r", encoding="utf-8") as f:
                st = json.load(f)
            return st if isinstance(st, dict) else {}
        except (OSError, ValueError):
            return {}

    def _store_state(self) -> None:
        tmp = self._state_path + ".tmp"
        try:
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(self._state, f)
            os.replace(tmp, self._state_path)
        except OSError:
            pass  # state is an optimization (cooldown across restarts)

    # ------------------------------------------------------------- duty

    def duty(self) -> None:
        """One evaluation tick (registered with the maintenance daemon;
        interval = citus.autopilot_interval_s)."""
        ap = self._cl.settings.autopilot
        mode = str(ap.mode)
        if mode not in ("observe", "on"):
            return
        self._cl.counters.bump("autopilot_ticks")
        from citus_tpu.observability.load_attribution import (
            GLOBAL_ATTRIBUTION,
        )
        from citus_tpu.operations.rebalance_plan import build_rebalance_plan
        GLOBAL_ATTRIBUTION.tick()
        rec = self._cl.flight_recorder
        active = rec.active_counts()
        health = {k: active.get(k, 0) for k in TRIGGER_KINDS
                  if active.get(k, 0)}
        steps = build_rebalance_plan(
            self._cl.catalog, "by_observed_load",
            threshold=float(ap.threshold), max_steps=4)
        now = wall_now()
        self._maybe_resolve_event(now, float(ap.cooldown_s))
        if not steps:
            self._pending.clear()
            if health:
                # a health trigger with no actionable plan is itself a
                # decision worth auditing (the "we looked and held
                # still" record the A/B analysis needs)
                self._decide(mode, "declined", None, 0,
                             "no actionable plan for active health "
                             "events", health, now)
            return
        step = steps[0]
        key = (step.action, step.table, step.shard_id,
               step.source_node, step.target_node)
        seen = self._pending.get(key, 0) + 1
        self._pending = {key: seen}
        sustain = max(1, int(ap.sustain_ticks))
        if seen < sustain:
            self._decide(mode, "declined", step, seen,
                         f"sustaining {seen}/{sustain}", health, now)
            return
        last_ts = float(self._state.get("last_action_ts", 0.0))
        if now - last_ts < float(ap.cooldown_s):
            self._decide(mode, "declined", step, seen,
                         f"cooldown ({ap.cooldown_s:.0f}s after "
                         f"{self._state.get('last_action_key')})",
                         health, now)
            return
        stale = self._check_inflight(now)
        if stale == "live":
            self._decide(mode, "declined", step, seen,
                         "autopilot operation already in flight",
                         health, now)
            return
        if stale == "adopted":
            self._decide(mode, "declined", step, seen,
                         "adopted a crashed autopilot's decision; "
                         "entering its cooldown instead of re-acting",
                         health, now)
            return
        if step.action != "move":
            self._decide(mode, "declined", step, seen,
                         f"{step.action} is advisory: surfaced for an "
                         "operator, never auto-executed", health, now)
            self._pending.clear()
            return
        if mode == "observe":
            self._enter_cooldown(key, None, now)
            self._decide(mode, "observed", step, seen,
                         "observe mode: would execute", health, now)
            self._pending.clear()
            return
        self._execute(step, key, seen, health, now)
        self._pending.clear()

    # -------------------------------------------------------- execution

    def _execute(self, step, key: tuple, seen: int, health: dict,
                 now: float) -> None:
        import uuid

        from citus_tpu.operations.cleaner import (
            complete_operation, mark_operation_phase, register_operation,
        )
        from citus_tpu.operations.shard_transfer import move_shard_placement
        cat = self._cl.catalog
        op_id = uuid.uuid4().int & ((1 << 62) - 1)
        # registry row FIRST: if we die mid-move, the next autopilot
        # (any coordinator on this data dir) adopts this row instead of
        # deciding again — the exactly-once bracket
        register_operation(cat, op_id, kind="autopilot")
        mark_operation_phase(cat, op_id, "decided")
        self._enter_cooldown(key, op_id, now)
        ok = False
        try:
            move_shard_placement(cat, step.shard_id, step.source_node,
                                 step.target_node,
                                 lock_manager=self._cl.locks,
                                 settings=self._cl.settings)
            ok = True
        finally:
            complete_operation(cat, op_id, success=ok)
            self._decide("on", "executed" if ok else "failed", step, seen,
                         f"moved shard {step.shard_id} "
                         f"{step.source_node}->{step.target_node}"
                         if ok else "move raised; registry row retired",
                         health, wall_now())
        subject = f"{step.table}.{step.shard_id}"
        self._cl.flight_recorder.emit_event(
            "autopilot_action", subject, step.score, 0.0,
            f"autopilot moved {subject} node {step.source_node}->"
            f"{step.target_node} (score {step.score:.2f})")
        self._live_event = ("autopilot_action", subject)

    def _enter_cooldown(self, key: tuple, op_id, now: float) -> None:
        self._state = {"last_action_ts": now,
                       "last_action_key": list(key),
                       "last_op_id": op_id}
        self._store_state()

    def _maybe_resolve_event(self, now: float, cooldown_s: float) -> None:
        if self._live_event is None:
            return
        if now - float(self._state.get("last_action_ts", 0.0)) >= cooldown_s:
            self._cl.flight_recorder.resolve_event(*self._live_event)
            self._live_event = None

    def _check_inflight(self, now: float) -> str:
        """Scan the operation registry for autopilot rows: 'live' while
        one runs (ours or another coordinator's), 'adopted' when a dead
        owner's row was just retired, '' when clear."""
        from citus_tpu.operations.cleaner import (
            _pid_alive, complete_operation, operations_view,
        )
        cat = self._cl.catalog
        adopted = False
        for op_id, row in sorted(operations_view(cat).items()):
            if row.get("kind") != "autopilot":
                continue
            if _pid_alive(int(row.get("pid", -1))):
                # ours never linger (the execute bracket retires them
                # in a finally), so a live row IS a concurrent
                # autopilot: max-concurrent-ops = 1
                return "live"
            # dead owner: it had DECIDED (row exists ⇒ past the point
            # of no return) — the move op itself has its own registry
            # row/cleaner handling; retire the decision row and take
            # over its cooldown so the cluster never double-acts
            complete_operation(cat, int(op_id), success=False)
            adopted = True
        if adopted:
            self._enter_cooldown(("adopted",), None, now)
            return "adopted"
        return ""

    # ----------------------------------------------------- decision log

    def _decide(self, mode: str, decision: str, step, seen: int,
                reason: str, health: dict, now: float) -> None:
        counter = {"executed": "autopilot_actions_executed",
                   "failed": "autopilot_actions_executed",
                   "observed": "autopilot_actions_observed"}.get(
                       decision, "autopilot_actions_declined")
        self._cl.counters.bump(counter)
        evidence = {"health": health, "sustain": seen,
                    "mode": mode}
        if step is not None:
            evidence["step"] = step.to_row(1)
        row = (round(float(now), 3), mode, decision,
               step.action if step else "", step.table if step else "",
               step.shard_id if step else -1,
               step.source_node if step else -1,
               step.target_node if step else -1,
               round(float(step.score), 4) if step else 0.0,
               reason, json.dumps(evidence, sort_keys=True))
        with self._mu:
            self._log.append(row)

    def log_rows(self) -> list[tuple]:
        """Newest-first decision rows for citus_autopilot_log()."""
        with self._mu:
            return list(reversed(self._log))
