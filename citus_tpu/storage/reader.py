"""Shard reader: stripe/chunk iteration with skip-list pruning.

Reference analog: ColumnarBeginRead/ColumnarReadNextRow and chunk skipping
(src/backend/columnar/columnar_reader.c:148-180,323) — but instead of
materializing one row per call, the unit of delivery is a whole chunk
batch (values + validity per projected column), ready to be padded and
shipped to a device kernel.  Pruning happens on the host from footer
min/max stats before any stream bytes are read or decompressed, like
SelectedChunkMask/BuildBaseConstraint in the reference.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Iterator, Optional

import numpy as np

from citus_tpu.errors import AnalysisError, StorageError
from citus_tpu.schema import Schema
from citus_tpu.storage.format import read_stripe_footer, read_chunk
from citus_tpu.storage.writer import _load_meta


# SET citus.decode_threads pushes here (the process-wide native pool has
# no cluster handle, like kernel_cache's set_capacity); None = read the
# ambient settings
_DECODE_THREADS: Optional[int] = None


def set_decode_threads(n: int) -> None:
    global _DECODE_THREADS
    _DECODE_THREADS = int(n)


def decode_thread_count() -> int:
    """Threads for the native read+decompress pool — citus.decode_threads
    (0 = auto: min(8, cpu_count))."""
    n = _DECODE_THREADS
    if n is None:
        from citus_tpu.config import current_settings
        n = current_settings().executor.decode_threads
    if n > 0:
        return n
    return min(8, os.cpu_count() or 1)


@dataclass(frozen=True)
class Interval:
    """Closed/open numeric interval constraint on a column's physical
    values — the pruning currency (analog of the reference's base
    constraint over the skip list's min/max)."""

    column: str
    lo: Optional[float] = None
    hi: Optional[float] = None
    lo_inclusive: bool = True
    hi_inclusive: bool = True

    def admits(self, cmin, cmax) -> bool:
        """Could any value in [cmin, cmax] satisfy this constraint?"""
        if cmin is None or cmax is None:
            return True  # no stats -> cannot prune
        if self.lo is not None:
            if cmax < self.lo or (cmax == self.lo and not self.lo_inclusive):
                return False
        if self.hi is not None:
            if cmin > self.hi or (cmin == self.hi and not self.hi_inclusive):
                return False
        return True


@dataclass
class ChunkBatch:
    """One chunk group's worth of projected columns."""

    values: dict[str, np.ndarray]
    validity: dict[str, Optional[np.ndarray]]  # None = all valid
    row_count: int
    stripe_file: str
    chunk_index: int
    # first row's offset within the stripe (position addressing for DML)
    chunk_row_offset: int = 0


class ShardReader:
    """Reads one shard directory written by ShardWriter."""

    def __init__(self, directory: str, schema: Schema):
        from citus_tpu.storage.overlay import visible_meta
        self.directory = directory
        self.schema = schema
        self.meta = visible_meta(directory)

    @property
    def row_count(self) -> int:
        return self.meta["row_count"]

    @property
    def stripe_files(self) -> list[str]:
        return [s["file"] for s in self.meta["stripes"]]

    def scan(
        self,
        columns: list[str],
        constraints: Optional[list[Interval]] = None,
        apply_deletes: bool = True,
        only_stripes: Optional[set] = None,
    ) -> Iterator[ChunkBatch]:
        """Yield chunk batches for the projected ``columns``, skipping
        chunks refuted by ``constraints`` (conjunctive semantics) and
        subtracting deletion bitmaps (unless ``apply_deletes=False``,
        used by DML that needs original row positions).  ``only_stripes``
        restricts to a stripe-file subset (index-lookup fallback)."""
        from citus_tpu.storage.deletes import deleted_mask
        from citus_tpu.storage.overlay import visible_deletes
        constraints = constraints or []
        for col in columns:
            self.schema.scan_column(col)  # validate projection
        delete_cache = visible_deletes(self.directory) if apply_deletes else {}
        for stripe in self.meta["stripes"]:
            if only_stripes is not None and stripe["file"] not in only_stripes:
                continue
            path = os.path.join(self.directory, stripe["file"])
            footer = read_stripe_footer(path)
            selected = self._selected_chunks(footer, constraints)
            try:
                from citus_tpu.executor.executor import GLOBAL_COUNTERS
                GLOBAL_COUNTERS.bump("chunks_total", footer.chunk_count)
                GLOBAL_COUNTERS.bump("chunks_selected", int(selected.sum()))
                # rows refuted by footer min/max BEFORE any stream bytes
                # of theirs are read or decompressed — the fused hot
                # loop's admission win
                skipped = int(np.asarray(
                    footer.chunk_row_counts)[~selected].sum())
                if skipped:
                    GLOBAL_COUNTERS.bump("fused_rows_skipped", skipped)
            except ImportError:
                pass
            if not selected.any():
                continue
            offsets = np.concatenate([[0], np.cumsum(footer.chunk_row_counts)[:-1]])
            del_mask = None
            if apply_deletes and stripe["file"] in delete_cache:
                del_mask = deleted_mask(self.directory, stripe["file"],
                                        footer.row_count, delete_cache)
            sel_idx = [int(i) for i in np.nonzero(selected)[0]]
            native = self._scan_stripe_native(path, footer, columns, sel_idx)
            if native is not None:
                for b in native:
                    b.chunk_row_offset = int(offsets[b.chunk_index])
                    yield self._subtract_deletes(b, del_mask)
                continue
            with open(path, "rb") as fh:
                for ci in sel_idx:
                    vals, valid = {}, {}
                    for col in columns:
                        c = self.schema.scan_column(col)
                        stream = footer.columns.get(
                            self.schema.scan_storage_name(col))
                        if stream is None:
                            # column added after this stripe: all NULL
                            n_ = footer.chunk_row_counts[ci]
                            vals[col] = np.zeros(n_, c.type.storage_dtype)
                            valid[col] = np.zeros(n_, bool)
                            continue
                        v, m = read_chunk(fh, footer, stream[ci], c.type.storage_dtype)
                        vals[col], valid[col] = v, m
                    b = ChunkBatch(
                        values=vals, validity=valid,
                        row_count=footer.chunk_row_counts[ci],
                        stripe_file=stripe["file"], chunk_index=ci,
                        chunk_row_offset=int(offsets[ci]))
                    yield self._subtract_deletes(b, del_mask)

    def lookup_eq(
        self,
        columns: list[str],
        column: str,
        value,
        constraints: Optional[list[Interval]] = None,
    ) -> Iterator[ChunkBatch]:
        """Index-driven point lookup: yield batches holding ONLY the rows
        whose ``column`` equals ``value`` (live rows; deletes applied).
        Stripes without a segment fall back to a pruned full scan —
        never wrong, just slower (reference analog: an index scan over
        columnar random row access, columnar_reader.c:370-391)."""
        from citus_tpu.storage.deletes import deleted_mask
        from citus_tpu.storage.index import positions_eq
        from citus_tpu.storage.overlay import visible_deletes
        try:
            from citus_tpu.executor.executor import GLOBAL_COUNTERS
        except ImportError:
            GLOBAL_COUNTERS = None
        delete_cache = visible_deletes(self.directory)
        fallback: set = set()
        for stripe in self.meta["stripes"]:
            pos = positions_eq(self.directory, stripe["file"], column, value)
            if pos is None:
                fallback.add(stripe["file"])
                continue
            path = os.path.join(self.directory, stripe["file"])
            footer = read_stripe_footer(path)
            if GLOBAL_COUNTERS is not None:
                GLOBAL_COUNTERS.bump("index_lookups")
                GLOBAL_COUNTERS.bump("chunks_total", footer.chunk_count)
            if pos.size == 0:
                continue
            if stripe["file"] in delete_cache:
                dm = deleted_mask(self.directory, stripe["file"],
                                  footer.row_count, delete_cache)
                if dm is not None:
                    pos = pos[~dm[pos]]
                    if pos.size == 0:
                        continue
            bounds = np.concatenate([[0], np.cumsum(footer.chunk_row_counts)])
            chunk_of = np.searchsorted(bounds, pos, "right") - 1
            needed = np.unique(chunk_of)
            if GLOBAL_COUNTERS is not None:
                GLOBAL_COUNTERS.bump("chunks_selected", int(needed.size))
            with open(path, "rb") as fh:
                for ci in needed:
                    local = np.sort(pos[chunk_of == ci]) - bounds[ci]
                    vals, valid = {}, {}
                    for col in columns:
                        c = self.schema.scan_column(col)
                        stream = footer.columns.get(
                            self.schema.scan_storage_name(col))
                        if stream is None:
                            # column added after this stripe: all NULL
                            vals[col] = np.zeros(local.size, c.type.storage_dtype)
                            valid[col] = np.zeros(local.size, bool)
                            continue
                        v, m = read_chunk(fh, footer, stream[int(ci)],
                                          c.type.storage_dtype)
                        vals[col] = v[local]
                        valid[col] = None if m is None else m[local]
                    yield ChunkBatch(values=vals, validity=valid,
                                     row_count=int(local.size),
                                     stripe_file=stripe["file"],
                                     chunk_index=int(ci))
        if fallback:
            yield from self.scan(columns, constraints,
                                 only_stripes=fallback)

    @staticmethod
    def _subtract_deletes(b: ChunkBatch, del_mask) -> ChunkBatch:
        if del_mask is None:
            return b
        sl = del_mask[b.chunk_row_offset:b.chunk_row_offset + b.row_count]
        if not sl.any():
            return b
        keep = ~sl
        b.values = {c: v[keep] for c, v in b.values.items()}
        b.validity = {c: (m[keep] if m is not None else None)
                      for c, m in b.validity.items()}
        b.row_count = int(keep.sum())
        return b

    def _scan_stripe_native(self, path, footer, columns, sel_idx):
        """Batched read+decompress of all selected streams of one stripe
        through the C++ runtime (one call per column); None = unavailable."""
        from citus_tpu.native import CODEC_IDS, get_lib
        lib = get_lib()
        if lib is None or footer.codec not in CODEC_IDS:
            return None
        import ctypes
        u8p = ctypes.POINTER(ctypes.c_uint8)
        i64p = ctypes.POINTER(ctypes.c_int64)
        cid = CODEC_IDS[footer.codec]
        # one native call per stripe: every (column, chunk) value stream
        streams = []  # (col, k, stats)
        missing = []  # columns added after this stripe was written
        for col in columns:
            sname = self.schema.scan_storage_name(col)
            if sname not in footer.columns:
                missing.append(col)
                continue
            for k, ci in enumerate(sel_idx):
                streams.append((col, k, footer.columns[sname][ci]))
        offs = np.array([s.value_offset for _, _, s in streams], np.int64)
        clens = np.array([s.value_length for _, _, s in streams], np.int64)
        rlens = np.array([s.value_raw_length for _, _, s in streams], np.int64)
        dsts = np.concatenate([[0], np.cumsum(rlens)[:-1]]).astype(np.int64)
        total = int(rlens.sum())
        out = np.empty(max(total, 1), np.uint8)
        if len(streams) >= 8:
            # thread-pooled read+decompress (each worker owns a file
            # handle + scratch) — saturates cold-scan bandwidth
            nt = decode_thread_count()
            rc = lib.ct_read_streams_mt(
                path.encode(), cid, len(streams),
                offs.ctypes.data_as(i64p), clens.ctypes.data_as(i64p),
                rlens.ctypes.data_as(i64p), dsts.ctypes.data_as(i64p),
                out.ctypes.data_as(u8p), max(total, 1), nt)
        else:
            scratch = np.empty(max(int(clens.max(initial=0)), 1), np.uint8)
            rc = lib.ct_read_streams(
                path.encode(), cid, len(streams),
                offs.ctypes.data_as(i64p), clens.ctypes.data_as(i64p),
                rlens.ctypes.data_as(i64p), dsts.ctypes.data_as(i64p),
                out.ctypes.data_as(u8p), max(total, 1),
                scratch.ctypes.data_as(u8p), len(scratch))
        if rc != 0:
            return None  # fall back to the python reader
        per_col_vals: dict[str, list] = {c: [None] * len(sel_idx) for c in columns}
        per_col_valid: dict[str, list] = {c: [None] * len(sel_idx) for c in columns}
        for si, (col, k, s) in enumerate(streams):
            dt = self.schema.scan_column(col).type.storage_dtype
            arr = out[dsts[si]:dsts[si] + rlens[si]].view(dt)
            if arr.shape[0] != s.row_count:
                return None
            per_col_vals[col][k] = arr
        for col in missing:
            dt = self.schema.scan_column(col).type.storage_dtype
            for k, ci in enumerate(sel_idx):
                n_ = footer.chunk_row_counts[ci]
                per_col_vals[col][k] = np.zeros(n_, dt)
                per_col_valid[col][k] = np.zeros(n_, bool)
        # validity streams (usually few; read individually)
        null_streams = [(col, k, footer.columns[self.schema.scan_storage_name(col)][ci])
                        for col in columns if col not in missing
                        for k, ci in enumerate(sel_idx)
                        if footer.columns[self.schema.scan_storage_name(col)][ci].has_nulls]
        if null_streams:
            from citus_tpu.storage import compression as comp
            with open(path, "rb") as fh:
                for col, k, s in null_streams:
                    fh.seek(s.exists_offset)
                    braw = comp.decompress(fh.read(s.exists_length),
                                           footer.codec, s.exists_raw_length)
                    bits = np.frombuffer(braw, np.uint8)
                    unpacked = np.empty(s.row_count, np.uint8)
                    lib.ct_unpack_bits(
                        bits.ctypes.data_as(u8p), s.row_count,
                        unpacked.ctypes.data_as(u8p))
                    per_col_valid[col][k] = unpacked.astype(bool)
        out_batches = []
        for k, ci in enumerate(sel_idx):
            out_batches.append(ChunkBatch(
                values={c: per_col_vals[c][k] for c in columns},
                validity={c: per_col_valid[c][k] for c in columns},
                row_count=footer.chunk_row_counts[ci],
                stripe_file=os.path.basename(path), chunk_index=ci))
        return out_batches

    def chunk_counts(self, constraints: Optional[list[Interval]] = None) -> tuple[int, int]:
        """(selected_chunks, total_chunks) — for EXPLAIN/statistics."""
        sel = tot = 0
        for stripe in self.meta["stripes"]:
            footer = read_stripe_footer(os.path.join(self.directory, stripe["file"]))
            mask = self._selected_chunks(footer, constraints or [])
            sel += int(mask.sum())
            tot += footer.chunk_count
        return sel, tot

    def _selected_chunks(self, footer, constraints: list[Interval]) -> np.ndarray:
        mask = np.ones(footer.chunk_count, dtype=bool)
        for c in constraints:
            try:
                sname = self.schema.scan_storage_name(c.column)
            except AnalysisError:
                sname = c.column
            chunks = footer.columns.get(sname)
            if chunks is None:
                # column added after this stripe: every row is NULL there,
                # so no range constraint can match
                mask[:] = False
                return mask
            for ci, stats in enumerate(chunks):
                if not mask[ci]:
                    continue
                if stats.row_count == stats.null_count:
                    mask[ci] = False  # all null: no row can match a range
                    continue
                if not c.admits(stats.minimum, stats.maximum):
                    mask[ci] = False
        return mask
