"""Per-stripe index segments: exact row positions by column value.

Reference: the reference builds btree indexes over columnar via
columnar_index_build_range_scan (columnar_tableam.c:1444) and random
row-number access (columnar_reader.c:370-391); index DDL propagates
through commands/index.c.  The TPU-native shape keeps stripes immutable
and stores, beside each stripe, one segment per indexed column: the
stripe's valid physical values sorted, plus the row offsets that order
them.  Lookups are two binary searches; segments are immutable and
travel with the stripe file (shard moves copy the directory).

A missing segment (stripe written before CREATE INDEX, or by a writer
unaware of the index) degrades to a full read of that stripe's column —
never wrong, just slower; backfill_index() closes the gap.
"""

from __future__ import annotations

import os
from typing import Optional

import numpy as np


def segment_path(directory: str, stripe_file: str, column: str) -> str:
    return os.path.join(directory, f"{stripe_file}.idx.{column}.npz")


def build_segment(directory: str, stripe_file: str, column: str,
                  values: np.ndarray, validity: Optional[np.ndarray]) -> None:
    """Persist the sorted (value -> row offset) segment for one stripe.
    ``values`` are the stripe's physical values in row order; invalid
    (NULL) rows are excluded — NULL never equals anything."""
    values = np.asarray(values)
    if validity is not None:
        pos = np.nonzero(np.asarray(validity))[0].astype(np.int64)
        vals = values[pos]
    else:
        pos = np.arange(len(values), dtype=np.int64)
        vals = values
    order = np.argsort(vals, kind="stable")
    p = segment_path(directory, stripe_file, column)
    tmp = p + ".tmp"
    with open(tmp, "wb") as fh:
        # lint: disable=CONF01 -- on-disk index segment format, not wire traffic (the wire codecs live in net/data_plane.py)
        np.savez(fh, sv=vals[order], pos=pos[order])
    os.replace(tmp, p)


def load_segment(directory: str, stripe_file: str, column: str):
    """-> (sorted_values, positions) or None when no segment exists."""
    p = segment_path(directory, stripe_file, column)
    if not os.path.exists(p):
        return None
    # lint: disable=CONF01 -- on-disk index segment format, not wire traffic (the wire codecs live in net/data_plane.py)
    with np.load(p) as z:
        return z["sv"], z["pos"]


def drop_segments(directory: str, column: str) -> None:
    """Remove a column's segments in one placement (DROP INDEX)."""
    suffix = f".idx.{column}.npz"
    for f in os.listdir(directory):
        if f.endswith(suffix):
            try:
                os.remove(os.path.join(directory, f))
            except OSError:
                pass


def positions_eq(directory: str, stripe_file: str, column: str,
                 value) -> Optional[np.ndarray]:
    """Row offsets within the stripe whose column equals ``value``;
    None when the stripe has no segment (caller must scan)."""
    seg = load_segment(directory, stripe_file, column)
    if seg is None:
        return None
    sv, pos = seg
    lo = np.searchsorted(sv, value, "left")
    hi = np.searchsorted(sv, value, "right")
    return pos[lo:hi]


def probe_any(directory: str, stripe_file: str, column: str,
              values: np.ndarray) -> Optional[np.ndarray]:
    """Per-value bool: does the stripe contain this value?  None when no
    segment exists (caller must scan).  Vectorized searchsorted — the
    uniqueness-probe fast path."""
    seg = load_segment(directory, stripe_file, column)
    if seg is None:
        return None
    sv, _pos = seg
    lo = np.searchsorted(sv, values, "left")
    hi = np.searchsorted(sv, values, "right")
    return hi > lo


def matching_positions(directory: str, stripe_file: str, column: str,
                       values: np.ndarray):
    """-> (per-value bool mask, concatenated row offsets) of rows whose
    column equals any of ``values``; None when no segment exists."""
    seg = load_segment(directory, stripe_file, column)
    if seg is None:
        return None
    sv, pos = seg
    lo = np.searchsorted(sv, values, "left")
    hi = np.searchsorted(sv, values, "right")
    found = hi > lo
    if not found.any():
        return found, np.empty(0, np.int64)
    parts = [pos[int(a):int(b)] for a, b in zip(lo[found], hi[found])]
    return found, np.concatenate(parts)


def backfill_index(cat, table, columns: list[str]) -> int:
    """Build missing segments for every stripe of every placement
    (CREATE INDEX on existing data).  Returns segments built."""
    from citus_tpu.schema import Schema  # noqa: F401 (typing aid)
    from citus_tpu.storage.reader import ShardReader

    built = 0
    for shard in table.shards:
        for node in shard.placements:
            d = cat.shard_dir(table.name, shard.shard_id, node)
            if not os.path.isdir(d):
                continue
            reader = ShardReader(d, table.schema)
            for stripe in reader.meta["stripes"]:
                sf = stripe["file"]
                missing = [c for c in columns
                           if not os.path.exists(segment_path(d, sf, c))]
                if not missing:
                    continue
                # accumulate the stripe's full column(s) in row order
                vals = {c: [] for c in missing}
                valid = {c: [] for c in missing}
                for batch in reader.scan(missing, apply_deletes=False,
                                         only_stripes={sf}):
                    for c in missing:
                        vals[c].append(batch.values[c])
                        m = batch.validity[c]
                        valid[c].append(
                            np.ones(batch.row_count, bool) if m is None
                            else m)
                for c in missing:
                    if not vals[c]:
                        continue
                    build_segment(d, sf, c, np.concatenate(vals[c]),
                                  np.concatenate(valid[c]))
                    built += 1
    return built
