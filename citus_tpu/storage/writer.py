"""Shard writer: buffers column batches into chunk groups and stripes.

Reference analog: ColumnarBeginWrite/ColumnarWriteRow/FlushStripe
(src/backend/columnar/columnar_writer.c:97,169,392) and the write-state
management that makes a transaction's pending writes visible to its own
scans (src/backend/columnar/write_state_management.c).  Here ingest is
batch-columnar from the start (the distributed COPY path hands us column
arrays), so the writer never sees single rows.
"""

from __future__ import annotations

import json
import os
from typing import Optional

import numpy as np

from citus_tpu.errors import StorageError
from citus_tpu.schema import Schema
from citus_tpu.storage.format import write_stripe_file

SHARD_META = "shard_meta.json"


class _meta_flock:
    """Serializes shard-metadata read-modify-write across threads and
    processes (two coordinators may ingest into one placement)."""

    def __init__(self, directory: str):
        self._path = os.path.join(directory, ".meta.lock")
        self._fd = None

    def __enter__(self):
        import fcntl
        self._fd = os.open(self._path, os.O_CREAT | os.O_RDWR)
        fcntl.flock(self._fd, fcntl.LOCK_EX)
        return self

    def __exit__(self, *exc):
        import fcntl
        fcntl.flock(self._fd, fcntl.LOCK_UN)
        os.close(self._fd)
        return False


def _load_meta(directory: str) -> dict:
    p = os.path.join(directory, SHARD_META)
    if not os.path.exists(p):
        return {"stripes": [], "row_count": 0, "next_stripe_id": 1}
    with open(p) as fh:
        return json.load(fh)


def _store_meta(directory: str, meta: dict) -> None:
    p = os.path.join(directory, SHARD_META)
    tmp = p + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(meta, fh)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, p)


# ---- staged (two-phase) metadata ---------------------------------------
# A transactional write appends stripes as usual but records them in a
# per-transaction side file; only commit_staged makes them visible by
# merging into the live metadata (reference analog: the write-visibility
# StripeWriteState machine, columnar.h:190-207, where a stripe exists on
# disk before its catalog row commits).

def _staged_path(directory: str, xid: int) -> str:
    return os.path.join(directory, f"{SHARD_META}.staged.{xid}")


def _load_staged(directory: str, xid: int) -> dict:
    p = _staged_path(directory, xid)
    if not os.path.exists(p):
        return {"stripes": [], "row_count": 0}
    with open(p) as fh:
        return json.load(fh)


def _store_staged(directory: str, xid: int, staged: dict) -> None:
    p = _staged_path(directory, xid)
    tmp = p + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(staged, fh)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, p)


def commit_staged(directory: str, xid: int) -> None:
    """Merge a transaction's staged stripes into the live metadata.
    Idempotent: safe to re-run during 2PC roll-forward."""
    staged = _load_staged(directory, xid)
    p = _staged_path(directory, xid)
    if not staged["stripes"]:
        if os.path.exists(p):
            os.remove(p)
        return
    with _meta_flock(directory):
        meta = _load_meta(directory)
        live_names = {s["file"] for s in meta["stripes"]}
        for s in staged["stripes"]:
            if s["file"] in live_names:
                continue  # already applied
            meta["stripes"].append(s)
            meta["row_count"] += s["row_count"]
            sid = int(s["file"].split("-")[1].split(".")[0])
            meta["next_stripe_id"] = max(meta["next_stripe_id"], sid + 1)
        _store_meta(directory, meta)
    os.remove(p)


def abort_staged(directory: str, xid: int) -> None:
    """Delete a transaction's staged stripes + side file (rollback)."""
    staged = _load_staged(directory, xid)
    if staged["stripes"]:
        listing = os.listdir(directory)
    for s in staged["stripes"]:
        fp = os.path.join(directory, s["file"])
        if os.path.exists(fp):
            os.remove(fp)
        # index segments travel with their stripe file
        for f in listing:
            if f.startswith(s["file"] + ".idx."):
                try:
                    os.remove(os.path.join(directory, f))
                except OSError:
                    pass
    p = _staged_path(directory, xid)
    if os.path.exists(p):
        os.remove(p)


class ShardWriter:
    """Append-only writer for one shard of one table."""

    def __init__(self, directory: str, schema: Schema, *, chunk_row_limit: int,
                 stripe_row_limit: int, codec: str = "zstd", level: int = 3,
                 staged_xid: int | None = None,
                 index_columns: tuple[str, ...] = ()):
        if stripe_row_limit % chunk_row_limit != 0:
            raise StorageError("stripe_row_limit must be a multiple of chunk_row_limit")
        self.directory = directory
        self.schema = schema
        self.chunk_row_limit = chunk_row_limit
        self.stripe_row_limit = stripe_row_limit
        self.codec = codec
        self.level = level
        self.staged_xid = staged_xid
        # columns with a secondary index: each flushed stripe also gets a
        # sorted value->offset segment per indexed column
        self.index_columns = tuple(index_columns)
        os.makedirs(directory, exist_ok=True)
        # sketch columns store dictionary ids whose order says nothing
        # about the state they name — never write min/max skip stats
        from citus_tpu.types import SKETCH
        self._no_stats_columns = frozenset(
            c.storage_name for c in schema if c.type.kind == SKETCH)
        # physical stream names: schema columns plus the int64 lane
        # companion each uuid column carries ("<name>::lo")
        self._names = schema.physical_names()
        self._buf: dict[str, list[np.ndarray]] = {n: [] for n in self._names}
        self._buf_valid: dict[str, list[np.ndarray]] = {n: [] for n in self._names}
        self._buf_rows = 0

    # ------------------------------------------------------------------
    def append_batch(self, values: dict[str, np.ndarray],
                     validity: Optional[dict[str, np.ndarray]] = None) -> None:
        """Append a column batch.  ``values[col]`` are physical-encoded
        arrays, all the same length; ``validity[col]`` bool arrays (missing
        key = all valid)."""
        lengths = {len(v) for v in values.values()}
        if len(lengths) != 1:
            raise StorageError("ragged column batch")
        n = lengths.pop()
        if n == 0:
            return
        if set(values) != set(self._buf):
            raise StorageError(f"batch columns {sorted(values)} != schema {sorted(self._buf)}")
        for col in self._names:
            v = np.asarray(values[col], dtype=self.schema.scan_dtype(col))
            self._buf[col].append(v)
            va = None if validity is None else validity.get(col)
            if va is None and validity is not None:
                # lane streams share the base uuid column's validity
                from citus_tpu.types import is_uuid_lane, uuid_lane_base
                if is_uuid_lane(col):
                    va = validity.get(uuid_lane_base(col))
            self._buf_valid[col].append(
                np.ones(n, dtype=bool) if va is None else np.asarray(va, dtype=bool))
        self._buf_rows += n
        while self._buf_rows >= self.stripe_row_limit:
            self._flush_rows(self.stripe_row_limit)

    def flush(self) -> None:
        """Flush any buffered rows as a (possibly short) final stripe."""
        if self._buf_rows:
            self._flush_rows(self._buf_rows)

    @property
    def row_count(self) -> int:
        return _load_meta(self.directory)["row_count"] + self._buf_rows

    # ------------------------------------------------------------------
    def _take(self, store: dict, col: str, n: int) -> np.ndarray:
        chunks, got, out = store[col], 0, []
        while got < n:
            head = chunks[0]
            take = min(n - got, len(head))
            out.append(head[:take])
            if take == len(head):
                chunks.pop(0)
            else:
                chunks[0] = head[take:]
            got += take
        return np.concatenate(out) if len(out) != 1 else out[0]

    def _flush_rows(self, n: int) -> None:
        column_chunks: dict[str, list] = {}
        chunk_rows: list[int] = []
        col_vals = {c: self._take(self._buf, c, n) for c in self._names}
        col_valid = {c: self._take(self._buf_valid, c, n) for c in self._names}
        for start in range(0, n, self.chunk_row_limit):
            stop = min(start + self.chunk_row_limit, n)
            chunk_rows.append(stop - start)
        for col in self._names:
            chunks = []
            for start in range(0, n, self.chunk_row_limit):
                stop = min(start + self.chunk_row_limit, n)
                vals = col_vals[col][start:stop]
                valid = col_valid[col][start:stop]
                # null slots hold 0 so compression and device kernels see
                # deterministic bytes
                if not valid.all():
                    vals = np.where(valid, vals, vals.dtype.type(0))
                    chunks.append((vals, valid))
                else:
                    chunks.append((vals, None))
            column_chunks[self.schema.scan_storage_name(col)] = chunks
        if self.staged_xid is not None:
            # staged stripes get a transaction-unique name so concurrent
            # ingests into one placement can never collide on a file
            staged = _load_staged(self.directory, self.staged_xid)
            meta = _load_meta(self.directory)
            sid = meta["next_stripe_id"] + len(staged["stripes"])
            fname = f"stripe-{sid:06d}-x{self.staged_xid}-p{os.getpid()}.cts"
            write_stripe_file(
                os.path.join(self.directory, fname), column_chunks, chunk_rows,
                self.chunk_row_limit, self.codec, self.level,
                no_stats_columns=self._no_stats_columns)
            self._build_index_segments(fname, col_vals, col_valid)
            staged["stripes"].append({"file": fname, "row_count": n})
            staged["row_count"] += n
            _store_staged(self.directory, self.staged_xid, staged)
        else:
            with _meta_flock(self.directory):
                meta = _load_meta(self.directory)
                sid = meta["next_stripe_id"]
                fname = f"stripe-{sid:06d}.cts"
                write_stripe_file(
                    os.path.join(self.directory, fname), column_chunks, chunk_rows,
                    self.chunk_row_limit, self.codec, self.level,
                    no_stats_columns=self._no_stats_columns)
                self._build_index_segments(fname, col_vals, col_valid)
                meta["stripes"].append({"file": fname, "row_count": n})
                meta["row_count"] += n
                meta["next_stripe_id"] = sid + 1
                _store_meta(self.directory, meta)
        self._buf_rows -= n

    def _build_index_segments(self, fname: str, col_vals, col_valid) -> None:
        """Write each indexed column's segment beside the new stripe
        (before the stripe enters any metadata, so a reader never sees a
        live stripe whose segment is mid-write)."""
        if not self.index_columns:
            return
        from citus_tpu.storage.index import build_segment
        for col in self.index_columns:
            if col in col_vals:
                build_segment(self.directory, fname, col,
                              col_vals[col], col_valid[col])
