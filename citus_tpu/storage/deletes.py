"""Deletion bitmaps.

The reference's columnar access method rejects UPDATE/DELETE outright
(columnar_tableam.c: columnar_fetch_row_version errors); row tables get
them from PostgreSQL's heap.  We close that capability gap the
columnar-native way: stripes stay immutable, and each placement keeps a
side file mapping stripe -> packed deletion bitmap.  Scans subtract the
bitmap; VACUUM rewrites stripes to reclaim the space.  Updates are
delete + re-insert (the moved-row case falls out naturally because
re-inserted rows re-hash to their shard).

The side file supports the same staged/2PC protocol as shard metadata.
"""

from __future__ import annotations

import json
import os

import numpy as np

DELETES_FILE = "deletes.json"


def _path(directory: str) -> str:
    return os.path.join(directory, DELETES_FILE)


def _staged_path(directory: str, xid: int) -> str:
    return os.path.join(directory, f"{DELETES_FILE}.staged.{xid}")


def _encode(mask: np.ndarray) -> str:
    return np.packbits(mask.astype(np.uint8)).tobytes().hex()


def _decode(hexstr: str, n_rows: int) -> np.ndarray:
    bits = np.frombuffer(bytes.fromhex(hexstr), np.uint8)
    return np.unpackbits(bits)[:n_rows].astype(bool)


def load_deletes(directory: str) -> dict[str, str]:
    p = _path(directory)
    if not os.path.exists(p):
        return {}
    with open(p) as fh:
        return json.load(fh)


def deleted_mask(directory: str, stripe_file: str, n_rows: int,
                 cache: dict | None = None) -> np.ndarray | None:
    d = cache if cache is not None else load_deletes(directory)
    h = d.get(stripe_file)
    if h is None:
        return None
    return _decode(h, n_rows)


def _store(path: str, d: dict[str, str]) -> None:
    tmp = path + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(d, fh)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, path)


def stage_deletes(directory: str, xid: int,
                  per_stripe: dict[str, tuple[np.ndarray, int]]) -> None:
    """Stage row deletions: per_stripe[stripe_file] = (row_indexes, n_rows).
    Merges with the placement's existing live bitmap AND with anything
    this transaction already staged (a multi-statement transaction may
    delete from the same stripe twice)."""
    live = load_deletes(directory)
    p = _staged_path(directory, xid)
    if os.path.exists(p):
        with open(p) as fh:
            staged = json.load(fh)
    else:
        staged = {}
    base = dict(live)
    base.update(staged)  # staged bitmaps are supersets of live
    for stripe_file, (idx, n_rows) in per_stripe.items():
        mask = deleted_mask(directory, stripe_file, n_rows, base)
        if mask is None:
            mask = np.zeros(n_rows, bool)
        mask[idx] = True
        staged[stripe_file] = _encode(mask)
    _store(p, staged)


def commit_staged_deletes(directory: str, xid: int) -> None:
    """Merge staged bitmaps into the live file (idempotent).  Deletion
    bits are monotonic, so the merge is a bitwise OR — concurrent DELETE
    transactions staged against the same base bitmap cannot lose each
    other's bits."""
    import fcntl
    p = _staged_path(directory, xid)
    if not os.path.exists(p):
        return
    with open(p) as fh:
        staged = json.load(fh)
    # serialize the read-modify-write across threads AND processes
    lock_fd = os.open(os.path.join(directory, ".deletes.lock"),
                      os.O_CREAT | os.O_RDWR)
    try:
        fcntl.flock(lock_fd, fcntl.LOCK_EX)
        live = load_deletes(directory)
        for stripe_file, h in staged.items():
            cur = live.get(stripe_file)
            if cur is None:
                live[stripe_file] = h
                continue
            a = np.frombuffer(bytes.fromhex(cur), np.uint8)
            b = np.frombuffer(bytes.fromhex(h), np.uint8)
            if len(a) != len(b):  # defensive: pad the shorter side
                n = max(len(a), len(b))
                a = np.pad(a, (0, n - len(a)))
                b = np.pad(b, (0, n - len(b)))
            live[stripe_file] = (a | b).tobytes().hex()
        _store(_path(directory), live)
        os.remove(p)
    finally:
        fcntl.flock(lock_fd, fcntl.LOCK_UN)
        os.close(lock_fd)


def abort_staged_deletes(directory: str, xid: int) -> None:
    p = _staged_path(directory, xid)
    if os.path.exists(p):
        os.remove(p)


def clear_deletes(directory: str) -> None:
    p = _path(directory)
    if os.path.exists(p):
        os.remove(p)


def deleted_count(directory: str, stripe_rows: dict[str, int]) -> int:
    d = load_deletes(directory)
    total = 0
    for stripe_file, h in d.items():
        n = stripe_rows.get(stripe_file)
        if n is not None:
            total += int(_decode(h, n).sum())
    return total
