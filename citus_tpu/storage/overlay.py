"""Transaction snapshot overlay: read-your-writes for open transactions.

Reference analog: write_state_management.c — a transaction's pending
columnar writes are visible to its own scans before commit.  Here a
multi-statement transaction stages stripes and deletion bitmaps in
per-xid side files (writer.py / deletes.py); while a statement of that
transaction executes, a thread-local overlay makes read paths merge the
transaction's own staged state into what they see.  Other sessions never
observe the overlay (their threads carry no overlay), which is exactly
the staged-files-invisible-until-commit isolation the 2PC flip relies
on.

Only *read* paths consult the overlay (``visible_meta`` /
``visible_deletes``); writer internals keep using the raw loaders so a
commit can never accidentally persist overlay-merged metadata as live.
"""

from __future__ import annotations

import contextlib
import os
import threading
from typing import Optional

_tls = threading.local()


def current_overlay():
    """The OpenTransaction whose staged writes this thread should see,
    or None."""
    return getattr(_tls, "txn", None)


def current_overlay_xid() -> Optional[int]:
    txn = current_overlay()
    return None if txn is None else txn.xid


@contextlib.contextmanager
def transaction_overlay(txn):
    """Make ``txn``'s staged writes visible to reads on this thread for
    the duration (statements execute synchronously, so nested reads —
    FK probes, subquery materialization, cascades — inherit it)."""
    prev = getattr(_tls, "txn", None)
    _tls.txn = txn
    try:
        yield
    finally:
        _tls.txn = prev


def visible_meta(directory: str) -> dict:
    """Shard metadata as this thread should see it: live stripes plus
    the active transaction's staged stripes for this placement."""
    from citus_tpu.storage.writer import _load_meta, _load_staged

    meta = _load_meta(directory)
    xid = current_overlay_xid()
    if xid is None:
        return meta
    staged = _load_staged(directory, xid)
    if not staged["stripes"]:
        return meta
    live_names = {s["file"] for s in meta["stripes"]}
    merged = dict(meta)
    merged["stripes"] = list(meta["stripes"]) + [
        s for s in staged["stripes"] if s["file"] not in live_names]
    merged["row_count"] = meta["row_count"] + sum(
        s["row_count"] for s in staged["stripes"]
        if s["file"] not in live_names)
    return merged


def visible_deletes(directory: str) -> dict:
    """Deletion bitmaps as this thread should see them: live bitmaps
    with the active transaction's staged bitmaps layered on top (staged
    bitmaps are supersets of live for their stripes — stage_deletes
    merges at stage time)."""
    from citus_tpu.storage.deletes import _staged_path, load_deletes
    import json

    live = load_deletes(directory)
    xid = current_overlay_xid()
    if xid is None:
        return live
    p = _staged_path(directory, xid)
    if not os.path.exists(p):
        return live
    with open(p) as fh:
        staged = json.load(fh)
    merged = dict(live)
    merged.update(staged)
    return merged
