"""Columnar storage engine.

TPU-native re-design of the reference columnar table access method
(reference: src/backend/columnar/ — columnar_writer.c, columnar_reader.c,
columnar_metadata.c, columnar_compression.c).  Same structural ideas:

- a shard's data is an append-only sequence of immutable *stripes*
- a stripe holds *chunk groups* of a fixed row limit
- per column per chunk group there are two independently-compressed,
  independently-addressable streams: values and a validity bitmap
- a skip list of per-chunk min/max/null-count enables chunk pruning
  before any decompression happens

Differences by design (TPU-first):

- chunk row limit is a power of two so decompressed chunks form padded
  device batches with no re-layout
- values are fixed-width physical encodings (see citus_tpu.types); text is
  dictionary-encoded at ingest, so kernels only ever see numbers
- stripes are plain files + a JSON footer instead of pages inside
  PostgreSQL's buffer manager; durability is write-temp + rename + catalog
  commit (the catalog, not the data file, is the source of truth —
  mirroring the reference's "metadata is truth, data immutable-append"
  split)
"""

from citus_tpu.storage.format import StripeFooter, ChunkStats, write_stripe_file, read_stripe_footer, read_chunk
from citus_tpu.storage.writer import ShardWriter
from citus_tpu.storage.reader import ShardReader, ChunkBatch, Interval
from citus_tpu.storage.index import (
    backfill_index, build_segment, drop_segments, load_segment,
    matching_positions, positions_eq, probe_any,
)

__all__ = [
    "StripeFooter",
    "ChunkStats",
    "write_stripe_file",
    "read_stripe_footer",
    "read_chunk",
    "ShardWriter",
    "ShardReader",
    "ChunkBatch",
    "Interval",
    "backfill_index",
    "build_segment",
    "drop_segments",
    "load_segment",
    "matching_positions",
    "positions_eq",
    "probe_any",
]
