"""On-disk stripe file format.

Layout of a ``stripe-NNNNNN.cts`` file::

    [8-byte magic "CTPUSTR1"]
    [stream bytes ...]           # concatenated compressed streams
    [footer: JSON, utf-8]
    [uint64 LE footer length]
    [8-byte magic "CTPUSTR1"]

Per column per chunk group there are two streams — values (fixed-width
little-endian physical encoding, see citus_tpu.types) and an optional
validity bitmap (np.packbits; absent when the chunk has no nulls).  The
footer carries the skip list: offsets/lengths plus min/max/null_count per
chunk, the analog of the reference's ColumnChunkSkipNode
(src/include/columnar/columnar.h:85-111) kept in the
columnar_internal.chunk catalog (src/backend/columnar/columnar_metadata.c).

Streams are independently addressable so a reader that pruned chunks (or
projected columns) never reads their bytes — same property the reference
gets from per-chunk existsBuffer/valueBuffer offsets.
"""

from __future__ import annotations

import json
import os
import struct
from dataclasses import dataclass, field
from typing import BinaryIO, Optional

import numpy as np

from citus_tpu.errors import StorageError
from citus_tpu.storage import compression as comp

MAGIC = b"CTPUSTR1"
FORMAT_VERSION = 1


@dataclass
class ChunkStats:
    """Skip-list node for one (column, chunk group)."""

    value_offset: int = 0
    value_length: int = 0          # compressed bytes
    value_raw_length: int = 0      # uncompressed bytes
    exists_offset: int = 0
    exists_length: int = 0
    exists_raw_length: int = 0
    has_nulls: bool = False
    null_count: int = 0
    row_count: int = 0
    minimum: Optional[float] = None  # physical value; None if all-null
    maximum: Optional[float] = None

    def to_json(self):
        return {
            "vo": self.value_offset, "vl": self.value_length, "vr": self.value_raw_length,
            "eo": self.exists_offset, "el": self.exists_length, "er": self.exists_raw_length,
            "hn": self.has_nulls, "nc": self.null_count, "rc": self.row_count,
            "mn": self.minimum, "mx": self.maximum,
        }

    @staticmethod
    def from_json(d) -> "ChunkStats":
        return ChunkStats(
            value_offset=d["vo"], value_length=d["vl"], value_raw_length=d["vr"],
            exists_offset=d["eo"], exists_length=d["el"], exists_raw_length=d["er"],
            has_nulls=d["hn"], null_count=d["nc"], row_count=d["rc"],
            minimum=d["mn"], maximum=d["mx"],
        )


@dataclass
class StripeFooter:
    row_count: int
    chunk_row_limit: int
    chunk_row_counts: list[int]
    codec: str
    columns: dict[str, list[ChunkStats]] = field(default_factory=dict)
    format_version: int = FORMAT_VERSION

    @property
    def chunk_count(self) -> int:
        return len(self.chunk_row_counts)

    def to_json(self) -> dict:
        return {
            "format_version": self.format_version,
            "row_count": self.row_count,
            "chunk_row_limit": self.chunk_row_limit,
            "chunk_row_counts": self.chunk_row_counts,
            "codec": self.codec,
            "columns": {name: [c.to_json() for c in chunks] for name, chunks in self.columns.items()},
        }

    @staticmethod
    def from_json(d: dict) -> "StripeFooter":
        f = StripeFooter(
            row_count=d["row_count"],
            chunk_row_limit=d["chunk_row_limit"],
            chunk_row_counts=d["chunk_row_counts"],
            codec=d["codec"],
            format_version=d["format_version"],
        )
        f.columns = {name: [ChunkStats.from_json(c) for c in chunks] for name, chunks in d["columns"].items()}
        return f


def _np_to_jsonable(v):
    if v is None:
        return None
    if isinstance(v, (np.integer,)):
        return int(v)
    if isinstance(v, (np.floating,)):
        fv = float(v)
        if fv != fv:  # NaN has no JSON form; drop the stat
            return None
        return fv
    return v


def write_stripe_file(
    path: str,
    column_chunks: dict[str, list[tuple[np.ndarray, Optional[np.ndarray]]]],
    chunk_row_counts: list[int],
    chunk_row_limit: int,
    codec: str,
    level: int,
    no_stats_columns: frozenset = frozenset(),
) -> StripeFooter:
    """Write one stripe atomically (temp file + rename).

    ``column_chunks[col]`` is a list of (values, validity) per chunk group;
    validity is a bool array or None when the chunk has no nulls.  Min/max
    stats are computed over valid rows only, like the reference's
    UpdateChunkSkipNodeMinMax (columnar_writer.c:664).
    ``no_stats_columns`` suppresses min/max for columns whose physical ids
    carry no value order (sketch state words): a skip node of None means
    "cannot prune", which is the only correct answer there.
    """
    footer = StripeFooter(
        row_count=int(sum(chunk_row_counts)),
        chunk_row_limit=chunk_row_limit,
        chunk_row_counts=[int(c) for c in chunk_row_counts],
        codec=codec,
    )
    tmp = path + ".tmp"
    with open(tmp, "wb") as fh:
        fh.write(MAGIC)
        offset = len(MAGIC)
        for name, chunks in column_chunks.items():
            stats_list = []
            for (values, validity) in chunks:
                cs = ChunkStats(row_count=int(values.shape[0]))
                raw = np.ascontiguousarray(values).tobytes()
                cdata = comp.compress(raw, codec, level)
                cs.value_offset, cs.value_length, cs.value_raw_length = offset, len(cdata), len(raw)
                fh.write(cdata)
                offset += len(cdata)
                if validity is not None and not bool(validity.all()):
                    bits = np.packbits(validity.astype(np.uint8))
                    braw = bits.tobytes()
                    bdata = comp.compress(braw, codec, level)
                    cs.exists_offset, cs.exists_length, cs.exists_raw_length = offset, len(bdata), len(braw)
                    cs.has_nulls = True
                    cs.null_count = int(values.shape[0] - int(validity.sum()))
                    fh.write(bdata)
                    offset += len(bdata)
                    valid_vals = values[validity]
                else:
                    valid_vals = values
                if valid_vals.size and name not in no_stats_columns:
                    cs.minimum = _np_to_jsonable(valid_vals.min())
                    cs.maximum = _np_to_jsonable(valid_vals.max())
                stats_list.append(cs)
            footer.columns[name] = stats_list
        fj = json.dumps(footer.to_json(), separators=(",", ":")).encode()
        fh.write(fj)
        fh.write(struct.pack("<Q", len(fj)))
        fh.write(MAGIC)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, path)
    return footer


def read_stripe_footer(path: str) -> StripeFooter:
    with open(path, "rb") as fh:
        fh.seek(0, os.SEEK_END)
        size = fh.tell()
        if size < len(MAGIC) * 2 + 8:
            raise StorageError(f"stripe file too small: {path}")
        fh.seek(size - len(MAGIC) - 8)
        tail = fh.read(8 + len(MAGIC))
        if tail[8:] != MAGIC:
            raise StorageError(f"bad trailing magic in {path}")
        (flen,) = struct.unpack("<Q", tail[:8])
        fh.seek(size - len(MAGIC) - 8 - flen)
        fj = fh.read(flen)
        fh.seek(0)
        if fh.read(len(MAGIC)) != MAGIC:
            raise StorageError(f"bad leading magic in {path}")
        return StripeFooter.from_json(json.loads(fj.decode()))


def read_chunk(
    fh: BinaryIO,
    footer: StripeFooter,
    stats: ChunkStats,
    storage_dtype: np.dtype,
) -> tuple[np.ndarray, Optional[np.ndarray]]:
    """Read + decompress one (column, chunk) -> (values, validity|None)."""
    fh.seek(stats.value_offset)
    raw = comp.decompress(fh.read(stats.value_length), footer.codec, stats.value_raw_length)
    values = np.frombuffer(raw, dtype=storage_dtype).copy()
    if values.shape[0] != stats.row_count:
        raise StorageError("chunk row count mismatch")
    validity = None
    if stats.has_nulls:
        fh.seek(stats.exists_offset)
        braw = comp.decompress(fh.read(stats.exists_length), footer.codec, stats.exists_raw_length)
        bits = np.frombuffer(braw, dtype=np.uint8)
        validity = np.unpackbits(bits)[: stats.row_count].astype(bool)
    return values, validity
