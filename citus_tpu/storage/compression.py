"""Compression codecs for column streams.

Reference: src/backend/columnar/columnar_compression.c (pglz/LZ4/ZSTD).
We provide zstd (python-zstandard), zlib (stdlib, the pglz stand-in), lz4
(via the system liblz4 through ctypes — no Python lz4 package is assumed),
and none.  A native C++ batch-decompression path lives in
citus_tpu/native and is used automatically when built; this module is the
portable fallback and the single place codec ids are defined.
"""

from __future__ import annotations

import ctypes
import ctypes.util
import zlib

from citus_tpu.errors import StorageError

try:
    import zstandard as _zstd
except ImportError:  # pragma: no cover
    _zstd = None

CODEC_NONE = "none"
CODEC_ZSTD = "zstd"
CODEC_LZ4 = "lz4"
CODEC_ZLIB = "zlib"

_lz4 = None


def _load_lz4():
    global _lz4
    if _lz4 is not None:
        return _lz4
    path = ctypes.util.find_library("lz4") or "liblz4.so.1"
    try:
        lib = ctypes.CDLL(path)
    except OSError as e:  # pragma: no cover
        raise StorageError(f"liblz4 not available: {e}")
    lib.LZ4_compress_default.restype = ctypes.c_int
    lib.LZ4_compress_default.argtypes = [ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int, ctypes.c_int]
    lib.LZ4_compressBound.restype = ctypes.c_int
    lib.LZ4_compressBound.argtypes = [ctypes.c_int]
    lib.LZ4_decompress_safe.restype = ctypes.c_int
    lib.LZ4_decompress_safe.argtypes = [ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int, ctypes.c_int]
    _lz4 = lib
    return lib


def _native():
    from citus_tpu.native import CODEC_IDS, get_lib
    return get_lib(), CODEC_IDS


def compress(data: bytes, codec: str, level: int = 3) -> bytes:
    if codec == CODEC_NONE:
        return data
    lib, ids = _native()
    if lib is not None and codec in ids:
        import numpy as np
        cid = ids[codec]
        bound = lib.ct_compress_bound(cid, len(data))
        out = np.empty(bound, np.uint8)
        src = np.frombuffer(data, np.uint8)
        n = lib.ct_compress(
            cid, src.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), len(data),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), bound, level)
        if n > 0:
            return out[:n].tobytes()
    if codec == CODEC_ZSTD:
        if _zstd is None:
            # no python-zstandard and no native backend: degrade to the
            # stdlib codec instead of making every write path unusable.
            # decompress() mirrors the fallback, so files written in
            # this environment round-trip; genuine zstd bytes from
            # elsewhere still fail cleanly there.
            return zlib.compress(data, min(level, 9))
        return _zstd.ZstdCompressor(level=level).compress(data)
    if codec == CODEC_ZLIB:
        return zlib.compress(data, min(level, 9))
    if codec == CODEC_LZ4:
        lib = _load_lz4()
        bound = lib.LZ4_compressBound(len(data))
        out = ctypes.create_string_buffer(bound)
        n = lib.LZ4_compress_default(data, out, len(data), bound)
        if n <= 0:
            raise StorageError("LZ4 compression failed")
        return out.raw[:n]
    raise StorageError(f"unknown codec {codec!r}")


def decompress(data: bytes, codec: str, raw_size: int) -> bytes:
    if codec == CODEC_NONE:
        return data
    lib, ids = _native()
    if lib is not None and codec in ids:
        import numpy as np
        out = np.empty(raw_size, np.uint8)
        src = np.frombuffer(data, np.uint8)
        n = lib.ct_decompress(
            ids[codec], src.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            len(data), out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            raw_size)
        if n == raw_size:
            return out.tobytes()
        if n >= 0:
            return out[:n].tobytes()
    if codec == CODEC_ZSTD:
        if _zstd is None:
            try:
                # mirror of the compress() fallback: zstd-labelled data
                # written without a zstd backend is zlib bytes
                return zlib.decompress(data)
            except zlib.error as e:
                raise StorageError(
                    "zstd-compressed data but no zstd backend available "
                    "(install zstandard or build the native codec)") from e
        return _zstd.ZstdDecompressor().decompress(data, max_output_size=raw_size)
    if codec == CODEC_ZLIB:
        return zlib.decompress(data)
    if codec == CODEC_LZ4:
        lib = _load_lz4()
        out = ctypes.create_string_buffer(raw_size)
        n = lib.LZ4_decompress_safe(data, out, len(data), raw_size)
        if n < 0:
            raise StorageError("LZ4 decompression failed")
        return out.raw[:n]
    raise StorageError(f"unknown codec {codec!r}")
