// Native columnar runtime: the IO + codec hot path.
//
// The reference implements its columnar engine in C inside PostgreSQL
// (src/backend/columnar/columnar_compression.c, columnar_reader.c);
// this library is the equivalent native layer under the Python/JAX
// planner: batch chunk reads (one pread per stream), zstd/lz4/zlib
// decompression, and validity-bitmap unpacking, all without the
// per-chunk Python overhead.  Exposed through a plain C ABI consumed
// via ctypes (no pybind11 dependency).

#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <thread>
#include <vector>

#include <zstd.h>
#include <zlib.h>

extern "C" {
// liblz4 runtime is present; header is not — declare what we use.
int LZ4_decompress_safe(const char* src, char* dst, int srcSize, int dstCapacity);
int LZ4_compress_default(const char* src, char* dst, int srcSize, int dstCapacity);
int LZ4_compressBound(int inputSize);
}

enum Codec : int32_t {
    CODEC_NONE = 0,
    CODEC_ZSTD = 1,
    CODEC_LZ4 = 2,
    CODEC_ZLIB = 3,
};

extern "C" {

// ---- single-shot codecs -------------------------------------------------

// returns decompressed size, or -1 on failure
int64_t ct_decompress(int32_t codec, const uint8_t* src, int64_t src_len,
                      uint8_t* dst, int64_t dst_cap) {
    switch (codec) {
        case CODEC_NONE:
            if (src_len > dst_cap) return -1;
            memcpy(dst, src, (size_t)src_len);
            return src_len;
        case CODEC_ZSTD: {
            size_t n = ZSTD_decompress(dst, (size_t)dst_cap, src, (size_t)src_len);
            if (ZSTD_isError(n)) return -1;
            return (int64_t)n;
        }
        case CODEC_LZ4: {
            int n = LZ4_decompress_safe((const char*)src, (char*)dst,
                                        (int)src_len, (int)dst_cap);
            return n < 0 ? -1 : n;
        }
        case CODEC_ZLIB: {
            uLongf out_len = (uLongf)dst_cap;
            int rc = uncompress((Bytef*)dst, &out_len, (const Bytef*)src,
                                (uLong)src_len);
            return rc == Z_OK ? (int64_t)out_len : -1;
        }
    }
    return -1;
}

int64_t ct_compress(int32_t codec, const uint8_t* src, int64_t src_len,
                    uint8_t* dst, int64_t dst_cap, int32_t level) {
    switch (codec) {
        case CODEC_NONE:
            if (src_len > dst_cap) return -1;
            memcpy(dst, src, (size_t)src_len);
            return src_len;
        case CODEC_ZSTD: {
            size_t n = ZSTD_compress(dst, (size_t)dst_cap, src, (size_t)src_len,
                                     level);
            if (ZSTD_isError(n)) return -1;
            return (int64_t)n;
        }
        case CODEC_LZ4: {
            int n = LZ4_compress_default((const char*)src, (char*)dst,
                                         (int)src_len, (int)dst_cap);
            return n <= 0 ? -1 : n;
        }
        case CODEC_ZLIB: {
            uLongf out_len = (uLongf)dst_cap;
            int rc = compress2((Bytef*)dst, &out_len, (const Bytef*)src,
                               (uLong)src_len, level > 9 ? 9 : level);
            return rc == Z_OK ? (int64_t)out_len : -1;
        }
    }
    return -1;
}

int64_t ct_compress_bound(int32_t codec, int64_t src_len) {
    switch (codec) {
        case CODEC_NONE: return src_len;
        case CODEC_ZSTD: return (int64_t)ZSTD_compressBound((size_t)src_len);
        case CODEC_LZ4:  return (int64_t)LZ4_compressBound((int)src_len);
        case CODEC_ZLIB: return (int64_t)compressBound((uLong)src_len);
    }
    return -1;
}

// ---- batched stripe-chunk reads ----------------------------------------
// Reads n streams from one open file and decompresses each into its slot
// of a caller-provided contiguous output buffer.  This is the native
// inner loop of the stripe reader (one call per (stripe, column) scan).
// returns 0 on success, -(1+i) identifying the failing stream.

int64_t ct_read_streams(const char* path, int32_t codec, int64_t n,
                        const int64_t* offsets, const int64_t* comp_lens,
                        const int64_t* raw_lens, const int64_t* dst_offsets,
                        uint8_t* dst, int64_t dst_cap,
                        uint8_t* scratch, int64_t scratch_cap) {
    FILE* f = fopen(path, "rb");
    if (!f) return -1000000;
    for (int64_t i = 0; i < n; i++) {
        if (comp_lens[i] > scratch_cap) { fclose(f); return -(1 + i); }
        if (dst_offsets[i] + raw_lens[i] > dst_cap) { fclose(f); return -(1 + i); }
        if (fseeko(f, (off_t)offsets[i], SEEK_SET) != 0) { fclose(f); return -(1 + i); }
        if (fread(scratch, 1, (size_t)comp_lens[i], f) != (size_t)comp_lens[i]) {
            fclose(f);
            return -(1 + i);
        }
        int64_t got = ct_decompress(codec, scratch, comp_lens[i],
                                    dst + dst_offsets[i], raw_lens[i]);
        if (got != raw_lens[i]) { fclose(f); return -(1 + i); }
    }
    fclose(f);
    return 0;
}

// ---- parallel batched reads --------------------------------------------
// Same contract as ct_read_streams, but streams are claimed from a
// shared counter by a small thread pool; each worker owns a file handle
// and scratch buffer.  The reference parallelizes scans across worker
// backends; within one host process this is the analog for saturating
// storage + decompression bandwidth on cold scans.

int64_t ct_read_streams_mt(const char* path, int32_t codec, int64_t n,
                           const int64_t* offsets, const int64_t* comp_lens,
                           const int64_t* raw_lens, const int64_t* dst_offsets,
                           uint8_t* dst, int64_t dst_cap, int32_t n_threads) {
    std::atomic<int64_t> err{0};
    std::atomic<int64_t> next{0};
    auto worker = [&]() {
        FILE* f = fopen(path, "rb");
        if (!f) {
            int64_t expect = 0;
            err.compare_exchange_strong(expect, -1000000);
            return;
        }
        std::vector<uint8_t> scratch;
        while (err.load(std::memory_order_relaxed) == 0) {
            int64_t i = next.fetch_add(1);
            if (i >= n) break;
            int64_t fail = -(1 + i), expect = 0;
            if ((int64_t)scratch.size() < comp_lens[i]) {
                scratch.resize((size_t)comp_lens[i]);
            }
            if (dst_offsets[i] + raw_lens[i] > dst_cap ||
                fseeko(f, (off_t)offsets[i], SEEK_SET) != 0 ||
                fread(scratch.data(), 1, (size_t)comp_lens[i], f)
                    != (size_t)comp_lens[i] ||
                ct_decompress(codec, scratch.data(), comp_lens[i],
                              dst + dst_offsets[i], raw_lens[i]) != raw_lens[i]) {
                err.compare_exchange_strong(expect, fail);
                break;
            }
        }
        fclose(f);
    };
    int nt = n_threads < 1 ? 1 : (n_threads > 16 ? 16 : n_threads);
    if ((int64_t)nt > n) nt = (int)n;
    std::vector<std::thread> threads;
    for (int t = 0; t < nt; t++) threads.emplace_back(worker);
    for (auto& t : threads) t.join();
    return err.load();
}

// ---- validity bitmap unpack --------------------------------------------
// big-endian bit order, matching numpy packbits

void ct_unpack_bits(const uint8_t* src, int64_t n_bits, uint8_t* dst) {
    for (int64_t i = 0; i < n_bits; i++) {
        dst[i] = (src[i >> 3] >> (7 - (i & 7))) & 1;
    }
}

int32_t ct_version(void) { return 1; }

}  // extern "C"
