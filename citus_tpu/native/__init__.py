"""ctypes bindings for the native columnar runtime.

Auto-builds libcitus_tpu_native.so with make on first use (a few
seconds, cached); every caller must tolerate ``LIB is None`` and fall
back to the pure-Python path, so the framework works even without a
toolchain.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_HERE = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_HERE, "libcitus_tpu_native.so")
_lock = threading.Lock()
_attempted = False

LIB = None

CODEC_IDS = {"none": 0, "zstd": 1, "lz4": 2, "zlib": 3}


def _try_build() -> bool:
    src = os.path.join(_HERE, "columnar_native.cpp")
    if os.path.exists(_SO) and os.path.getmtime(_SO) >= os.path.getmtime(src):
        return True
    try:
        subprocess.run(["make", "-C", _HERE], capture_output=True, timeout=120,
                       check=True)
        return os.path.exists(_SO)
    except Exception:
        return False


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    i64, i32 = ctypes.c_int64, ctypes.c_int32
    u8p = ctypes.POINTER(ctypes.c_uint8)
    i64p = ctypes.POINTER(ctypes.c_int64)
    lib.ct_decompress.restype = i64
    lib.ct_decompress.argtypes = [i32, u8p, i64, u8p, i64]
    lib.ct_compress.restype = i64
    lib.ct_compress.argtypes = [i32, u8p, i64, u8p, i64, i32]
    lib.ct_compress_bound.restype = i64
    lib.ct_compress_bound.argtypes = [i32, i64]
    lib.ct_read_streams.restype = i64
    lib.ct_read_streams.argtypes = [ctypes.c_char_p, i32, i64, i64p, i64p,
                                    i64p, i64p, u8p, i64, u8p, i64]
    lib.ct_read_streams_mt.restype = i64
    lib.ct_read_streams_mt.argtypes = [ctypes.c_char_p, i32, i64, i64p, i64p,
                                       i64p, i64p, u8p, i64, i32]
    lib.ct_unpack_bits.restype = None
    lib.ct_unpack_bits.argtypes = [u8p, i64, u8p]
    lib.ct_version.restype = i32
    lib.ct_version.argtypes = []
    return lib


def get_lib():
    """The bound native library, or None when unavailable."""
    global LIB, _attempted
    if LIB is not None:
        return LIB
    with _lock:
        if LIB is not None or _attempted:
            return LIB
        _attempted = True
        # lint: disable=BLK01 -- one-shot native build: the lock exists precisely to run make exactly once
        if _try_build():
            try:
                LIB = _bind(ctypes.CDLL(_SO))
            except OSError:
                LIB = None
    return LIB
