"""run_command_on_shards/_placements and table-DDL reconstruction.

Reference: operations/citus_tools.c (run_command_on_*) and
operations/node_protocol.c (master_get_table_ddl_events).
"""

from __future__ import annotations

from citus_tpu.errors import AnalysisError, UnsupportedFeatureError
from citus_tpu.executor import Result, execute_select
from citus_tpu.planner import ast as A
from citus_tpu.planner import parse_sql
from citus_tpu.planner.bind import bind_select


def _run_command_on_shards(cl, table_name: str, command: str,
                           per_placement: bool = False) -> Result:
    """reference: citus_tools.c run_command_on_shards/_placements —
    the %s placeholder becomes the shard; here the command is a
    SELECT template executed with the plan restricted to one shard
    (the shard-suffix-name trick has no meaning without SQL-visible
    shard relations)."""
    import dataclasses as _dc

    from citus_tpu.planner.physical import plan_select
    t = cl.catalog.table(table_name)
    sql = command.replace("%s", table_name)
    stmt = parse_sql(sql)[0]
    if not isinstance(stmt, A.Select):
        raise UnsupportedFeatureError(
            "run_command_on_shards supports SELECT commands")
    if not (isinstance(stmt.from_, A.TableRef)
            and stmt.from_.name == t.name):
        raise AnalysisError(
            "run_command_on_shards command must read the named table "
            "(use %s as the relation)")
    bound = bind_select(cl.catalog, stmt)
    plan = plan_select(cl.catalog, bound,
                       direct_limit=cl.settings.planner.direct_gid_limit)
    rows = []
    # one row per shard of the table (reference behavior), even when
    # the command's WHERE clause would prune some shards
    for si in range(len(t.shards)):
        shard = t.shards[si]
        targets = shard.placements if per_placement else [None]
        for node in targets:
            try:
                sp = _dc.replace(plan, shard_indexes=[si])
                r = execute_select(cl.catalog, bound, cl.settings,
                                   plan=sp)
                cell = r.rows[0][0] if r.rows and r.rows[0] else ""
                row = (shard.shard_id, True, str(cell))
            except Exception as exc:
                row = (shard.shard_id, False, str(exc))
            if per_placement:
                row = (row[0], node) + row[1:]
            rows.append(row)
    cols = ["shardid", "nodeid", "success", "result"] if per_placement \
        else ["shardid", "success", "result"]
    return Result(columns=cols, rows=rows)

def _table_ddl(cl, name: str) -> list[str]:
    """Reconstruct the DDL statements that recreate a table
    (reference: master_get_table_ddl_events,
    operations/node_protocol.c)."""
    t = cl.catalog.table(name)
    sql_names = {"bool": "boolean", "int16": "smallint", "int32": "int",
                 "int64": "bigint", "float32": "real",
                 "float64": "double", "date": "date",
                 "timestamp": "timestamp", "text": "text"}
    cols = []
    for c in t.schema:
        enum_t = cl.catalog.enum_columns.get(f"{name}.{c.name}")
        tn = enum_t if enum_t else sql_names.get(c.type.kind, str(c.type))
        if c.type.is_decimal:
            tn = str(c.type)  # decimal(p,s) spells itself
        cols.append(f"{c.name} {tn}"
                    + (" NOT NULL" if c.not_null else ""))
    for fk in t.foreign_keys:
        action = "" if fk["on_delete"] == "restrict" \
            else f" ON DELETE {fk['on_delete'].upper()}"
        cols.append(
            f"FOREIGN KEY ({', '.join(fk['columns'])}) REFERENCES "
            f"{fk['ref_table']} ({', '.join(fk['ref_columns'])})"
            + action)
    out = [f"CREATE TABLE {name} ({', '.join(cols)})"]
    if t.is_distributed:
        out.append(f"SELECT create_distributed_table('{name}', "
                   f"'{t.dist_column}', {t.shard_count})")
    elif t.is_reference:
        out.append(f"SELECT create_reference_table('{name}')")
    return out
