"""Catalog-object DDL handlers: schemas, types, functions, roles,
policies, triggers, text-search configs, views, sequences, extensions,
domains, collations, publications, statistics.

Reference: the per-object-type handlers under
src/backend/distributed/commands/ (type.c, function.c, role.c, view.c,
sequence.c, extension.c, domain.c, collation.c, publication.c,
statistics.c, policy.c, trigger.c, text_search.c, schema.c) dispatched
through the DistributeObjectOps registry.
"""

from __future__ import annotations

from citus_tpu.commands.registry import handles
from citus_tpu.errors import AnalysisError, CatalogError
from citus_tpu.executor import Result
from citus_tpu.planner import ast as A
from citus_tpu.planner import parse_sql
from citus_tpu.types import type_from_sql


@handles(A.CreateSchema)
def create_schema(cl, stmt):
    if stmt.if_not_exists and stmt.name in cl.catalog.schemas:
        return Result(columns=[], rows=[])
    cl.catalog.create_schema(stmt.name)
    cl.catalog.commit()
    return Result(columns=[], rows=[])


@handles(A.DropSchema)
def drop_schema(cl, stmt):
    members = cl.catalog.drop_schema(stmt.name, cascade=stmt.cascade)
    for m in members:
        cl.catalog.drop_table(m)
    cl.catalog.commit()
    cl._plan_cache.clear()
    return Result(columns=[], rows=[])


@handles(A.CreateType)
def create_type(cl, stmt):
    if stmt.name in cl.catalog.types:
        raise CatalogError(f'type "{stmt.name}" already exists')
    if not stmt.labels or len(set(stmt.labels)) != len(stmt.labels):
        raise AnalysisError("enum labels must be unique and non-empty")
    cl.catalog.types[stmt.name] = list(stmt.labels)
    cl.catalog.ddl_epoch += 1
    cl.catalog.commit()
    return Result(columns=[], rows=[])


@handles(A.DropType)
def drop_type(cl, stmt):
    if stmt.if_exists and stmt.name not in cl.catalog.types:
        return Result(columns=[], rows=[])
    if stmt.name not in cl.catalog.types:
        raise CatalogError(f'type "{stmt.name}" does not exist')
    users = [k for k, v in cl.catalog.enum_columns.items()
             if v == stmt.name]
    if users:
        raise CatalogError(
            f'cannot drop type "{stmt.name}": used by {users[0]}')
    del cl.catalog.types[stmt.name]
    cl.catalog.tombstone("types", stmt.name)
    cl.catalog.ddl_epoch += 1
    cl.catalog.commit()
    return Result(columns=[], rows=[])


@handles(A.CreateFunction)
def create_function(cl, stmt):
    from citus_tpu.planner.aggregates import AGG_REGISTRY
    from citus_tpu.planner.bind import AGG_FUNCS
    if stmt.name in AGG_FUNCS or stmt.name in AGG_REGISTRY:
        raise CatalogError(
            f'cannot replace built-in function "{stmt.name}"')
    if stmt.name in cl.catalog.functions and not stmt.or_replace:
        raise CatalogError(f'function "{stmt.name}" already exists')
    if stmt.returns != "trigger" and any(
            t.get("function") == stmt.name
            for t in cl.catalog.triggers.values()):
        raise CatalogError(
            f'cannot replace "{stmt.name}": trigger(s) depend on it '
            "remaining a trigger function")
    # expression macros validate as expressions; trigger functions
    # (RETURNS trigger) hold a SQL statement body
    entry = {"args": list(stmt.arg_names),
             "arg_types": list(stmt.arg_types),
             "returns": stmt.returns, "body": stmt.body}
    if stmt.returns == "trigger":
        parse_sql(stmt.body)
        entry["kind"] = "statement"
    else:
        from citus_tpu.planner.parser import Parser as _P
        _P(stmt.body).parse_expr()
    cl.catalog.functions[stmt.name] = entry
    cl.catalog.ddl_epoch += 1
    cl.catalog.commit()
    cl._plan_cache.clear()
    return Result(columns=[], rows=[])


@handles(A.DropFunction)
def drop_function(cl, stmt):
    if stmt.if_exists and stmt.name not in cl.catalog.functions:
        return Result(columns=[], rows=[])
    if stmt.name not in cl.catalog.functions:
        raise CatalogError(f'function "{stmt.name}" does not exist')
    users = [n for n, t in cl.catalog.triggers.items()
             if t.get("function") == stmt.name]
    if users:
        raise CatalogError(
            f'cannot drop function "{stmt.name}": trigger(s) '
            f'{", ".join(sorted(users))} depend on it')
    del cl.catalog.functions[stmt.name]
    cl.catalog.tombstone("functions", stmt.name)
    cl.catalog.ddl_epoch += 1
    cl.catalog.commit()
    cl._plan_cache.clear()
    return Result(columns=[], rows=[])


@handles(A.CreateRole)
def create_role(cl, stmt):
    if stmt.if_not_exists and stmt.name in cl.catalog.roles:
        return Result(columns=[], rows=[])
    cl.catalog.create_role(stmt.name)
    cl.catalog.commit()
    return Result(columns=[], rows=[])


@handles(A.DropRole)
def drop_role(cl, stmt):
    if stmt.if_exists and stmt.name not in cl.catalog.roles:
        return Result(columns=[], rows=[])
    cl.catalog.drop_role(stmt.name)
    cl.catalog.commit()
    return Result(columns=[], rows=[])


@handles(A.Grant)
def grant(cl, stmt):
    if stmt.revoke:
        cl.catalog.revoke(stmt.table, stmt.role, stmt.privileges)
    else:
        cl.catalog.grant(stmt.table, stmt.role, stmt.privileges)
    cl.catalog.commit()
    return Result(columns=[], rows=[])


@handles(A.CreatePolicy)
def create_policy(cl, stmt):
    cl.catalog.table(stmt.table)  # must exist
    pols = cl.catalog.policies.setdefault(stmt.table, [])
    if any(p["name"] == stmt.name for p in pols):
        raise CatalogError(
            f'policy "{stmt.name}" for table "{stmt.table}" '
            "already exists")
    from citus_tpu.planner.parser import Parser as _P
    for text in (stmt.using_sql, stmt.check_sql):
        if text is not None:
            _P(text).parse_expr()  # validate
    pols.append({"name": stmt.name, "cmd": stmt.cmd,
                 "roles": list(stmt.roles),
                 "using": stmt.using_sql, "check": stmt.check_sql})
    cl.catalog.commit()
    return Result(columns=[], rows=[])


@handles(A.DropPolicy)
def drop_policy(cl, stmt):
    pols = cl.catalog.policies.get(stmt.table, [])
    kept = [p for p in pols if p["name"] != stmt.name]
    if len(kept) == len(pols):
        if stmt.if_exists:
            return Result(columns=[], rows=[])
        raise CatalogError(
            f'policy "{stmt.name}" for table "{stmt.table}" '
            "does not exist")
    if kept:
        cl.catalog.policies[stmt.table] = kept
    else:
        del cl.catalog.policies[stmt.table]
    # per-policy tombstone: the commit-time merge is per policy
    cl.catalog.tombstone("policies", f"{stmt.table}.{stmt.name}")
    cl.catalog.commit()
    return Result(columns=[], rows=[])


@handles(A.AlterTableRls)
def alter_table_rls(cl, stmt):
    cl.catalog.table(stmt.table)
    if stmt.enable:
        cl.catalog.rls[stmt.table] = True
    elif cl.catalog.rls.pop(stmt.table, None) is not None:
        cl.catalog.tombstone("rls", stmt.table)
    cl.catalog.commit()
    return Result(columns=[], rows=[])


@handles(A.CreateTrigger)
def create_trigger(cl, stmt):
    cl.catalog.table(stmt.table)
    if stmt.name in cl.catalog.triggers:
        raise CatalogError(f'trigger "{stmt.name}" already exists')
    fn = cl.catalog.functions.get(stmt.function)
    if fn is None or fn.get("kind") != "statement":
        raise CatalogError(
            f'"{stmt.function}" is not a trigger function '
            "(CREATE FUNCTION ... RETURNS trigger)")
    cl.catalog.triggers[stmt.name] = {
        "table": stmt.table, "event": stmt.event,
        "function": stmt.function}
    cl.catalog.commit()
    return Result(columns=[], rows=[])


@handles(A.DropTrigger)
def drop_trigger(cl, stmt):
    t = cl.catalog.triggers.get(stmt.name)
    if t is None or t.get("table") != stmt.table:
        if stmt.if_exists:
            return Result(columns=[], rows=[])
        raise CatalogError(
            f'trigger "{stmt.name}" on "{stmt.table}" does not exist')
    del cl.catalog.triggers[stmt.name]
    cl.catalog.tombstone("triggers", stmt.name)
    cl.catalog.commit()
    return Result(columns=[], rows=[])


@handles(A.CreateTsConfig)
def create_ts_config(cl, stmt):
    if stmt.name in cl.catalog.ts_configs:
        raise CatalogError(
            f'text search configuration "{stmt.name}" already exists')
    src = stmt.options.get("copy")
    if src is not None and src not in cl.catalog.ts_configs \
            and src != "simple":
        raise CatalogError(
            f'text search configuration "{src}" does not exist')
    base = (dict(cl.catalog.ts_configs.get(src, {}))
            if src is not None else {})
    base["parser"] = stmt.options.get("parser",
                                      base.get("parser", "default"))
    cl.catalog.ts_configs[stmt.name] = base
    cl.catalog.commit()
    return Result(columns=[], rows=[])


@handles(A.DropTsConfig)
def drop_ts_config(cl, stmt):
    if stmt.name not in cl.catalog.ts_configs:
        if stmt.if_exists:
            return Result(columns=[], rows=[])
        raise CatalogError(
            f'text search configuration "{stmt.name}" does not exist')
    del cl.catalog.ts_configs[stmt.name]
    cl.catalog.tombstone("ts_configs", stmt.name)
    cl.catalog.commit()
    return Result(columns=[], rows=[])


@handles(A.CreateView)
def create_view(cl, stmt):
    # validate the body against current metadata (LIMIT 0 run)
    import dataclasses

    from citus_tpu.cluster import _from_relations, _limit0
    probe = dataclasses.replace(stmt.select, limit=0) \
        if isinstance(stmt.select, A.Select) else stmt.select
    replacing = stmt.or_replace and stmt.name in cl.catalog.views
    if replacing:
        if stmt.name in _from_relations(stmt.select):
            raise AnalysisError(
                f'view "{stmt.name}" cannot reference itself')
    new_r = cl._execute_stmt(probe)
    if replacing:
        # PostgreSQL: a replace may only ADD columns at the end,
        # keeping existing names AND types
        from citus_tpu.planner.parser import parse_statement
        old_sel = parse_statement(cl.catalog.views[stmt.name])
        old_r = cl._execute_stmt(_limit0(old_sel))
        old_cols = old_r.columns
        if new_r.columns[:len(old_cols)] != old_cols:
            raise AnalysisError(
                "cannot drop, rename, or reorder columns of "
                f'view "{stmt.name}" with CREATE OR REPLACE')
        if old_r.types and new_r.types:
            for i, (ot, nt) in enumerate(zip(old_r.types, new_r.types)):
                if ot is not None and nt is not None \
                        and ot.kind != nt.kind:
                    raise AnalysisError(
                        "cannot change data type of view column "
                        f'"{old_cols[i]}"')
    cl.catalog.create_view(stmt.name, stmt.sql, or_replace=stmt.or_replace)
    cl.catalog.commit()
    cl._plan_cache.clear()
    return Result(columns=[], rows=[])


@handles(A.DropView)
def drop_view(cl, stmt):
    if stmt.if_exists and stmt.name not in cl.catalog.views:
        return Result(columns=[], rows=[])
    cl.catalog.drop_view(stmt.name)
    cl.catalog.commit()
    cl._plan_cache.clear()
    return Result(columns=[], rows=[])


@handles(A.CreateSequence)
def create_sequence(cl, stmt):
    if stmt.if_not_exists and stmt.name in cl.catalog.sequences:
        return Result(columns=[], rows=[])
    cl.catalog.create_sequence(stmt.name, stmt.start, stmt.increment)
    cl.catalog.commit()
    return Result(columns=[], rows=[])


@handles(A.DropSequence)
def drop_sequence(cl, stmt):
    if stmt.if_exists and stmt.name not in cl.catalog.sequences:
        return Result(columns=[], rows=[])
    cl.catalog.drop_sequence(stmt.name)
    cl.catalog.commit()
    return Result(columns=[], rows=[])


@handles(A.CreateExtension)
def create_extension(cl, stmt):
    if stmt.name in cl.catalog.extensions:
        if stmt.if_not_exists:
            return Result(columns=[], rows=[])
        raise CatalogError(f'extension "{stmt.name}" already exists')
    cl.catalog.extensions[stmt.name] = {
        "version": stmt.version or "1.0"}
    cl.catalog.ddl_epoch += 1
    cl.catalog.commit()
    return Result(columns=[], rows=[])


@handles(A.DropExtension)
def drop_extension(cl, stmt):
    return cl._drop_catalog_object("extensions", stmt)


@handles(A.CreateDomain)
def create_domain(cl, stmt):
    if stmt.name in cl.catalog.domains:
        raise CatalogError(f'domain "{stmt.name}" already exists')
    type_from_sql(stmt.base, stmt.type_args or None)  # must resolve
    if stmt.check_sql is not None:
        from citus_tpu.planner.parser import Parser as _P
        _P(stmt.check_sql).parse_expr()  # validate
    cl.catalog.domains[stmt.name] = {
        "base": stmt.base, "args": list(stmt.type_args or []),
        "not_null": stmt.not_null, "check": stmt.check_sql}
    cl.catalog.ddl_epoch += 1
    cl.catalog.commit()
    return Result(columns=[], rows=[])


@handles(A.DropDomain)
def drop_domain(cl, stmt):
    users = [k for k, v in cl.catalog.domain_columns.items()
             if v == stmt.name]
    if users and stmt.name in cl.catalog.domains:
        raise CatalogError(
            f'cannot drop domain "{stmt.name}": column {users[0]} '
            "depends on it")
    return cl._drop_catalog_object("domains", stmt)


@handles(A.CreateCollation)
def create_collation(cl, stmt):
    if stmt.name in cl.catalog.collations:
        raise CatalogError(f'collation "{stmt.name}" already exists')
    cl.catalog.collations[stmt.name] = dict(stmt.options)
    cl.catalog.ddl_epoch += 1
    cl.catalog.commit()
    return Result(columns=[], rows=[])


@handles(A.DropCollation)
def drop_collation(cl, stmt):
    return cl._drop_catalog_object("collations", stmt)


@handles(A.CreatePublication)
def create_publication(cl, stmt):
    if stmt.name in cl.catalog.publications:
        raise CatalogError(
            f'publication "{stmt.name}" already exists')
    if isinstance(stmt.tables, list):
        for tn in stmt.tables:
            cl.catalog.table(tn)  # must exist
    cl.catalog.publications[stmt.name] = {"tables": stmt.tables}
    cl.catalog.ddl_epoch += 1
    cl.catalog.commit()
    return Result(columns=[], rows=[])


@handles(A.DropPublication)
def drop_publication(cl, stmt):
    return cl._drop_catalog_object("publications", stmt)


@handles(A.CreateStatistics)
def create_statistics(cl, stmt):
    if stmt.name in cl.catalog.statistics:
        raise CatalogError(
            f'statistics object "{stmt.name}" already exists')
    t = cl.catalog.table(stmt.table)
    for c in stmt.columns:
        t.schema.column(c)
    # extended statistics: n-distinct over the column combination
    # (reference: CREATE STATISTICS ndistinct; computed eagerly — our
    # ANALYZE analog)
    nd = cl._compute_ndistinct(stmt.table, list(stmt.columns))
    cl.catalog.statistics[stmt.name] = {
        "table": stmt.table, "columns": list(stmt.columns),
        "ndistinct": nd}
    cl.catalog.ddl_epoch += 1
    cl.catalog.commit()
    return Result(columns=[], rows=[])


@handles(A.DropStatistics)
def drop_statistics(cl, stmt):
    return cl._drop_catalog_object("statistics", stmt)
