"""COPY ... WITH (format binary): columnar batch frames instead of CSV.

Reference: commands/multi_copy.c forwards PostgreSQL's binary COPY
format between coordinator and shards (:552-); our on-the-wire batch
container (net/data_plane.py npz frames) doubles as the file format —
one serialization for both the DCN data plane and bulk import/export.

File layout: magic line ``CTPUBIN1 <json header>\\n`` (columns + type
spellings + row count per frame), then repeated ``<uint32 length><npz
batch>`` frames.  Numeric columns travel PHYSICAL (scaled decimals, day
/microsecond epochs — lossless and cheap); dictionary kinds (text/uuid/
bytea/arrays) travel as canonical WORDS, so a binary file is
self-contained and portable across clusters with different dictionary
id assignments (unlike raw ids)."""

from __future__ import annotations

import json
import struct

import numpy as np

from citus_tpu.errors import AnalysisError, ExecutionError
from citus_tpu.net.data_plane import _npz_bytes, _npz_load

MAGIC = b"CTPUBIN1"

#: rows per frame (a frame decompresses as one unit)
FRAME_ROWS = 262_144


def copy_to_binary(cl, table_name: str, path: str) -> int:
    from citus_tpu.executor.batches import load_shard_batches
    from citus_tpu.planner.bind import bind_select
    from citus_tpu.planner import ast as A
    from citus_tpu.planner.physical import plan_select

    t = cl.catalog.table(table_name)
    names = t.schema.names
    sel = A.Select([A.SelectItem(A.ColumnRef(c)) for c in names],
                   A.TableRef(table_name))
    bound = bind_select(cl.catalog, sel)
    plan = plan_select(cl.catalog, bound)
    total = 0
    header = {"columns": list(names),
              "types": [str(t.schema.column(c).type) for c in names]}
    with open(path, "wb") as fh:
        fh.write(MAGIC + b" " + json.dumps(header).encode() + b"\n")
        for si in plan.shard_indexes:
            for values, masks, n in load_shard_batches(
                    cl.catalog, plan, si, max_batch_rows=FRAME_ROWS):
                arrays = {}
                for c in names:
                    ct = t.schema.column(c).type
                    if ct.is_text:
                        words = cl.catalog.decode_strings(
                            table_name, c, values[c].tolist())
                        # nulls carry an empty word; validity restores
                        arrays[f"v__{c}"] = np.asarray(
                            [w if (m and w is not None) else ""
                             for w, m in zip(words, masks[c])], dtype=str)
                    elif ct.kind == "uuid":
                        # lanes recombine to canonical words: the file
                        # stays portable and format-compatible
                        from citus_tpu import types as T
                        lane = values[T.uuid_lane_name(c)]
                        arrays[f"v__{c}"] = np.asarray(
                            [T.uuid_from_lane_pair(int(h), int(l)) if m
                             else "" for h, l, m in
                             zip(values[c], lane, masks[c])], dtype=str)
                    else:
                        arrays[f"v__{c}"] = values[c]
                    arrays[f"m__{c}"] = np.asarray(masks[c], bool)
                blob = _npz_bytes(arrays)
                fh.write(struct.pack(">I", len(blob)) + blob)
                total += n
    return total


def copy_from_binary(cl, table_name: str, path: str) -> int:
    t = cl.catalog.table(table_name)
    total = 0
    with open(path, "rb") as fh:
        head = fh.readline()
        if not head.startswith(MAGIC + b" "):
            raise AnalysisError(
                f"{path!r} is not a citus_tpu binary COPY file")
        header = json.loads(head[len(MAGIC) + 1:])
        cols = header["columns"]
        missing = [c for c in t.schema.names if c not in cols]
        if missing:
            raise AnalysisError(
                f"binary file lacks column(s) {missing} of "
                f'"{table_name}"')
        while True:
            lb = fh.read(4)
            if not lb:
                break
            if len(lb) != 4:
                raise ExecutionError(f"truncated binary COPY file {path!r}")
            (n,) = struct.unpack(">I", lb)
            blob = fh.read(n)
            if len(blob) != n:
                raise ExecutionError(f"truncated binary COPY file {path!r}")
            arrays = _npz_load(blob)
            columns = {}
            for c in t.schema.names:
                ct = t.schema.column(c).type
                v = arrays[f"v__{c}"]
                m = np.asarray(arrays[f"m__{c}"], bool)
                if ct.is_text or ct.kind == "uuid":
                    columns[c] = [w if ok else None
                                  for w, ok in zip(v.tolist(), m)]
                elif m.all():
                    # all-valid numerics stay physical: the ingest fast
                    # path adopts integer arrays without re-conversion
                    columns[c] = v
                else:
                    columns[c] = [ct.from_physical(x) if ok else None
                                  for x, ok in zip(v.tolist(), m)]
            total += cl.copy_from(table_name, columns=columns)
    return total
