"""DML statement handlers: INSERT, COPY, DELETE, UPDATE, MERGE,
TRUNCATE, VACUUM [ANALYZE], plus config/utility statement dispatch.

Reference: the modify paths of multi_router_planner.c
(CreateModifyPlan), commands/multi_copy.c, merge_planner/executor, and
commands/truncate.c / vacuum.c; here each handler drives the columnar
DML executors (executor/dml.py, executor/merge_executor.py) under the
colocation-group write-lock protocol.
"""

from __future__ import annotations

from citus_tpu.commands.registry import handles
from citus_tpu.errors import (
    AnalysisError, ExecutionError, UnsupportedFeatureError,
)
from citus_tpu.executor import Result
from citus_tpu.observability import trace as _trace
from citus_tpu.planner import ast as A
from citus_tpu.stats import begin_wait, end_wait


@handles(A.Insert)
def insert(cl, stmt):
    return cl._execute_insert(stmt)


@handles(A.CopyTo)
def copy_to(cl, stmt):
    from citus_tpu.cluster import _option_bool
    if str(stmt.options.get("format", "csv")).lower() == "binary":
        from citus_tpu.commands.copy_binary import copy_to_binary
        n = copy_to_binary(cl, stmt.table, stmt.path)
        return Result(columns=[], rows=[], explain={"copied": n})
    n = cl.copy_to_csv(
        stmt.table, stmt.path,
        delimiter=stmt.options.get("delimiter", ","),
        header=_option_bool(stmt.options.get("header", "false")),
        null_string=stmt.options.get("null", ""))
    return Result(columns=[], rows=[], explain={"copied": n})


@handles(A.CopyQueryTo)
def copy_query_to(cl, stmt):
    from citus_tpu.cluster import _option_bool
    r = cl._execute_stmt(stmt.select)
    nulls = stmt.options.get("null", "")
    with open(stmt.path, "w", newline="") as fh:
        w = cl._open_csv_writer(
            fh, r.columns,
            delimiter=stmt.options.get("delimiter", ","),
            header=_option_bool(stmt.options.get("header", "false")))
        for row in r.rows:
            w.writerow([nulls if v is None else v for v in row])
    return Result(columns=[], rows=[], explain={"copied": len(r.rows)})


@handles(A.CopyFrom)
def copy_from(cl, stmt):
    from citus_tpu.cluster import _option_bool
    if str(stmt.options.get("format", "csv")).lower() == "binary":
        from citus_tpu.commands.copy_binary import copy_from_binary
        n = copy_from_binary(cl, stmt.table, stmt.path)
        return Result(columns=[], rows=[], explain={"copied": n})
    n = cl.copy_from_csv(
        stmt.table, stmt.path,
        delimiter=stmt.options.get("delimiter", ","),
        header=_option_bool(stmt.options.get("header", "false")),
        null_string=stmt.options.get("null", ""))
    return Result(columns=[], rows=[], explain={"copied": n})


def _forward_remote_dml(cl, stmt, t, where):
    """A modify statement whose surviving shards live on other
    coordinators: a single remote owner gets the whole statement
    forwarded (the router path — reference: deparsed SQL shipped to the
    owning worker over libpq); shards spanning several hosts run as a
    cross-host 2PC (_two_phase_remote_dml).  Returns a Result when
    handled remotely, None when every surviving shard is local."""
    if cl.catalog.remote_data is None \
            or getattr(cl._remote_exec_guard, "v", False):
        return None
    if not t.is_distributed:
        # a reference table's replicas span hosts: a local-only modify
        # would diverge them — refuse until replicated cross-host DML
        # exists (the reference runs these under 2PC to every node)
        if any(cl.catalog.is_remote_node(nd)
               for s in t.shards for nd in s.placements):
            raise UnsupportedFeatureError(
                "modifying a reference table with remote-hosted replicas "
                "is not supported yet")
        return None
    from citus_tpu.planner.physical import prune_shards
    surviving = prune_shards(t, where)
    # replicated shards spanning hosts: the modify would run against
    # one placement only, silently diverging the replica on the other
    # host — fail closed, mirroring the reference-table guard above
    if any(len(t.shards[si].placements) > 1
           and any(cl.catalog.is_remote_node(nd)
                   for nd in t.shards[si].placements)
           for si in surviving):
        raise UnsupportedFeatureError(
            "modifying a distributed table whose replicated shard "
            "placements span hosts is not supported yet (only one "
            "placement would see the modify, diverging replicas)")
    owners = {t.shards[si].placements[0] for si in surviving}
    remote = {o for o in owners if cl.catalog.is_remote_node(o)}
    if not remote:
        return None
    from citus_tpu.storage.overlay import current_overlay
    endpoints = {cl.catalog.node_endpoint(o) for o in remote}
    if getattr(stmt, "returning", None):
        raise UnsupportedFeatureError(
            "RETURNING is not supported on forwarded remote DML yet")
    sql = getattr(cl._stmt_sql, "v", None)
    if sql is None:
        raise UnsupportedFeatureError(
            "cannot forward this modify statement to its remote host "
            "(no original SQL text — issue it as a single statement)")
    txn = current_overlay()
    if txn is not None:
        return _txn_remote_dml(cl, stmt, t, sql, sorted(endpoints), txn,
                               has_local=(owners != remote))
    if owners == remote and len(endpoints) == 1:
        # router case: one remote owner, no local shards — forward the
        # whole statement, its host's own 2PC makes it atomic
        r = cl.catalog.remote_data.call(next(iter(endpoints)),
                                        "execute_sql", {"sql": sql})
        cl._plan_cache.invalidate_table(t.name)
        return Result(columns=r.get("columns", []),
                      rows=[tuple(row) for row in r.get("rows", [])],
                      explain=r.get("explain", {}))
    return _two_phase_remote_dml(cl, stmt, t, sql, sorted(endpoints),
                                 has_local=(owners != remote))


def _txn_remote_dml(cl, stmt, t, sql: str, endpoints: list, txn,
                    has_local: bool):
    """A modify inside BEGIN..COMMIT touching remote-hosted shards:
    each remote owner gets the statement in a PERSISTENT branch session
    keyed by the transaction's gxid (the reference's worker session of
    a coordinated transaction); COMMIT later drives the branch 2PC
    (cluster._commit_txn).  Returns a Result when no local shards
    survive, else None (local execution continues, remote counts merge
    via cl._remote_counts)."""
    import uuid as _uuid
    if cl._control is None:
        raise UnsupportedFeatureError(
            "a transaction touching remote-hosted shards needs a "
            "metadata authority (the durable outcome store)")
    if txn.catalog_dirty:
        raise UnsupportedFeatureError(
            "DDL and remote-shard DML cannot mix in one transaction yet")
    if txn.savepoints:
        raise UnsupportedFeatureError(
            "savepoints with remote-shard DML are not supported yet")
    if txn.gxid is None:
        txn.gxid = _uuid.uuid4().hex
    counts: dict = {}
    try:
        for ep in endpoints:
            r = cl.catalog.remote_data.call(
                ep, "txn_stmt", {"gxid": txn.gxid, "sql": sql})
            txn.remote_endpoints.add(ep)
            for k, v in (r.get("explain") or {}).items():
                if isinstance(v, (int, float)):
                    counts[k] = counts.get(k, 0) + v
    except BaseException:
        txn.failed = True  # the block must roll back (branches too)
        raise
    txn.remote_written_tables.add(t.name)
    if has_local:
        # local part runs normally; the handler adds these in
        cl._remote_counts.v = counts
        return None
    cl._plan_cache.invalidate_table(t.name)
    return Result(columns=[], rows=[], explain=counts)


def _two_phase_remote_dml(cl, stmt, t, sql: str, endpoints: list,
                          has_local: bool) -> Result:
    """Cross-host 2PC for a modify spanning several hosts (reference:
    PREPARE TRANSACTION on every write connection + COMMIT PREPARED,
    transaction_management.c:319 / remote_transaction.c):

    1. dml_prepare on every remote owner (statement runs there against
       its placements, branch stays staged+locked, PREPARED durable);
       a local branch prepares the same way when local shards survive;
    2. the outcome is recorded DURABLY at the metadata authority
       (gxid_outcomes store — the pg_dist_transaction analog); this is
       the commit point: a branch that misses phase 2 resolves from it
       (absent = presumed abort);
    3. dml_decide(commit) everywhere + local finish."""
    import uuid as _uuid
    if cl._control is None:
        raise UnsupportedFeatureError(
            "a modify spanning several hosts needs a metadata authority "
            "(the durable transaction-outcome store); attach the "
            "coordinators via serve_port/coordinator")
    gxid = _uuid.uuid4().hex
    prepared: list = []
    local_session = None
    local_prepared = False
    counts: dict = {}

    def _abort_everything() -> str:
        # claim abort in the decision register first, so any branch
        # that expires concurrently agrees; then best-effort decides.
        # Returns the REGISTER's winner: 'commit' means our own commit
        # record already landed (response lost) and the caller must
        # complete the commit instead; 'in-doubt' means the claim never
        # reached the register (authority unreachable) — a prepared
        # branch must then be LEFT ALONE: deciding abort on it without
        # a durable claim could diverge from a commit record that did
        # (or will) land, so prepared branches resolve against the
        # outcome register instead (absent record = presumed abort).
        try:
            winner = cl._control.record_txn_outcome(gxid, "abort")
        except Exception:
            # the abort claim is NOT durable; only a local branch that
            # never prepared is unambiguous and safe to roll back
            if local_session is not None \
                    and local_session.txn is not None \
                    and not local_prepared:
                try:
                    cl._rollback_txn(local_session)
                # lint: disable=SWL01 -- in-doubt path: recovery resolves the branch; rollback is opportunistic
                except Exception:
                    pass
            return "in-doubt"
        if winner == "commit":
            return "commit"
        for ep in prepared:
            try:
                cl.catalog.remote_data.call(
                    ep, "dml_decide", {"gxid": gxid, "commit": False})
            # lint: disable=SWL01 -- abort already durable in the outcome store; branch expiry resolves it
            except Exception:
                pass  # branch expiry resolves it
        if local_session is not None and local_session.txn is not None:
            try:
                if local_prepared:
                    cl._finish_branch(local_session, False)
                else:
                    # statement failed BEFORE prepare: the txn is a
                    # plain open transaction — normal rollback cleans
                    # its staged files (finish_branch's empty payload
                    # would leak them)
                    cl._rollback_txn(local_session)
            # lint: disable=SWL01 -- abort outcome already durable; local cleanup failure surfaces via recovery
            except Exception:
                pass
        return "abort"


    def _complete_commit() -> None:
        # local branch finishes FIRST (its outcome can never change
        # now; raising before it would strand a committed prepared
        # branch), then the remote decides — divergence surfaces after
        # local state is consistent
        _c_span = _trace.span("2pc_decide", participants=len(endpoints))
        _c_span.__enter__()
        wtok = begin_wait("2pc_decision")
        try:
            _complete_commit_body()
        finally:
            end_wait(wtok)
            _c_span.__exit__(None, None, None)

    def _complete_commit_body() -> None:
        if local_session is not None and local_session.txn is not None:
            cl._finish_branch(local_session, True)
        cl._plan_cache.clear()
        divergence = None
        for ep in endpoints:
            try:
                r = cl.catalog.remote_data.call(
                    ep, "dml_decide", {"gxid": gxid, "commit": True})
                if not r.get("ok") and r.get("resolved") != "commit":
                    divergence = (ep, r.get("resolved"))
            # lint: disable=SWL01 -- commit already durable; an unreachable peer resolves from the outcome store
            except Exception:
                pass  # resolves to commit from the outcome store
        if divergence is not None:
            raise ExecutionError(
                f"cross-host branch on {divergence[0]} diverged: "
                f"resolved={divergence[1]!r} after a committed outcome")

    try:
        with _trace.span("2pc_prepare", participants=len(endpoints),
                         local=bool(has_local)):
            for ep in endpoints:
                r = cl.catalog.remote_data.call(
                    ep, "dml_prepare", {"gxid": gxid, "sql": sql})
                prepared.append(ep)
                for k, v in (r.get("explain") or {}).items():
                    if isinstance(v, (int, float)):
                        counts[k] = counts.get(k, 0) + v
            if has_local:
                local_session = cl.session()
                guard = cl._remote_exec_guard
                prev = getattr(guard, "v", False)
                guard.v = True
                try:
                    local_session.execute("BEGIN")
                    r = local_session.execute(sql)
                    cl._prepare_branch(local_session, gxid)
                    local_prepared = True
                finally:
                    guard.v = prev
                for k, v in (r.explain or {}).items():
                    if isinstance(v, (int, float)):
                        counts[k] = counts.get(k, 0) + v
        # THE commit point: first writer into the durable decision
        # register wins — if a participant's presumed-abort claim got
        # there first, WE must abort
        with _trace.span("2pc_commit_point"):
            wtok = begin_wait("2pc_decision")
            try:
                winner = cl._control.record_txn_outcome(gxid, "commit")
            finally:
                end_wait(wtok)
        if winner != "commit":
            raise ExecutionError(
                "cross-host transaction aborted by a participant "
                "(branch timed out before the commit decision)")
    except BaseException as exc:
        outcome = _abort_everything()
        if outcome == "commit":
            # our commit record already landed (response lost): the
            # transaction IS committed — complete it, don't diverge
            _complete_commit()
            counts["gxid"] = gxid
            return Result(columns=[], rows=[], explain=counts)
        if outcome == "in-doubt":
            from citus_tpu.errors import TransactionError
            raise TransactionError(
                f"cross-host transaction {gxid} is in doubt: the abort "
                f"decision could not be durably recorded (metadata "
                f"authority unreachable); prepared branches are left to "
                f"resolve against the outcome register") from exc
        raise
    _complete_commit()
    counts["gxid"] = gxid
    return Result(columns=[], rows=[], explain=counts)


@handles(A.Delete)
def delete(cl, stmt):
    from citus_tpu.executor.dml import execute_delete
    from citus_tpu.planner.bind import Binder
    from citus_tpu.transaction.locks import EXCLUSIVE
    t = cl.catalog.table(stmt.table)
    if t.is_partitioned:
        return cl._partition_dml(stmt, t)
    where = Binder(cl.catalog, t).bind_scalar(stmt.where) \
        if stmt.where is not None else None
    cl._remote_counts.v = None
    fwd = _forward_remote_dml(cl, stmt, t, where)
    if fwd is not None:
        return fwd
    with cl._write_lock(t, EXCLUSIVE):
        if cl.catalog.referencing_fks(stmt.table):
            # RESTRICT / CASCADE / SET NULL on referencing tables
            # before the parent rows disappear
            from citus_tpu.integrity import on_parent_delete
            on_parent_delete(cl, stmt.table, stmt.where)
        # RETURNING reads the pre-image under the same lock so the rows
        # returned are exactly the rows deleted
        ret = cl._returning_result(stmt.table, stmt.where,
                                   stmt.returning) \
            if stmt.returning else None
        t = cl.catalog.table(stmt.table)  # re-fetch: fresh placements
        from citus_tpu.storage.overlay import current_overlay
        try:
            n = execute_delete(cl.catalog, cl.txlog, t, where,
                               txn=current_overlay())
        finally:
            pend = getattr(cl._remote_counts, "v", None)
            cl._remote_counts.v = None  # never leak into a later statement
    if pend:
        n += int(pend.get("deleted", 0))
    cl._plan_cache.invalidate_table(t.name)
    if cl._cdc_captures(t.name) and n:
        cl._emit_cdc(t.name, "delete", count=n)
    if ret is not None:
        ret.explain["deleted"] = n
        return ret
    return Result(columns=[], rows=[], explain={"deleted": n})


@handles(A.Update)
def update(cl, stmt):
    from citus_tpu.executor.dml import execute_update
    from citus_tpu.planner.bind import Binder
    from citus_tpu.planner.bound import BCast, BLiteral
    from citus_tpu.transaction.locks import EXCLUSIVE
    t = cl.catalog.table(stmt.table)
    if t.is_partitioned:
        return cl._partition_dml(stmt, t)
    b = Binder(cl.catalog, t)
    cl._remote_counts.v = None
    if cl.catalog.remote_data is not None:
        bw = b.bind_scalar(stmt.where) if stmt.where is not None else None
        fwd = _forward_remote_dml(cl, stmt, t, bw)
        if fwd is not None:
            return fwd
    assignments = []
    for col, e in stmt.assignments:
        target = t.schema.column(col)
        bound = b.bind_scalar(e)
        if target.type.kind == "uuid":
            # fold a string literal to the physical 128-bit value here;
            # the executor splits it into int64 lanes (dictionary bypass)
            if isinstance(bound, BLiteral) and isinstance(bound.value, str):
                bound = BLiteral(target.type.to_physical(bound.value),
                                 target.type)
            elif bound.type.kind != "uuid":
                raise AnalysisError(
                    f"cannot assign {bound.type} to {col} ({target.type})")
            assignments.append((col, bound))
            continue
        if target.type.is_text:
            if isinstance(bound, BLiteral) and isinstance(bound.value, str):
                did = cl.catalog.encode_strings(t.name, col, [bound.value])[0]
                bound = BLiteral(int(did), target.type)
            elif not bound.type.is_text:
                raise AnalysisError(
                    f"cannot assign {bound.type} to {col} ({target.type})")
        elif bound.type.is_text:
            raise AnalysisError(
                f"cannot assign text to {col} ({target.type})")
        elif bound.type != target.type:
            bound = BCast(bound, target.type)
        assignments.append((col, bound))
    where = b.bind_scalar(stmt.where) if stmt.where is not None else None
    with cl._write_lock(t, EXCLUSIVE):
        assigned_cols = {c for c, _e in stmt.assignments}
        if cl.catalog.referencing_fks(stmt.table):
            from citus_tpu.integrity import on_parent_update
            on_parent_update(cl, stmt.table, assigned_cols,
                             stmt.where, stmt.assignments)
        if t.foreign_keys:
            from citus_tpu.integrity import check_child_update
            check_child_update(cl, t, stmt.assignments)
        ret = None
        if stmt.returning:
            # new values = assignments substituted into the items,
            # evaluated over the pre-image under the same lock
            subst = {}
            for col, e in stmt.assignments:
                subst[A.ColumnRef(col)] = e
                subst[A.ColumnRef(col, stmt.table)] = e
            ret = cl._returning_result(stmt.table, stmt.where,
                                       stmt.returning, subst)
        t = cl.catalog.table(stmt.table)  # re-fetch: fresh placements
        from citus_tpu.storage.overlay import current_overlay
        assigned = {c for c, _e in stmt.assignments}
        checks = []
        if any(c in assigned
               for c, _dn, _d in cl._domain_columns_of(t)):
            checks.append(
                lambda v, m: cl._check_domains_physical(t, v, m))
        if t.partition_of is not None:
            from citus_tpu.partitioning import check_partition_bounds
            checks.append(
                lambda v, m: check_partition_bounds(cl.catalog, t, v, m))
        if t.check_constraints:
            from citus_tpu.integrity import enforce_check_constraints
            checks.append(
                lambda v, m: enforce_check_constraints(cl.catalog, t, v, m))
        check = None
        if checks:
            check = lambda v, m: [c(v, m) for c in checks]  # noqa: E731
        try:
            n = execute_update(cl.catalog, cl.txlog, t, assignments,
                               where, txn=current_overlay(), check=check)
        finally:
            pend = getattr(cl._remote_counts, "v", None)
            cl._remote_counts.v = None  # never leak into a later statement
    if pend:
        n += int(pend.get("updated", 0))
    cl._plan_cache.invalidate_table(t.name)
    if cl._cdc_captures(t.name) and n:
        cl._emit_cdc(t.name, "update", count=n)
    if ret is not None:
        ret.explain["updated"] = n
        return ret
    return Result(columns=[], rows=[], explain={"updated": n})


@handles(A.Merge)
def merge(cl, stmt):
    from citus_tpu.executor.merge_executor import execute_merge
    from citus_tpu.transaction.locks import EXCLUSIVE
    _mt = cl.catalog.table(stmt.target.name)
    if cl.catalog.remote_data is not None and any(
            cl.catalog.is_remote_node(nd)
            for s in _mt.shards for nd in s.placements):
        # the merge executor reads/writes placements directly; a remote
        # shard would look empty (matched rows re-inserted, then
        # dropped by the remote-skipping ingest) — fail closed
        raise UnsupportedFeatureError(
            "MERGE into a table with remote-hosted shards is not "
            "supported yet (no cross-host 2PC)")
    if _mt.foreign_keys or cl.catalog.referencing_fks(_mt.name):
        # the merge executor writes through the storage layer directly;
        # fail closed rather than bypass FK enforcement
        raise UnsupportedFeatureError(
            "MERGE on tables with foreign key constraints is not "
            "supported")
    # unique indexes are enforced inside execute_merge (pre-commit
    # delete-aware probe); FK targets stay refused above
    with cl._write_lock(cl.catalog.table(stmt.target.name), EXCLUSIVE):
        st = execute_merge(
            cl.catalog, cl.txlog, stmt,
            encode_value=lambda tbl, col, v:
                int(cl.catalog.encode_strings(tbl, col, [v])[0]))
    cl._plan_cache.invalidate_table(stmt.target.name)
    if cl._cdc_captures(stmt.target.name):
        cl.cdc.emit(stmt.target.name, "merge",
                    cl.clock.transaction_clock(), force=True,
                    count=sum(st.values()))
    return Result(columns=[], rows=[], explain=st)


@handles(A.Truncate)
def truncate(cl, stmt):
    import contextlib as _ctxlib

    from citus_tpu.integrity import forbid_truncate_referenced
    from citus_tpu.transaction.locks import EXCLUSIVE
    from citus_tpu.transaction.write_locks import group_resource
    # validate EVERY relation up front (existence + FK rule with
    # list-awareness: a referenced parent is fine when all its children
    # are in the same list, like PostgreSQL): truncation deletes files
    # irreversibly, so a bad later name must not leave earlier tables
    # already emptied
    names = (stmt.table,) + tuple(stmt.more)
    expanded = []
    for name in names:
        t0 = cl.catalog.table(name)
        expanded.append(name)
        if t0.is_partitioned:
            expanded += [p.name for p in cl.catalog.partitions_of(name)]
    for name in expanded:
        forbid_truncate_referenced(cl.catalog, name,
                                   also_truncated=set(expanded))
    # acquire every relation's EXCLUSIVE lock (sorted, to dodge
    # lock-order inversions) BEFORE the first irreversible flip:
    # PostgreSQL's TRUNCATE a, b is all-or-nothing, so a later table's
    # lock timeout must fail the statement while no table has been
    # emptied yet
    metas = {}
    for name in expanded:
        t0 = cl.catalog.table(name)
        if not t0.is_partitioned:
            metas.setdefault(group_resource(t0), t0)
    # placements hosted by other coordinators: forward the statement to
    # each owning host (it truncates ITS placements; the guard stops it
    # forwarding back), then truncate the local ones.  Not atomic
    # across hosts — like the per-host 2PC elsewhere — but never the
    # silent data resurrection of truncating only local directories.
    if cl.catalog.remote_data is not None \
            and not getattr(cl._remote_exec_guard, "v", False):
        eps = {cl.catalog.node_endpoint(nd)
               for t0 in metas.values()
               for s in t0.shards for nd in s.placements
               if cl.catalog.is_remote_node(nd)}
        if eps:
            sql = getattr(cl._stmt_sql, "v", None)
            if sql is None:
                raise UnsupportedFeatureError(
                    "cannot forward TRUNCATE to remote placement hosts "
                    "(no original SQL text — issue it as a single "
                    "statement)")
            for ep in sorted(eps):
                cl.catalog.remote_data.call(ep, "execute_sql",
                                            {"sql": sql})
    with _ctxlib.ExitStack() as stack:
        for res in sorted(metas):
            stack.enter_context(cl._write_lock(metas[res], EXCLUSIVE))
        for name in names:
            cl._truncate_one(name)
    if cl.catalog.remote_data is not None:
        for t0 in metas.values():
            cl.catalog.remote_data.invalidate_cache(t0.name)
    return Result(columns=[], rows=[])


@handles(A.Vacuum)
def vacuum(cl, stmt):
    from citus_tpu.executor.dml import execute_vacuum
    from citus_tpu.transaction.locks import EXCLUSIVE
    t = cl.catalog.table(stmt.table)
    if t.is_partitioned:
        # the parent holds no data: vacuum every partition
        return cl._fanout_partitions(stmt, aggregate_explain=True)
    with cl._write_lock(t, EXCLUSIVE):
        st = execute_vacuum(cl.catalog, cl.catalog.table(stmt.table))
    cl._plan_cache.invalidate_table(t.name)
    return Result(columns=[], rows=[], explain=st)


@handles(A.VacuumAnalyze)
def vacuum_analyze(cl, stmt):
    cl._execute_stmt(A.Vacuum(stmt.table, stmt.full))
    return cl._execute_analyze(stmt.table)


@handles(A.Analyze)
def analyze(cl, stmt):
    return cl._execute_analyze(stmt.table)


@handles(A.SetConfig)
def set_config(cl, stmt):
    return cl._execute_set(stmt)


@handles(A.ShowConfig)
def show_config(cl, stmt):
    return cl._execute_show(stmt)


@handles(A.Reindex)
def reindex(cl, stmt):
    return cl._execute_reindex(stmt)


@handles(A.UtilityCall)
def utility_call(cl, stmt):
    return cl._execute_utility(stmt)


@handles(A.Explain)
def explain(cl, stmt):
    return cl._execute_explain(stmt)
