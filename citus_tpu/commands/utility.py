"""UDF-style admin/monitoring calls (SELECT citus_*(...) surface).

Reference: the L7 SQL API — sql/udfs/ (200 UDF dirs) dispatched through
C entry points all over the reference tree; here one registry keyed by
function name (see commands/registry.py).  Handler signature:
``fn(cl, name, args) -> Result``.
"""

from __future__ import annotations

import json

from citus_tpu.executor import Result
from citus_tpu.commands.registry import UTILITY_HANDLERS, utility
from citus_tpu.errors import CatalogError, UnsupportedFeatureError


def execute_utility(cl, stmt) -> Result:
    fn = UTILITY_HANDLERS.get(stmt.name)
    if fn is None:
        raise UnsupportedFeatureError(
            f"utility {stmt.name}() not supported yet")
    return fn(cl, stmt.name, stmt.args)


# ----------------------------------------------------------- distribution

@utility("create_distributed_table")
def _create_distributed_table(cl, name, args):
    shard_count = int(args[2]) if len(args) > 2 else None
    cl.create_distributed_table(args[0], args[1], shard_count)
    return Result(columns=[name], rows=[(None,)])


@utility("create_reference_table")
def _create_reference_table(cl, name, args):
    cl.create_reference_table(args[0])
    return Result(columns=[name], rows=[(None,)])


@utility("create_time_partitions")
def _create_time_partitions(cl, name, args):
    from citus_tpu.partitioning import create_time_partitions
    n = create_time_partitions(
        cl, args[0], args[1], args[2],
        args[3] if len(args) > 3 else None)
    return Result(columns=[name], rows=[(n > 0,)],
                  explain={"partitions_created": n})


@utility("drop_old_time_partitions")
def _drop_old_time_partitions(cl, name, args):
    from citus_tpu.partitioning import drop_old_time_partitions
    n = drop_old_time_partitions(cl, args[0], args[1])
    return Result(columns=[name], rows=[(n,)],
                  explain={"partitions_dropped": n})


@utility("time_partitions")
def _time_partitions(cl, name, args):
    # the time_partitions view (reference: a SQL view over pg_class +
    # partition bounds)
    rows = []
    for t in cl.catalog.tables.values():
        if t.partition_of is not None:
            rows.append((t.partition_of["parent"], t.name,
                         t.partition_of["lo"], t.partition_of["hi"]))
    return Result(
        columns=["parent_table", "partition", "from_value", "to_value"],
        rows=sorted(rows))


# ----------------------------------------------------- object inventories

@utility("citus_extensions")
def _citus_extensions(cl, name, args):
    return Result(columns=["name", "version"],
                  rows=sorted((k, v.get("version"))
                              for k, v in cl.catalog.extensions.items()))


@utility("citus_domains")
def _citus_domains(cl, name, args):
    return Result(
        columns=["name", "base_type", "not_null", "check"],
        rows=sorted((k, v["base"], v["not_null"], v.get("check"))
                    for k, v in cl.catalog.domains.items()))


@utility("citus_collations")
def _citus_collations(cl, name, args):
    return Result(columns=["name", "locale", "provider"],
                  rows=sorted((k, v.get("locale"), v.get("provider"))
                              for k, v in cl.catalog.collations.items()))


@utility("citus_publications")
def _citus_publications(cl, name, args):
    rows = []
    for k, v in sorted(cl.catalog.publications.items()):
        tl = v.get("tables")
        rows.append((k, "ALL TABLES" if tl == "all" else ", ".join(tl)))
    return Result(columns=["name", "tables"], rows=rows)


@utility("citus_statistics_objects")
def _citus_statistics_objects(cl, name, args):
    return Result(
        columns=["name", "table", "columns", "ndistinct"],
        rows=sorted((k, v["table"], ", ".join(v["columns"]), v["ndistinct"])
                    for k, v in cl.catalog.statistics.items()))


@utility("citus_roles")
def _citus_roles(cl, name, args):
    return Result(columns=["role_name"],
                  rows=[(r,) for r in sorted(cl.catalog.roles)])


@utility("citus_grants")
def _citus_grants(cl, name, args):
    rows = []
    for tbl, by_role in sorted(cl.catalog.grants.items()):
        for r, privs in sorted(by_role.items()):
            rows.append((tbl, r, ",".join(privs)))
    return Result(columns=["table_name", "role_name", "privileges"],
                  rows=rows)


@utility("citus_types")
def _citus_types(cl, name, args):
    return Result(columns=["type_name", "labels"],
                  rows=[(n, ",".join(ls)) for n, ls in
                        sorted(cl.catalog.types.items())])


@utility("citus_policies")
def _citus_policies(cl, name, args):
    rows = []
    for tbl in sorted(cl.catalog.policies):
        for p in cl.catalog.policies[tbl]:
            rows.append((tbl, p["name"], p["cmd"], ",".join(p["roles"]),
                         p.get("using"), p.get("check")))
    return Result(columns=["table_name", "policy_name", "cmd", "roles",
                           "using_expr", "check_expr"], rows=rows)


@utility("citus_triggers")
def _citus_triggers(cl, name, args):
    return Result(
        columns=["trigger_name", "table_name", "event", "function"],
        rows=[(n, t["table"], t["event"], t["function"])
              for n, t in sorted(cl.catalog.triggers.items())])


@utility("citus_text_search_configs")
def _citus_text_search_configs(cl, name, args):
    return Result(
        columns=["config_name", "parser"],
        rows=[(n, c.get("parser", "default"))
              for n, c in sorted(cl.catalog.ts_configs.items())])


@utility("citus_views")
def _citus_views(cl, name, args):
    return Result(columns=["view_name", "definition"],
                  rows=sorted(cl.catalog.views.items()))


@utility("citus_sequences")
def _citus_sequences(cl, name, args):
    rows = [(n, s["value"], s["increment"], s["start"])
            for n, s in sorted(cl.catalog.sequences.items())]
    return Result(columns=["sequence_name", "next_block_start",
                           "increment", "start"], rows=rows)


@utility("citus_schemas")
def _citus_schemas(cl, name, args):
    rows = []
    for sname, info in cl.catalog.schemas.items():
        members = [t for t in cl.catalog.tables if t.startswith(sname + ".")]
        size = sum(cl._table_size(m) for m in members)
        rows.append((sname, info["colocation_id"], info["home_node"],
                     len(members), size))
    return Result(columns=["schema_name", "colocation_id", "node",
                           "table_count", "schema_size"], rows=rows)


# ------------------------------------------------------- stats/monitoring

@utility("citus_stat_pool")
def _citus_stat_pool(cl, name, args):
    # shared task-pool admission counters (the citus.max_shared_pool_size
    # / shared_connection_stats view)
    from citus_tpu.executor.admission import GLOBAL_POOL
    st = GLOBAL_POOL.stats()
    st["pool_size"] = cl.settings.executor.max_shared_pool_size
    cols = ["pool_size", "in_use", "high_water", "granted",
            "denied_optional", "waits", "coalesced", "timeouts"]
    return Result(columns=cols, rows=[tuple(st[c] for c in cols)])


@utility("citus_megabatch_stats")
def _citus_megabatch_stats(cl, name, args):
    # same-family coalescing view (executor/megabatch.py): dispatch and
    # occupancy accounting next to the knobs that shape it
    from citus_tpu.executor.megabatch import GLOBAL_MEGABATCH
    st = GLOBAL_MEGABATCH.stats()

    def _hist(h: dict) -> str:
        return ", ".join(f"{k}:{v}" for k, v in sorted(h.items()))
    ex = cl.settings.executor
    return Result(
        columns=["window_ms", "max_size", "batches", "queries",
                 "fallbacks", "avg_occupancy", "occupancy_hist",
                 "query_occupancy_hist"],
        rows=[(ex.megabatch_window_ms, ex.megabatch_max_size,
               st["batches"], st["queries"], st["fallbacks"],
               round(st["avg_occupancy"], 2),
               _hist(st["occupancy_hist"]),
               _hist(st["query_occupancy_hist"]))])


@utility("citus_stat_counters")
def _citus_stat_counters(cl, name, args):
    snap = cl.counters.snapshot()
    return Result(columns=["counter", "value"], rows=sorted(snap.items()))


@utility("citus_stat_counters_reset")
def _citus_stat_counters_reset(cl, name, args):
    # one atomic observability reset: counters zero, then their reset
    # hooks re-zero derived state (the flight recorder's rate
    # baselines), then the per-family latency histograms drop — so no
    # surface can difference post-reset values against pre-reset ones
    cl.counters.reset()
    cl.query_stats.reset()
    return Result(columns=[name], rows=[(None,)])


@utility("citus_stat_statements")
def _citus_stat_statements(cl, name, args):
    return Result(columns=["query", "executor", "partition_key",
                           "calls", "total_time_ms", "rows",
                           "p50_ms", "p95_ms", "p99_ms"],
                  rows=cl.query_stats.rows_view())


@utility("citus_stat_statements_reset")
def _citus_stat_statements_reset(cl, name, args):
    cl.query_stats.reset()
    return Result(columns=[name], rows=[(None,)])


@utility("citus_stat_tenants")
def _citus_stat_tenants(cl, name, args):
    # live view: the 60 s sliding window (router attribution) joined
    # with the workload scheduler's admission accounting and latency
    # percentiles; "*" is the shared class (multi-shard analytics)
    from citus_tpu.workload import GLOBAL_SCHEDULER
    window = {r[0]: r for r in cl.tenant_stats.rows_view()}
    sched = {r[0]: r for r in GLOBAL_SCHEDULER.rows_view()}
    rows = []
    for t in set(window) | set(sched):
        _, qc, tt = window.get(t, (t, 0, 0.0))
        (_, running, queued, granted, shed, coalesced, remote,
         p50, p99) = sched.get(t, (t, 0, 0, 0, 0, 0, 0, 0.0, 0.0))
        rows.append((t, qc, tt, running, queued, granted, shed,
                     coalesced, remote, p50, p99))
    rows.sort(key=lambda r: (-r[5], -r[1], str(r[0])))
    return Result(columns=["tenant", "query_count", "total_time_ms",
                           "running", "queued", "granted", "shed",
                           "coalesced", "remote_tasks", "p50_ms",
                           "p99_ms"],
                  rows=rows)


@utility("citus_stat_activity")
def _citus_stat_activity(cl, name, args):
    return Result(columns=["global_pid", "state", "elapsed_s", "query",
                           "phase", "wait_event"],
                  rows=cl.activity.rows_view())


@utility("citus_dist_stat_activity")
def _citus_dist_stat_activity(cl, name, args):
    """Cluster-wide activity: the stat fan-out's merged payloads, one
    row per live statement on ANY node, node-attributed (reference:
    citus_dist_stat_activity over every worker).  A node that misses
    its citus.stat_fanout_timeout_s budget shows one node_unreachable
    row rather than hanging or failing the view."""
    from citus_tpu.observability.cluster_stats import (
        cluster_node_stats, payload_node,
    )
    rows = []
    for p in cluster_node_stats(cl):
        node = payload_node(p)
        if p.get("unreachable"):
            rows.append((None, node, "node_unreachable", None,
                         p.get("endpoint", ""), "", ""))
            continue
        for a in p.get("activity", []):
            gpid, state, elapsed_s, sql, phase, wait_event = a
            rows.append((gpid, node, state, elapsed_s, sql, phase,
                         wait_event))
    return Result(columns=["global_pid", "node", "state", "elapsed_s",
                           "query", "phase", "wait_event"],
                  rows=rows)


@utility("citus_metrics")
def _citus_metrics(cl, name, args):
    """Prometheus text exposition as rows — same payload SHOW
    citus.metrics returns and scripts/metrics_exporter.py serves."""
    from citus_tpu.observability.export import prometheus_text
    return Result(columns=["metrics"],
                  rows=[(line,) for line in
                        prometheus_text(cl).splitlines()])


@utility("citus_cluster_metrics")
def _citus_cluster_metrics(cl, name, args):
    """Cluster-wide Prometheus text: every node's counters/gauges as
    node-labeled series, in-flight task progress as gauges, and a
    citus_node_unreachable marker per dead node."""
    from citus_tpu.observability.export import prometheus_cluster_text
    return Result(columns=["metrics"],
                  rows=[(line,) for line in
                        prometheus_cluster_text(cl).splitlines()])


@utility("citus_cluster_slow_queries")
def _citus_cluster_slow_queries(cl, name, args):
    """Every node's slow-query ring merged, node-attributed, newest
    first across the cluster."""
    from citus_tpu.observability.cluster_stats import (
        cluster_node_stats, payload_node,
    )
    rows = []
    for p in cluster_node_stats(cl):
        if p.get("unreachable"):
            continue
        node = payload_node(p)
        for r in p.get("slow_queries", []):
            logged_at, duration_ms, trace_id, phases, sql = r
            rows.append((node, logged_at, duration_ms, trace_id, phases,
                         sql))
    rows.sort(key=lambda r: -(r[1] or 0))
    return Result(columns=["node", "captured_at", "duration_ms",
                           "trace_id", "phases", "query"],
                  rows=rows)


#: citus_health_events() severity per event kind — the row type half of
#: the health-event contract (cituslint CNT04 checks every kind
#: declared in observability/flight_recorder.py appears here).
_HEALTH_SEVERITY = {
    "p99_regression": "warning",
    "shed_rate_spike": "warning",
    "catchup_stall": "warning",
    "pool_saturation": "critical",
    "dead_node": "critical",
    "device_probe_wedged": "warning",
    "metadata_sync_lag": "warning",
    "autopilot_action": "info",
}


@utility("citus_stat_history")
def _citus_stat_history(cl, name, args):
    """Time-series view over the flight recorder's ring, cluster-wide:
    (ts, node, metric, value, rate) rows fanned in through
    get_node_stats; dead nodes contribute nothing (degraded, not
    fatal).  Args: metric name, optional lookback window in seconds."""
    from citus_tpu.observability.cluster_stats import (
        cluster_node_stats, payload_node,
    )
    metric = str(args[0]) if args else None
    since_s = float(args[1]) if len(args) > 1 else None
    from citus_tpu.utils.clock import now as wall_now
    cutoff = None if since_s is None else wall_now() - since_s
    rows = []
    for p in cluster_node_stats(cl):
        if p.get("unreachable"):
            continue
        node = payload_node(p)
        for h in p.get("history", []):
            ts, mname, value, rate = h
            if metric is not None and mname != metric:
                continue
            if cutoff is not None and ts < cutoff:
                continue
            rows.append((ts, node, mname, value, rate))
    rows.sort(key=lambda r: (r[0], r[1], r[2]))
    return Result(columns=["ts", "node", "metric", "value", "rate"],
                  rows=rows)


@utility("citus_health_events")
def _citus_health_events(cl, name, args):
    """The health engine's typed event log, cluster-wide and node-
    attributed; an unreachable node yields one dead_node row from the
    coordinator's own recorder rather than failing the view."""
    from citus_tpu.observability.cluster_stats import (
        cluster_node_stats, payload_node,
    )
    rows = []
    for p in cluster_node_stats(cl):
        if p.get("unreachable"):
            continue
        node = payload_node(p)
        for e in p.get("health", []):
            ts, kind, subject, value, baseline, detail, active = e
            rows.append((ts, node, kind,
                         _HEALTH_SEVERITY.get(kind, "warning"), subject,
                         value, baseline, bool(active), detail))
    rows.sort(key=lambda r: (r[0], r[1]))
    return Result(columns=["ts", "node", "kind", "severity", "subject",
                           "value", "baseline", "active", "detail"],
                  rows=rows)


@utility("citus_shard_load")
def _citus_shard_load(cl, name, args):
    """The per-placement attribution ledger, cluster-wide: every node's
    booked (table, shard, placement, tenant) load fanned in through
    get_node_stats — ``observer`` is the node that did the work (a
    coordinator scanning a mirrored remote placement books there;
    a worker running a pushed task books on itself).  Optional arg:
    table-name filter."""
    from citus_tpu.observability.cluster_stats import (
        cluster_node_stats, payload_node,
    )
    table = str(args[0]) if args else None
    rows = []
    for p in cluster_node_stats(cl):
        if p.get("unreachable"):
            continue
        observer = payload_node(p)
        for r in p.get("shard_load", []):
            if table is not None and r[0] != table:
                continue
            rows.append((observer, *r))
    rows.sort(key=lambda r: (-r[6], r[1], r[2], r[3], str(r[4]), r[0]))
    return Result(columns=["observer", "table_name", "shard_id", "node",
                           "tenant", "queries", "device_ms",
                           "bytes_scanned", "rows_returned",
                           "remote_wait_ms", "ewma_ms_per_s"],
                  rows=rows)


@utility("citus_rebalance_plan")
def _citus_rebalance_plan(cl, name, args):
    """Dry-run rebalance plan (operations/rebalance_plan.py): ordered
    move/split/isolate steps with expected-benefit scores, computed
    from the current catalog + attribution snapshot.  Pure
    observability — executes nothing.  Args: strategy (default
    by_observed_load), optional imbalance threshold."""
    from citus_tpu.operations.rebalance_plan import (
        PLAN_COLUMNS, build_rebalance_plan, plan_rows,
    )
    strategy = str(args[0]) if args else "by_observed_load"
    threshold = float(args[1]) if len(args) > 1 else 0.1
    steps = build_rebalance_plan(cl.catalog, strategy,
                                 threshold=threshold)
    return Result(columns=list(PLAN_COLUMNS), rows=plan_rows(steps))


@utility("citus_autopilot_log")
def _citus_autopilot_log(cl, name, args):
    """The autopilot's decision ring, cluster-wide: every evaluated
    action — executed, observed (dry-run mode), declined, adopted —
    with the evidence snapshot that drove it (services/autopilot.py)."""
    from citus_tpu.observability.cluster_stats import (
        cluster_node_stats, payload_node,
    )
    from citus_tpu.services.autopilot import LOG_COLUMNS
    rows = []
    for p in cluster_node_stats(cl):
        if p.get("unreachable"):
            continue
        node = payload_node(p)
        for r in p.get("autopilot", []):
            rows.append((node, *r))
    rows.sort(key=lambda r: (-(r[1] or 0), r[0]))
    return Result(columns=["node", *LOG_COLUMNS], rows=rows)


@utility("citus_device_memory")
def _citus_device_memory(cl, name, args):
    """HBM ledger of the device batch cache: one row per
    (table, tenant) attribution plus total/high-water/capacity rows —
    the invariant surface (entry rows sum exactly to the total)."""
    from citus_tpu.executor.device_cache import GLOBAL_CACHE
    mv = GLOBAL_CACHE.memory_view()
    rows = [("entry", table, tenant, b)
            for table, tenant, b in mv["by_owner"]]
    rows.append(("total", None, None, mv["live_bytes"]))
    rows.append(("high_water", None, None, mv["high_water_bytes"]))
    rows.append(("capacity", None, None, mv["capacity_bytes"]))
    return Result(columns=["scope", "table", "tenant", "bytes"],
                  rows=rows)


# -------------------------------------------------- continuous aggregation


@utility("citus_create_rollup")
def _citus_create_rollup(cl, name, args):
    """SELECT citus_create_rollup(name, source, 'g1, g2',
    'count(*), sum(x), approx_count_distinct(y)') — register a
    re-mergeable rollup table colocated with its source and backfill
    it from the current contents (rollup/manager.py)."""
    if len(args) != 4:
        raise UnsupportedFeatureError(
            "citus_create_rollup(name, source, group_cols, aggs)")
    cl.rollup_manager.create_rollup(str(args[0]), str(args[1]),
                                    str(args[2]), str(args[3]))
    return Result(columns=[name], rows=[(None,)])


@utility("citus_drop_rollup")
def _citus_drop_rollup(cl, name, args):
    cl.rollup_manager.drop_rollup(str(args[0]))
    return Result(columns=[name], rows=[(None,)])


@utility("citus_refresh_rollups")
def _citus_refresh_rollups(cl, name, args):
    """Synchronously drain every rollup to its CDC head (the manual
    door; the background loop does the same on a cadence)."""
    folded = cl.rollup_manager.run_once()
    return Result(columns=["rows_folded"], rows=[(folded,)],
                  explain={"rollup_rows_folded": folded})


@utility("citus_rollups")
def _citus_rollups(cl, name, args):
    """One row per registered rollup with its durable watermark, the
    source's CDC head, and the refresh lag in pending change records."""
    return Result(
        columns=["name", "source", "rollup_table", "backend",
                 "watermark", "head_lsn", "pending_changes"],
        rows=[tuple(r) for r in cl.rollup_manager.rollup_rows()])


@utility("citus_slow_queries")
def _citus_slow_queries(cl, name, args):
    """The bounded slow-query ring (citus.log_min_duration_ms),
    newest first, with per-phase durations from each query's trace."""
    from citus_tpu.observability.slowlog import GLOBAL_SLOW_LOG
    return Result(columns=["captured_at", "duration_ms", "trace_id",
                           "phases", "query"],
                  rows=GLOBAL_SLOW_LOG.rows_view())


@utility("citus_slow_queries_reset")
def _citus_slow_queries_reset(cl, name, args):
    from citus_tpu.observability.slowlog import GLOBAL_SLOW_LOG
    GLOBAL_SLOW_LOG.clear()
    return Result(columns=[name], rows=[(None,)])


@utility("citus_locks")
def _citus_locks(cl, name, args):
    return Result(columns=["resource", "session", "mode", "granted"],
                  rows=cl.locks.lock_rows())


@utility("citus_lock_waits")
def _citus_lock_waits(cl, name, args):
    graph = cl.locks.wait_graph()
    return Result(columns=["waiting_session", "blocking_session"],
                  rows=[(w, b) for w, bs in graph.items() for b in sorted(bs)])


@utility("get_rebalance_progress")
def _get_rebalance_progress(cl, name, args):
    rows = []
    if cl._background_jobs is not None:
        # public snapshot only — no reaching into the runner's lock/state
        jobs = [j["job_id"] for j in cl._background_jobs.jobs_view()["jobs"]]
        for jid in jobs:
            rows.extend(cl._background_jobs.job_progress(jid))
    return Result(columns=["task_id", "op", "args", "status", "attempts",
                           "phase", "bytes_done", "bytes_total",
                           "started_at", "eta_s"],
                  rows=rows)


# -------------------------------------------------------- shards & sizing

@utility("citus_table_size", "citus_relation_size",
         "citus_total_relation_size")
def _citus_table_size(cl, name, args):
    return Result(columns=[name], rows=[(cl._table_size(str(args[0])),)])


@utility("citus_shard_sizes")
def _citus_shard_sizes(cl, name, args):
    import os as _os
    rows = []
    for t in cl.catalog.tables.values():
        for s_ in t.shards:
            for node in s_.placements:
                d = cl.catalog.shard_dir(t.name, s_.shard_id, node)
                size = sum(_os.path.getsize(_os.path.join(d, f))
                           for f in _os.listdir(d)) if _os.path.isdir(d) else 0
                rows.append((t.name, s_.shard_id, node, size))
    return Result(columns=["table_name", "shardid", "node", "size"], rows=rows)


@utility("citus_shards")
def _citus_shards(cl, name, args):
    rows = []
    for t in cl.catalog.tables.values():
        for s in t.shards:
            for node in s.placements:
                rows.append((t.name, s.shard_id, t.method, t.colocation_id,
                             node, s.hash_min, s.hash_max))
    return Result(columns=["table_name", "shardid", "citus_table_type",
                           "colocation_id", "nodename", "shardminvalue",
                           "shardmaxvalue"], rows=rows)


@utility("citus_tables")
def _citus_tables(cl, name, args):
    from citus_tpu.catalog.stats import table_row_count
    rows = []
    for t in cl.catalog.tables.values():
        rows.append((t.name, t.method, t.dist_column, t.colocation_id,
                     cl._table_size(t.name), t.shard_count,
                     table_row_count(cl.catalog, t)))
    return Result(columns=["table_name", "citus_table_type",
                           "distribution_column", "colocation_id",
                           "table_size", "shard_count", "row_count"],
                  rows=rows)


@utility("get_shard_id_for_distribution_column")
def _get_shard_id_for_distribution_column(cl, name, args):
    import numpy as _np

    from citus_tpu.catalog.hashing import hash_int64_scalar
    t2 = cl.catalog.table(str(args[0]))
    if not t2.is_distributed:
        return Result(columns=[name], rows=[(t2.shards[0].shard_id,)])
    h = hash_int64_scalar(int(args[1]))
    si = t2.route_hash(h)
    return Result(columns=[name], rows=[(t2.shards[si].shard_id,)])


# -------------------------------------------------------- node management

@utility("citus_check_cluster_node_health")
def _citus_check_cluster_node_health(cl, name, args):
    import os as _os
    rows = []
    for nid in cl.catalog.active_node_ids():
        ok = True
        for t in cl.catalog.tables.values():
            for s_ in t.shards:
                if nid in s_.placements:
                    d = cl.catalog.shard_dir(t.name, s_.shard_id, nid)
                    if _os.path.isdir(d) and not _os.access(d, _os.R_OK):
                        ok = False
        rows.append((nid, ok))
    return Result(columns=["node", "healthy"], rows=rows)


@utility("master_get_active_worker_nodes")
def _master_get_active_worker_nodes(cl, name, args):
    return Result(columns=["node_id"],
                  rows=[(nid,) for nid in cl.catalog.active_node_ids()])


@utility("citus_add_node")
def _citus_add_node(cl, name, args):
    """citus_add_node([nodename, nodeport]): with arguments, the node
    advertises a data-plane endpoint (pg_dist_node nodename/nodeport,
    sql/citus--8.0-1.sql:401); without, a local-placement node."""
    from citus_tpu.catalog.catalog import NodeMeta
    nid = max(cl.catalog.nodes, default=-1) + 1
    host = str(args[0]) if len(args) > 0 else None
    port = int(args[1]) if len(args) > 1 else None
    cl.catalog.nodes[nid] = NodeMeta(nid, True, host, port)
    cl.catalog.ddl_epoch += 1
    cl.catalog.commit()
    return Result(columns=["citus_add_node"], rows=[(nid,)])


@utility("citus_remote_stats")
def _citus_remote_stats(cl, name, args):
    """Data-plane transfer counters (files/bytes fetched, batches
    shipped, placement syncs) — the cross-host analog of the
    connection-level stats views."""
    rd = cl.catalog.remote_data
    st = dict(rd.stats) if rd is not None else {}
    cols = ["files_fetched", "bytes_fetched", "batches_shipped",
            "remote_syncs"]
    return Result(columns=cols,
                  rows=[tuple(st.get(c, 0) for c in cols)])


@utility("citus_remove_node")
def _citus_remove_node(cl, name, args):
    nid = int(args[0]) if args else None
    if nid is None or nid not in cl.catalog.nodes:
        raise CatalogError(f"node {nid} does not exist")
    for t in cl.catalog.tables.values():
        for s in t.shards:
            if nid in s.placements:
                raise CatalogError(
                    f"cannot remove node {nid}: it still has shard placements")
    del cl.catalog.nodes[nid]
    cl.catalog.ddl_epoch += 1
    cl.catalog.commit()
    return Result(columns=["citus_remove_node"], rows=[(None,)])


@utility("citus_disable_node")
def _citus_disable_node(cl, name, args):
    nid = int(args[0])
    if nid not in cl.catalog.nodes:
        raise CatalogError(f"node {nid} does not exist")
    cl.catalog.nodes[nid].is_active = False
    cl.catalog.ddl_epoch += 1
    cl.catalog.commit()
    cl._plan_cache.clear()
    return Result(columns=[name], rows=[(None,)])


@utility("citus_activate_node")
def _citus_activate_node(cl, name, args):
    nid = int(args[0])
    if nid not in cl.catalog.nodes:
        raise CatalogError(f"node {nid} does not exist")
    cl.catalog.nodes[nid].is_active = True
    cl.catalog.ddl_epoch += 1
    cl.catalog.commit()
    cl._plan_cache.clear()
    return Result(columns=[name], rows=[(nid,)])


@utility("citus_activate_node_metadata")
def _citus_activate_node_metadata(cl, name, args):
    # start_metadata_sync_to_node/citus_activate_node analog: mark the
    # node a full metadata peer (pg_dist_node.hasmetadata) so it plans
    # and admits locally; the sync engine keeps its catalog converged
    nid = int(args[0])
    if nid not in cl.catalog.nodes:
        raise CatalogError(f"node {nid} does not exist")
    cl.catalog.nodes[nid].metadata_synced = True
    cl.catalog.ddl_epoch += 1
    cl.catalog.commit()
    return Result(columns=[name], rows=[(nid,)])


@utility("citus_sync_metadata")
def _citus_sync_metadata(cl, name, args):
    # one on-demand pull-on-mismatch round against the metadata
    # authority (the interval loop's unit of work); returns how many
    # catalog objects were applied — 0 means already converged, and on
    # the authority itself there is nothing to pull from
    applied = cl.metadata_sync.sync_once()
    return Result(columns=["objects_applied"], rows=[(applied,)])


@utility("citus_get_active_worker_nodes")
def _citus_get_active_worker_nodes(cl, name, args):
    return Result(columns=["node_id"],
                  rows=[(n,) for n in cl.catalog.active_node_ids()])


@utility("citus_coordinator_nodeid")
def _citus_coordinator_nodeid(cl, name, args):
    nids = sorted(cl.catalog.active_node_ids())
    return Result(columns=["citus_coordinator_nodeid"],
                  rows=[(nids[0] if nids else 0,)])


# ------------------------------------------------------ shard operations

@utility("citus_move_shard_placement")
def _citus_move_shard_placement(cl, name, args):
    from citus_tpu.operations import move_shard_placement
    move_shard_placement(cl.catalog, int(args[0]), int(args[1]),
                         int(args[2]), lock_manager=cl.locks,
                         settings=cl.settings)
    cl._plan_cache.clear()
    return Result(columns=[name], rows=[(None,)])


@utility("citus_shard_move_stats")
def _citus_shard_move_stats(cl, name, args):
    # per-move view of the non-blocking sequence (operations/
    # shard_transfer.py MOVE_STATS): catch-up rounds run and the
    # blocked-write window — the milliseconds writers were actually
    # excluded — next to the total move time they'd have been blocked
    # for under a stop-the-world copy
    from citus_tpu.operations import MOVE_STATS
    cols = ["op", "shard_id", "source", "target", "bytes_copied",
            "catchup_rounds", "blocked_write_ms", "total_ms"]
    return Result(columns=cols,
                  rows=[tuple(r.get(c) for c in cols)
                        for r in MOVE_STATS.rows()])


@utility("get_rebalance_table_shards_plan")
def _get_rebalance_table_shards_plan(cl, name, args):
    from citus_tpu.operations import get_rebalance_plan
    moves = get_rebalance_plan(
        cl.catalog, args[0] if args else None,
        strategy=str(args[1]) if len(args) > 1 else "by_disk_size")
    return Result(columns=["shardid", "sourcenode", "targetnode"],
                  rows=[m.to_row() for m in moves])


@utility("rebalance_table_shards")
def _rebalance_table_shards(cl, name, args):
    from citus_tpu.operations import rebalance_table_shards
    moves = rebalance_table_shards(
        cl.catalog, args[0] if args else None,
        strategy=str(args[1]) if len(args) > 1 else "by_disk_size",
        lock_manager=cl.locks, settings=cl.settings)
    cl._plan_cache.clear()
    return Result(columns=["rebalance_table_shards"], rows=[(len(moves),)])


@utility("citus_rebalance_start")
def _citus_rebalance_start(cl, name, args):
    from citus_tpu.operations import get_rebalance_plan
    moves = get_rebalance_plan(cl.catalog)
    jid = cl.background_jobs.create_job("Rebalance all colocation groups")
    prev = None
    for m in moves:
        prev = cl.background_jobs.add_task(
            jid, "move_shard",
            {"shard_id": m.shard_id, "source": m.source_node,
             "target": m.target_node},
            depends_on=[prev] if prev is not None else None,
            node=m.target_node)
    return Result(columns=["citus_rebalance_start"], rows=[(jid,)])


@utility("citus_job_wait")
def _citus_job_wait(cl, name, args):
    status = cl.background_jobs.wait_for_job(int(args[0]))
    cl._plan_cache.clear()
    return Result(columns=["citus_job_wait"], rows=[(status,)])


@utility("citus_cleanup_orphaned_resources")
def _citus_cleanup_orphaned_resources(cl, name, args):
    from citus_tpu.operations import try_drop_orphaned_resources
    n = try_drop_orphaned_resources(cl.catalog)
    return Result(columns=["citus_cleanup_orphaned_resources"], rows=[(n,)])


@utility("citus_copy_shard_placement")
def _citus_copy_shard_placement(cl, name, args):
    from citus_tpu.operations import copy_shard_placement
    copy_shard_placement(cl.catalog, int(args[0]), int(args[1]), int(args[2]))
    cl._plan_cache.clear()
    return Result(columns=[name], rows=[(None,)])


@utility("citus_split_shard_by_split_points")
def _citus_split_shard_by_split_points(cl, name, args):
    from citus_tpu.operations.shard_split import split_shard
    points = [int(a) for a in args[1:]
              if not isinstance(a, str) or a.lstrip("-").isdigit()]
    new_ids = split_shard(cl.catalog, int(args[0]), points,
                          lock_manager=cl.locks, settings=cl.settings)
    cl._plan_cache.clear()
    return Result(columns=["new_shard_ids"], rows=[(i,) for i in new_ids])


@utility("isolate_tenant_to_new_shard")
def _isolate_tenant_to_new_shard(cl, name, args):
    # reference: isolate_shards.c — put one distribution-key value in its
    # own shard by splitting around its hash
    from citus_tpu.catalog.hashing import hash_int64_scalar
    from citus_tpu.operations.shard_split import split_shard
    t = cl.catalog.table(args[0])
    h = hash_int64_scalar(int(args[1]))
    shard = t.shards[t.route_hash(h)]
    points = []
    if h - 1 >= shard.hash_min:
        points.append(h - 1)
    if h < shard.hash_max:
        points.append(h)
    new_ids = split_shard(cl.catalog, shard.shard_id, points,
                          lock_manager=cl.locks, settings=cl.settings)
    cl._plan_cache.clear()
    return Result(columns=["isolate_tenant_to_new_shard"],
                  rows=[(new_ids[1 if h - 1 >= shard.hash_min else 0],)])


# ----------------------------------------------------- workload management

@utility("citus_add_tenant_quota")
def _citus_add_tenant_quota(cl, name, args):
    # SELECT citus_add_tenant_quota(tenant, weight [, max_concurrency
    # [, rate_limit_qps [, queue_depth [, priority_class]]]]) — a
    # REPLICATED catalog write (metadata/quotas.py): the quota persists
    # in the catalog document and every coordinator's registry mirrors
    # it, so admission decisions match cluster-wide; 0/"" falls back to
    # the citus.tenant_* GUC defaults
    from citus_tpu.metadata import replicated_set_quota
    replicated_set_quota(
        cl, str(args[0]),
        weight=float(args[1]) if len(args) > 1 else 0.0,
        max_concurrency=int(args[2]) if len(args) > 2 else 0,
        rate_limit_qps=float(args[3]) if len(args) > 3 else 0.0,
        queue_depth=int(args[4]) if len(args) > 4 else 0,
        priority_class=str(args[5]) if len(args) > 5 else "")
    return Result(columns=[name], rows=[(str(args[0]),)])


@utility("citus_remove_tenant_quota")
def _citus_remove_tenant_quota(cl, name, args):
    from citus_tpu.metadata import replicated_remove_quota
    return Result(columns=[name],
                  rows=[(replicated_remove_quota(cl, str(args[0])),)])


@utility("citus_tenant_quotas")
def _citus_tenant_quotas(cl, name, args):
    from citus_tpu.workload import GLOBAL_TENANTS
    return Result(columns=["tenant", "weight", "max_concurrency",
                           "rate_limit_qps", "queue_depth", "pinned_node",
                           "priority_class"],
                  rows=GLOBAL_TENANTS.rows_view())


@utility("citus_add_priority_class")
def _citus_add_priority_class(cl, name, args):
    # SELECT citus_add_priority_class(class, weight) — a class node in
    # the scheduler's two-level stride tree; replicated like a quota
    from citus_tpu.metadata import replicated_set_class
    replicated_set_class(cl, str(args[0]),
                         float(args[1]) if len(args) > 1 else 1.0)
    return Result(columns=[name], rows=[(str(args[0]),)])


@utility("citus_priority_classes")
def _citus_priority_classes(cl, name, args):
    from citus_tpu.workload import GLOBAL_TENANTS
    return Result(columns=["class", "weight"],
                  rows=GLOBAL_TENANTS.classes_view())


@utility("citus_isolate_tenant_to_node")
def _citus_isolate_tenant_to_node(cl, name, args):
    # isolate_tenant_to_new_shard + move_shard_placement in one call:
    # the tenant's shard lands on a dedicated host and the pin is
    # recorded in the quota registry (workload/isolation.py)
    from citus_tpu.workload.isolation import isolate_tenant_to_node
    shard_id = isolate_tenant_to_node(cl, str(args[0]), args[1],
                                      int(args[2]))
    return Result(columns=[name], rows=[(shard_id,)])


@utility("undistribute_table")
def _undistribute_table(cl, name, args):
    from citus_tpu.operations.alter_table import undistribute_table
    undistribute_table(cl.catalog, args[0], txlog=cl.txlog)
    cl._plan_cache.clear()
    return Result(columns=[name], rows=[(None,)])


@utility("alter_distributed_table")
def _alter_distributed_table(cl, name, args):
    from citus_tpu.operations.alter_table import alter_distributed_table
    kw = {}
    if len(args) > 1:
        kw["shard_count"] = int(args[1])
    if len(args) > 2:
        kw["distribution_column"] = str(args[2])
    alter_distributed_table(cl.catalog, args[0], txlog=cl.txlog, **kw)
    cl._plan_cache.clear()
    return Result(columns=[name], rows=[(None,)])


# --------------------------------------------------- clock, restore, misc

@utility("citus_get_node_clock")
def _citus_get_node_clock(cl, name, args):
    return Result(columns=["citus_get_node_clock"], rows=[(cl.clock.now(),)])


@utility("citus_get_transaction_clock")
def _citus_get_transaction_clock(cl, name, args):
    return Result(columns=["citus_get_transaction_clock"],
                  rows=[(cl.clock.transaction_clock(),)])


@utility("citus_create_restore_point")
def _citus_create_restore_point(cl, name, args):
    from citus_tpu.operations.restore import create_restore_point
    create_restore_point(cl.catalog, str(args[0]))
    return Result(columns=["citus_create_restore_point"],
                  rows=[(str(args[0]),)])


@utility("citus_list_restore_points")
def _citus_list_restore_points(cl, name, args):
    from citus_tpu.operations.restore import list_restore_points
    return Result(columns=["name", "created_at"],
                  rows=list_restore_points(cl.catalog))


@utility("nextval")
def _nextval(cl, name, args):
    return Result(columns=["nextval"],
                  rows=[(cl.catalog.nextval(str(args[0])),)])


@utility("currval")
def _currval(cl, name, args):
    return Result(columns=["currval"],
                  rows=[(cl.catalog.currval(str(args[0])),)])


@utility("setval")
def _setval(cl, name, args):
    v = cl.catalog.setval(str(args[0]), int(args[1]))
    return Result(columns=["setval"], rows=[(v,)])


@utility("citus_cdc_events")
def _citus_cdc_events(cl, name, args):
    # consumer API: changes for a table after an LSN (reference: the
    # decoder stream a subscriber reads)
    table = str(args[0])
    from_lsn = int(args[1]) if len(args) > 1 else 0
    rows = [(e["lsn"], e["op"], e.get("count"),
             json.dumps(e.get("rows")) if e.get("rows") else None)
            for e in cl.cdc.events(table, from_lsn)]
    return Result(columns=["lsn", "op", "count", "rows"], rows=rows)


@utility("recover_prepared_transactions")
def _recover_prepared_transactions(cl, name, args):
    from citus_tpu.transaction.recovery import recover_transactions
    st = recover_transactions(cl.catalog, cl.txlog,
                              peer_inflight=cl._peer_inflight(),
                              gxid_outcome=cl._gxid_outcome)
    return Result(columns=["recover_prepared_transactions"],
                  rows=[(st["rolled_forward"] + st["rolled_back"],)])


@utility("run_command_on_workers")
def _run_command_on_workers(cl, name, args):
    # reference: operations/citus_tools.c run_command_on_workers — one
    # row per node.  Nodes here share one engine, so the command runs
    # ONCE and the result row replicates per node (running it N times
    # would also repeat side effects)
    try:
        r = cl.execute(str(args[0]))
        cell = r.rows[0][0] if r.rows and r.rows[0] else ""
        ok, res = True, str(cell)
    except Exception as exc:
        ok, res = False, str(exc)
    rows = [(nid, ok, res) for nid in sorted(cl.catalog.active_node_ids())]
    return Result(columns=["nodeid", "success", "result"], rows=rows)


@utility("run_command_on_shards", "run_command_on_placements")
def _run_command_on_shards(cl, name, args):
    return cl._run_command_on_shards(
        str(args[0]), str(args[1]),
        per_placement=(name == "run_command_on_placements"))


@utility("master_get_table_ddl_events")
def _master_get_table_ddl_events(cl, name, args):
    return Result(columns=["master_get_table_ddl_events"],
                  rows=[(d,) for d in cl._table_ddl(str(args[0]))])


@utility("citus_backend_gpid")
def _citus_backend_gpid(cl, name, args):
    import threading as _threading
    return Result(columns=["citus_backend_gpid"],
                  rows=[(_threading.get_ident(),)])


@utility("citus_version")
def _citus_version(cl, name, args):
    from citus_tpu.version import __version__ as _v
    return Result(columns=["citus_version"],
                  rows=[(f"citus_tpu {_v} (capability parity target: "
                         "Citus 15.0devel)",)])
