"""Statement-type handler registry (DistributeObjectOps analog).

Each handler is ``fn(cl, stmt) -> Result`` where ``cl`` is the Cluster.
Handlers register against AST node types; ``dispatch`` resolves the
statement's type (exact match — AST nodes are flat dataclasses with no
inheritance between statement kinds).

Reference: commands/distribute_object_ops.c maps parse-tree node tags to
{deparse, qualify, preprocess, postprocess, address, markDistributed}
operation sets; our per-task executable form is a plan + jitted kernel
spec rather than SQL text, so one ``execute`` hook suffices.
"""

from __future__ import annotations

from typing import Callable, Optional

STATEMENT_HANDLERS: dict[type, Callable] = {}

UTILITY_HANDLERS: dict[str, Callable] = {}


def handles(*ast_types):
    """Register a handler for one or more AST statement types."""
    def deco(fn):
        for t in ast_types:
            if t in STATEMENT_HANDLERS:
                raise RuntimeError(f"duplicate handler for {t.__name__}")
            STATEMENT_HANDLERS[t] = fn
        return fn
    return deco


def utility(*names):
    """Register a handler for a UDF-style admin call by name."""
    def deco(fn):
        for n in names:
            if n in UTILITY_HANDLERS:
                raise RuntimeError(f"duplicate utility handler for {n}")
            UTILITY_HANDLERS[n] = fn
        return fn
    return deco


def lookup(stmt) -> Optional[Callable]:
    return STATEMENT_HANDLERS.get(type(stmt))
