"""Table DDL handlers: CREATE TABLE [AS / PARTITION OF], DROP TABLE,
ALTER TABLE, CREATE/DROP INDEX.

Reference: commands/table.c (4601 LoC), commands/index.c,
commands/alter_table.c dispatched through DistributeObjectOps.
"""

from __future__ import annotations

from citus_tpu.commands.registry import handles
from citus_tpu.errors import (
    AnalysisError, CatalogError, UnsupportedFeatureError,
)
from citus_tpu.executor import Result
from citus_tpu.planner import ast as A
from citus_tpu.schema import Column, Schema
from citus_tpu.types import type_from_sql


@handles(A.CreateTableAs)
def create_table_as(cl, stmt):
    if cl.catalog.has_table(stmt.name):
        if stmt.if_not_exists:
            return Result(columns=[], rows=[])
        raise CatalogError(f'relation "{stmt.name}" already exists')
    r = cl._execute_stmt(stmt.select)
    names, types = cl._schema_from_result(r, strict_empty=True)
    # atomic create+load: a load failure must not leave an empty
    # committed table behind (transparent inside a user txn)
    with cl._internal_txn():
        cl.create_table(stmt.name,
                        Schema([Column(cn, ct_)
                                for cn, ct_ in zip(names, types)]))
        if r.rows:
            cl.copy_from(stmt.name, rows=r.rows, column_names=names)
    return Result(columns=[], rows=[], explain={"selected": len(r.rows)})


@handles(A.CreateTable)
def create_table(cl, stmt):
    if stmt.partition_of is not None:
        cl._create_partition(
            stmt.name, stmt.partition_of["parent"],
            stmt.partition_of["lo"], stmt.partition_of["hi"],
            if_not_exists=stmt.if_not_exists)
        return Result(columns=[], rows=[])
    from citus_tpu import types as T
    cols, enum_binds = [], []
    domain_binds = []
    serial_seqs = []  # sequences to create for serial columns
    _SERIAL = {"smallserial": "smallint", "serial": "int",
               "bigserial": "bigint"}
    for c in stmt.columns:
        default_sql = c.default_sql
        type_name = c.type_name
        if type_name in _SERIAL:
            # serial = integer + owned sequence + nextval default
            # (reference: commands/sequence.c ownership propagation)
            seq = f"{stmt.name}_{c.name}_seq"
            serial_seqs.append(seq)
            default_sql = f"nextval('{seq}')"
            type_name = _SERIAL[type_name]
        if type_name in cl.catalog.types:
            cols.append(Column(c.name, T.TEXT_T, c.not_null,
                               default_sql=default_sql))
            enum_binds.append((c.name, c.type_name))
        elif type_name in cl.catalog.domains:
            d = cl.catalog.domains[type_name]
            cols.append(Column(
                c.name,
                type_from_sql(d["base"], d["args"] or None),
                c.not_null or d["not_null"], default_sql=default_sql))
            domain_binds.append((c.name, type_name))
        else:
            cols.append(Column(
                c.name, type_from_sql(type_name, c.type_args or None),
                c.not_null, default_sql=default_sql))
    schema = Schema(cols)
    opts = {k: v for k, v in stmt.options.items() if k != "access_method"}
    fks = []
    pre_existing = cl.catalog.has_table(stmt.name)
    # pre-validate implicit PK/UNIQUE indexes and the partition clause
    # BEFORE the table commits: PostgreSQL's CREATE TABLE is
    # all-or-nothing
    want_indexes = []
    if not pre_existing:
        seen_ix: set = set()
        for c in stmt.columns:
            if not (c.primary_key or c.unique):
                continue
            iname = (f"{stmt.name}_pkey" if c.primary_key
                     else f"{stmt.name}_{c.name}_key")
            if iname in seen_ix or cl._find_index(iname)[1] is not None:
                raise CatalogError(f'index "{iname}" already exists')
            seen_ix.add(iname)
            if schema.column(c.name).type.is_float:
                raise UnsupportedFeatureError(
                    "UNIQUE indexes over floating-point columns "
                    "are not supported (no exact equality)")
            want_indexes.append((iname, c.name))
        if stmt.partition_by is not None:
            schema.column(stmt.partition_by)  # must exist
            # PostgreSQL: a unique constraint on a partitioned table
            # must include the partition column
            for _, cname in want_indexes:
                if cname != stmt.partition_by:
                    raise UnsupportedFeatureError(
                        "unique constraint on partitioned table "
                        "must include the partition column")
    if serial_seqs and not pre_existing:
        # a serial column's implicit sequence must not clobber a
        # pre-existing same-named sequence (PostgreSQL errors with
        # 'relation already exists'); the one exception is a leftover
        # OWNED by an earlier incarnation of this same table, which a
        # DROP TABLE crash could strand — that one restarts below.
        # Validated BEFORE the table commits: all-or-nothing.
        for seq in serial_seqs:
            existing = cl.catalog.sequences.get(seq)
            if existing is not None \
                    and existing.get("owner") != stmt.name:
                raise CatalogError(f'relation "{seq}" already exists')
    if stmt.checks and not pre_existing:
        # pre-validate CHECK expressions BEFORE the table commits
        # (CREATE TABLE is all-or-nothing, like the index/partition
        # validation above) — bound against a transient TableMeta
        from citus_tpu.catalog.catalog import TableMeta as _TM
        from citus_tpu.planner.bind import Binder
        from citus_tpu.planner.parser import Parser
        probe = _TM(name=stmt.name, schema=schema)
        for sql in stmt.checks:
            bound = Binder(cl.catalog, probe).bind_scalar(
                Parser(sql).parse_expr())
            if bound.type.kind != "bool":
                raise AnalysisError(
                    f"CHECK constraint must be boolean: ({sql})")
    if stmt.foreign_keys and not pre_existing:
        from citus_tpu.integrity import declare_fks
        fks = declare_fks(cl.catalog, stmt.name,
                          stmt.foreign_keys, schema=schema)
    cl.create_table(stmt.name, schema, if_not_exists=stmt.if_not_exists,
                    **opts)
    if fks and not pre_existing and cl.catalog.has_table(stmt.name):
        # IF NOT EXISTS no-op must not clobber existing constraints
        cl.catalog.table(stmt.name).foreign_keys = fks
        cl.catalog.commit()
    if enum_binds and cl.catalog.has_table(stmt.name):
        for cn, tn in enum_binds:
            cl.catalog.enum_columns[f"{stmt.name}.{cn}"] = tn
        cl.catalog.commit()
    if domain_binds and not pre_existing \
            and cl.catalog.has_table(stmt.name):
        for cn, dn in domain_binds:
            cl.catalog.domain_columns[f"{stmt.name}.{cn}"] = dn
        cl.catalog.commit()
    if want_indexes and cl.catalog.has_table(stmt.name):
        # PRIMARY KEY / UNIQUE column constraints become unique indexes
        # (PostgreSQL's implicit btree; pg_index rows) — pre-validated
        # above, so these cannot fail halfway
        for iname, cname in want_indexes:
            cl.create_index(iname, stmt.name, cname, unique=True)
    if stmt.partition_by is not None \
            and not pre_existing and cl.catalog.has_table(stmt.name):
        # validated before create_table above
        t0 = cl.catalog.table(stmt.name)
        t0.partition_by = {"column": stmt.partition_by, "kind": "range"}
        cl.catalog.commit()
    if stmt.checks and not pre_existing \
            and cl.catalog.has_table(stmt.name):
        t0 = cl.catalog.table(stmt.name)
        for i, sql in enumerate(stmt.checks):  # pre-validated above
            t0.check_constraints.append(
                {"name": f"{stmt.name}_check{i + 1}", "sql": sql})
        cl.catalog.commit()
    if serial_seqs and not pre_existing \
            and cl.catalog.has_table(stmt.name):
        # owned sequences exist only once the table does; a stale
        # same-owner sequence from an earlier incarnation restarts
        # (PostgreSQL drops owned sequences with their table) —
        # foreign sequences were rejected before the table committed
        for seq in serial_seqs:
            if seq in cl.catalog.sequences:
                cl.catalog.drop_sequence(seq)
            cl.catalog.create_sequence(seq, 1, 1)
            # ownership tag: lets the pre-validation above tell a
            # restartable leftover from somebody else's sequence
            cl.catalog.sequences[seq]["owner"] = stmt.name
        cl.catalog.commit()
    return Result(columns=[], rows=[])


@handles(A.DropTable)
def drop_table(cl, stmt):
    cl.drop_table(stmt.name, if_exists=stmt.if_exists)
    return Result(columns=[], rows=[])


@handles(A.CreateIndex)
def create_index(cl, stmt):
    return cl._execute_create_index(stmt)


@handles(A.DropIndex)
def drop_index(cl, stmt):
    return cl._execute_drop_index(stmt)


@handles(A.AlterTable)
def alter_table(cl, stmt):
    if cl.catalog.has_table(stmt.table) \
            and cl.catalog.table(stmt.table).is_partitioned:
        if stmt.action in ("rename_table", "rename_column"):
            raise UnsupportedFeatureError(
                "renaming a partitioned parent (or its columns) "
                "is not supported")
        if stmt.action == "drop_column" \
                and stmt.old_name == cl.catalog.table(
                    stmt.table).partition_by["column"]:
            raise CatalogError("cannot drop the partition column")
        # PostgreSQL: schema changes on the parent cascade to every
        # partition
        import dataclasses as _dc
        for p in cl.catalog.partitions_of(stmt.table):
            cl._execute_stmt(_dc.replace(stmt, table=p.name))
    if stmt.action == "drop_constraint":
        t0 = cl.catalog.table(stmt.table)
        kept = [c for c in t0.check_constraints
                if c["name"] != stmt.old_name]
        fks_kept = [f for f in t0.foreign_keys
                    if f.get("name") != stmt.old_name]
        if len(kept) == len(t0.check_constraints) \
                and len(fks_kept) == len(t0.foreign_keys):
            raise CatalogError(
                f'constraint "{stmt.old_name}" of relation '
                f'"{stmt.table}" does not exist')
        t0.check_constraints[:] = kept
        t0.foreign_keys[:] = fks_kept
        t0.version += 1
        cl.catalog.commit()
        cl._plan_cache.invalidate_table(stmt.table)
        return Result(columns=[], rows=[])
    if stmt.action == "set_default":
        import dataclasses as _dc
        t0 = cl.catalog.table(stmt.table)
        t0.schema.column(stmt.old_name)  # must exist
        if stmt.check_sql is not None:
            from citus_tpu.planner.parser import Parser
            Parser(stmt.check_sql).parse_expr()  # must parse
        t0.schema.columns[:] = [
            _dc.replace(c, default_sql=stmt.check_sql or "")
            if c.name == stmt.old_name else c
            for c in t0.schema.columns]
        t0.version += 1
        cl.catalog.commit()
        return Result(columns=[], rows=[])
    if stmt.action == "add_check":
        from citus_tpu.planner.bind import Binder
        from citus_tpu.planner.parser import Parser
        from citus_tpu.transaction.locks import EXCLUSIVE
        t0 = cl.catalog.table(stmt.table)
        bound = Binder(cl.catalog, t0).bind_scalar(
            Parser(stmt.check_sql).parse_expr())
        if bound.type.kind != "bool":
            raise AnalysisError(
                f"CHECK constraint must be boolean: ({stmt.check_sql})")
        # PostgreSQL validates existing rows at ADD time: any row where
        # the expression is FALSE (NULL passes) rejects the DDL.  The
        # validation scan and the catalog commit hold the colocation
        # group's EXCLUSIVE write lock as ONE critical section — a
        # writer landing between them could commit a violating row the
        # scan never saw (PostgreSQL holds AccessExclusiveLock across
        # ADD CONSTRAINT's validation for the same reason); reads are
        # snapshot-based and never block behind this lock
        with cl._write_lock(t0, EXCLUSIVE):
            t0 = cl.catalog.table(stmt.table)  # re-fetch under lock
            r = cl._execute_stmt(A.Select(
                [A.SelectItem(A.FuncCall("count", (A.Star(),)))],
                A.TableRef(stmt.table),
                A.UnOp("not", Parser(stmt.check_sql).parse_expr())))
            if r.rows and r.rows[0][0]:
                raise AnalysisError(
                    f'check constraint of relation "{stmt.table}" is '
                    f"violated by {r.rows[0][0]} existing row(s)")
            ck_name = stmt.new_name or \
                f"{stmt.table}_check{len(t0.check_constraints) + 1}"
            if any(c["name"] == ck_name for c in t0.check_constraints):
                raise CatalogError(
                    f'constraint "{ck_name}" already exists')
            t0.check_constraints.append({"name": ck_name,
                                         "sql": stmt.check_sql})
            cl.catalog.commit()
        cl._plan_cache.invalidate_table(stmt.table)
        return Result(columns=[], rows=[])
    if stmt.action == "add_column":
        from citus_tpu import types as T
        tn = stmt.column.type_name
        if tn in cl.catalog.types:  # enum
            col = Column(stmt.column.name, T.TEXT_T,
                         stmt.column.not_null)
            cl.catalog.add_column(stmt.table, col)
            cl.catalog.enum_columns[
                f"{stmt.table}.{stmt.column.name}"] = tn
        elif tn in cl.catalog.domains:
            d = cl.catalog.domains[tn]
            col = Column(stmt.column.name,
                         type_from_sql(d["base"], d["args"] or None),
                         stmt.column.not_null or d["not_null"])
            cl.catalog.add_column(stmt.table, col)
            cl.catalog.domain_columns[
                f"{stmt.table}.{stmt.column.name}"] = tn
        else:
            col = Column(stmt.column.name,
                         type_from_sql(tn, stmt.column.type_args or None),
                         stmt.column.not_null)
            cl.catalog.add_column(stmt.table, col)
    elif stmt.action == "drop_column":
        t0 = cl.catalog.table(stmt.table)
        if t0.index_on(stmt.old_name) is not None:
            from citus_tpu.storage.overlay import current_overlay
            txn0 = current_overlay()
            if txn0 is not None:
                # irreversible file removal: defer to COMMIT
                col0 = stmt.old_name
                tname0 = t0.name
                txn0.on_commit.append(
                    lambda: cl._drop_index_segments_if_unindexed(
                        tname0, col0))
            else:
                cl._drop_index_segments(t0, stmt.old_name)
            t0.indexes[:] = [ix for ix in t0.indexes
                             if ix["column"] != stmt.old_name]
        # PostgreSQL drops the table's own FK constraints that include
        # the column; a referenced parent column needs CASCADE
        # (unsupported here), so fail closed instead of leaving a stale
        # constraint behind.
        for child, fk in cl.catalog.referencing_fks(stmt.table):
            if child == stmt.table:
                continue  # self-FK belongs to this table: dropped
            if stmt.old_name in fk["ref_columns"]:
                raise AnalysisError(
                    f'cannot drop column "{stmt.old_name}" of '
                    f'table "{stmt.table}" because foreign key '
                    f'constraint "{fk["name"]}" on table '
                    f'"{child}" depends on it')
        t = cl.catalog.table(stmt.table)
        t.foreign_keys[:] = [
            fk for fk in t.foreign_keys
            if stmt.old_name not in fk["columns"]
            and not (fk["ref_table"] == stmt.table
                     and stmt.old_name in fk["ref_columns"])]
        key = f"{stmt.table}.{stmt.old_name}"
        if cl.catalog.domain_columns.pop(key, None) is not None:
            cl.catalog.tombstone("domain_columns", key)
        if cl.catalog.enum_columns.pop(key, None) is not None:
            cl.catalog.tombstone("enum_columns", key)
        # PostgreSQL auto-drops extended statistics with a column
        for sname in [n for n, st in cl.catalog.statistics.items()
                      if st["table"] == stmt.table
                      and stmt.old_name in st["columns"]]:
            del cl.catalog.statistics[sname]
            cl.catalog.tombstone("statistics", sname)
        cl.catalog.drop_column(stmt.table, stmt.old_name)
    elif stmt.action == "rename_column":
        t0 = cl.catalog.table(stmt.table)
        if t0.index_on(stmt.old_name) is not None:
            # segments are keyed by logical column name on disk: rename
            # them with the column
            import os as _os
            suffix = f".idx.{stmt.old_name}.npz"
            for shard in t0.shards:
                for node in shard.placements:
                    d = cl.catalog.shard_dir(
                        t0.name, shard.shard_id, node)
                    if not _os.path.isdir(d):
                        continue
                    for f in _os.listdir(d):
                        if f.endswith(suffix):
                            base = f[:-len(suffix)]
                            _os.replace(
                                _os.path.join(d, f),
                                _os.path.join(
                                    d, base + f".idx.{stmt.new_name}.npz"))
            for ix in t0.indexes:
                if ix["column"] == stmt.old_name:
                    ix["column"] = stmt.new_name
        cl.catalog.rename_column(stmt.table, stmt.old_name, stmt.new_name)
        # keep FK metadata consistent: this table's own key columns and
        # every child's referenced-column names
        for fk in cl.catalog.table(stmt.table).foreign_keys:
            fk["columns"] = [stmt.new_name if c == stmt.old_name
                             else c for c in fk["columns"]]
        for _child, fk in cl.catalog.referencing_fks(stmt.table):
            fk["ref_columns"] = [stmt.new_name if c == stmt.old_name
                                 else c for c in fk["ref_columns"]]
    elif stmt.action == "rename_table":
        from citus_tpu.transaction.locks import EXCLUSIVE
        t = cl.catalog.table(stmt.table)
        with cl._write_lock(t, EXCLUSIVE):
            cl.catalog.rename_table(stmt.table, stmt.new_name)
        # repoint children's FK edges at the new name
        for other in cl.catalog.tables.values():
            for fk in other.foreign_keys:
                if fk["ref_table"] == stmt.table:
                    fk["ref_table"] = stmt.new_name
    else:
        raise UnsupportedFeatureError(
            f"ALTER TABLE {stmt.action} not supported")
    cl.catalog.commit()
    # rename included: entries under the old name drop naturally — the
    # old name no longer resolves to this TableMeta object
    cl._plan_cache.invalidate_table(stmt.table)
    return Result(columns=[], rows=[])
